import sys
import numpy as np, jax, jax.numpy as jnp
from elasticsearch_tpu.ops import pallas_knn_binned as binned
from elasticsearch_tpu.ops.knn import Corpus

qmode, clip = sys.argv[1], sys.argv[2]
n, d, K = 2_000_000, 768, 10
chunk = 1_000_000
BLOCK = binned.BLOCK_N
n_pad = ((n + BLOCK - 1) // BLOCK) * BLOCK
ncenters, cnoise = 16384, 0.7

key = jax.random.PRNGKey(42)
kc, kq, k1, k2 = jax.random.split(key, 4)
centers = jax.random.normal(kc, (ncenters, d)) * 2.0

@jax.jit
def gen(k):
    ka, kb = jax.random.split(k)
    idx = jax.random.randint(ka, (chunk,), 0, ncenters)
    x = centers[idx] + cnoise * jax.random.normal(kb, (chunk, d))
    return x / jnp.linalg.norm(x, axis=-1, keepdims=True)

ka, kb = jax.random.split(kq)
x0 = gen(k1)
qi = jax.random.randint(ka, (256,), 0, chunk)
q = x0[qi] + float(clip) * jax.random.normal(kb, (256, d))
del x0
q = q / jnp.linalg.norm(q, axis=-1, keepdims=True)

mat = jnp.zeros((n_pad, d), jnp.int8)
scl = jnp.ones((n_pad,), jnp.float32)
best_s = jnp.full((256, K), -1e30); best_i = jnp.zeros((256, K), jnp.int32)

@jax.jit
def truth_update(x, base, bs, bi):
    s = jax.lax.dot_general(q, x, (((1,), (1,)), ((), ())), precision=jax.lax.Precision.HIGHEST)
    ids = base + jnp.arange(chunk, dtype=jnp.int32)[None, :]
    cs = jnp.concatenate([bs, s], axis=1); ci = jnp.concatenate([bi, jnp.broadcast_to(ids, s.shape)], axis=1)
    v, p = jax.lax.top_k(cs, K)
    return v, jnp.take_along_axis(ci, p, axis=1)

@jax.jit
def quantize(x):
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8), scale[:, 0]

for i, k in enumerate((k1, k2)):
    x = gen(k)
    best_s, best_i = truth_update(x, i * chunk, best_s, best_i)
    q8, sc = quantize(x)
    mat = jax.lax.dynamic_update_slice(mat, q8, (i * chunk, 0))
    scl = jax.lax.dynamic_update_slice(scl, sc, (i * chunk,))
    del x, q8, sc

ids_ref = np.asarray(best_i)
corpus = Corpus(matrix=mat, sq_norms=jnp.ones((n_pad,), jnp.float32), scales=scl, num_valid=jnp.int32(n))
s8, i8 = jax.jit(lambda qq, cc: binned.binned_knn_search(qq, cc, K))(q, corpus)
i8 = np.asarray(i8)
rec = sum(len(set(i8[r]) & set(ids_ref[r])) for r in range(256)) / (256 * K)
print(f"doc-anchored qnoise={clip}: recall={rec:.4f}")
