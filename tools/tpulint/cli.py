"""tpulint CLI: `python -m tools.tpulint [paths...] [--json]
[--baseline write]`.

Exit-code contract (tier-1 and CI key off it):

  0  no unsuppressed findings (pragma- and baseline-suppressed sites are
     reported in the summary / JSON but don't fail the run)
  1  at least one unsuppressed finding
  2  usage error (bad flag, missing path, unparseable source)

`--baseline write` rewrites `tools/tpulint/baseline.json` from the
current findings (reasons of surviving entries are preserved; new
entries get a TODO reason the lint-clean test rejects) and exits 0 —
baselining is an explicit, reviewed act, not a side effect of linting.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from tools.tpulint.engine import (
    BASELINE_DEFAULT,
    Config,
    lint_paths,
    write_baseline,
)


def _repo_root() -> str:
    """The directory holding `tools/` — baseline paths stay stable no
    matter where the CLI is invoked from."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.tpulint",
        description="AST-based JAX-discipline analyzer (rules TPU001-"
                    "TPU008; each encodes a historical serving bug)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint "
                        "(default: elasticsearch_tpu/)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a JSON report on stdout")
    p.add_argument("--baseline", metavar="write", default=None,
                   help="'write' regenerates the checked-in baseline "
                        "from current findings and exits 0")
    p.add_argument("--baseline-file", default=BASELINE_DEFAULT,
                   help="baseline path (default: tools/tpulint/"
                        "baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report everything)")
    p.add_argument("--select", default=None,
                   help="comma-separated rule ids to run (default: all)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.baseline not in (None, "write"):
        print(f"tpulint: unknown --baseline mode {args.baseline!r} "
              "(only 'write' is supported)", file=sys.stderr)
        return 2

    root = _repo_root()
    paths = args.paths or [os.path.join(root, "elasticsearch_tpu")]
    for p in paths:
        if not os.path.exists(p):
            print(f"tpulint: no such path: {p}", file=sys.stderr)
            return 2

    select = None
    if args.select:
        from tools.tpulint.rules import ALL_RULES
        known = {r.rule_id for r in ALL_RULES}
        select = tuple(s.strip() for s in args.select.split(","))
        unknown = [s for s in select if s not in known]
        if unknown:
            # a typo must not silently select zero rules and exit green
            print(f"tpulint: unknown rule id(s) {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
    config = Config(select=select)
    baseline_path = None if args.no_baseline else args.baseline_file
    try:
        unsuppressed, by_pragma, by_baseline = lint_paths(
            paths, config=config, baseline_path=baseline_path, root=root)
    except SystemExit as exc:
        print(str(exc), file=sys.stderr)
        return 2

    if args.baseline == "write":
        from tools.tpulint.engine import linted_rel_paths
        n = write_baseline(
            unsuppressed + [f for f, _ in by_baseline],
            args.baseline_file,
            # scope the rewrite to what this run actually looked at — a
            # partial run (path subset / --select) must not wipe other
            # files'/rules' entries and their written reasons
            linted_paths=linted_rel_paths(paths, root),
            selected_rules=select)
        print(f"tpulint: wrote {n} baseline entries to "
              f"{os.path.relpath(args.baseline_file, root)}")
        return 0

    if args.as_json:
        report = {
            "findings": [f.to_json() for f in unsuppressed],
            "suppressed": {
                "pragma": [dict(f.to_json(), reason=r)
                           for f, r in by_pragma],
                "baseline": [dict(f.to_json(), reason=r)
                             for f, r in by_baseline],
            },
            "counts": {"unsuppressed": len(unsuppressed),
                       "pragma": len(by_pragma),
                       "baseline": len(by_baseline)},
        }
        print(json.dumps(report, indent=2))
    else:
        for f in unsuppressed:
            print(f.render())
        print(f"tpulint: {len(unsuppressed)} finding(s), "
              f"{len(by_pragma)} pragma-suppressed, "
              f"{len(by_baseline)} baselined")
    return 1 if unsuppressed else 0
