import sys

from tools.tpulint.cli import main

if __name__ == "__main__":
    sys.exit(main())
