"""Light name-binding dataflow for tpulint rules.

This is NOT a general abstract interpreter — it is the minimum tracking
the historical bug classes need, resolved per function in statement
order:

* device taint — which locals hold device arrays (results of
  `dispatch.call`, `jax.device_put`, `jnp.*` constructors, calls of a
  local bound to a `shard_map(...)` program), so TPU002 only fires host
  syncs on arrays that actually live on the device, and TPU004 can see a
  donated buffer through later slicing;
* static rank — array ranks inferable from local construction
  (`jnp.zeros((a, b))` is rank 2 whatever a and b are), so TPU007 can
  check PartitionSpec ranks without running anything;
* tuple-literal bindings — `in_specs = (P(None), P("shard", None))`
  assigned one statement before the `shard_map(...)` call still counts
  as a literal spec.

Unknown stays unknown: every helper returns None/absent rather than
guessing, so rules built on top fire only on statically certain facts.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# Name helpers
# ---------------------------------------------------------------------------


def dotted(node: ast.AST) -> str:
    """'jax.experimental.pjit.pjit' for nested Attribute/Name chains,
    '' when the expression isn't a plain dotted name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(node: ast.Call) -> str:
    return dotted(node.func)


def base_name(node: ast.AST) -> Optional[str]:
    """The root Name of an expression chain: `corpus.matrix[0].T` -> the
    name 'corpus'; None when the chain doesn't root in a Name."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def iter_functions(tree: ast.AST):
    """Yield every (node) FunctionDef/AsyncFunctionDef in the module,
    including nested ones (each is analyzed with its own local scope)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def assign_targets(stmt: ast.stmt) -> List[str]:
    """Simple Name targets bound by this statement (tuple unpack
    included); attribute/subscript targets are ignored."""
    names: List[str] = []

    def collect(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            names.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect(e)
        elif isinstance(t, ast.Starred):
            collect(t.value)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            collect(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        collect(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        collect(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                collect(item.optional_vars)
    return names


# ---------------------------------------------------------------------------
# Device taint
# ---------------------------------------------------------------------------

_DISPATCH_HINTS = ("dispatch", "DISPATCH")
# jnp constructors / converters whose results live on device
_JNP_PREFIXES = ("jnp.", "jax.numpy.")


def numpy_aliases(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(module aliases, bare converter names) numpy is bound to in this
    module — `import numpy as _np` and `from numpy import asarray as aa`
    must count as host converters exactly like the conventional `np`
    (the serving batcher itself imports `numpy as _np`)."""
    mods = {"np", "numpy"}
    fns: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    mods.add(a.asname or "numpy")
        elif isinstance(node, ast.ImportFrom) and node.module == "numpy":
            for a in node.names:
                if a.name in ("asarray", "array"):
                    fns.add(a.asname or a.name)
    return mods, fns


def is_dispatch_call(node: ast.Call) -> bool:
    """`dispatch.call(...)`, `DISPATCH.call(...)`,
    `_dispatch.DISPATCH.call(...)` — the kernel execution entrypoints."""
    name = call_name(node)
    return (name.endswith(".call")
            and any(h in name for h in _DISPATCH_HINTS))


class DeviceTaint:
    """Statement-order device-array tracking for one function body."""

    def __init__(self, np_mods: Optional[Set[str]] = None,
                 np_fns: Optional[Set[str]] = None) -> None:
        self.device: Set[str] = set()
        self.shardmap_fns: Set[str] = set()
        mods = np_mods if np_mods is not None else {"np", "numpy"}
        # d2h converter spellings under this module's actual imports
        self.host_converters: Set[str] = {
            f"{m}.{fn}" for m in mods for fn in ("asarray", "array")}
        self.np_fn_converters: Set[str] = set(np_fns or ())

    # ------------------------------------------------------------ queries
    def expr_is_device(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.device
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            b = base_name(node)
            return b is not None and b in self.device
        if isinstance(node, ast.Call):
            return self.call_returns_device(node)
        if isinstance(node, ast.BinOp):
            return (self.expr_is_device(node.left)
                    or self.expr_is_device(node.right))
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr_is_device(e) for e in node.elts)
        return False

    def call_returns_device(self, node: ast.Call) -> bool:
        name = call_name(node)
        if is_dispatch_call(node):
            return True
        if name == "jax.device_put":
            return True
        if any(name.startswith(p) for p in _JNP_PREFIXES):
            return name not in ()  # every jnp.* result is a device array
        if isinstance(node.func, ast.Name):
            if node.func.id in self.shardmap_fns:
                return True
            if node.func.id in self.np_fn_converters:
                return False
        # method on a device value keeps the taint (.astype, .reshape,
        # .at[...].set, slicing chains) — EXCEPT the host converters
        if isinstance(node.func, ast.Attribute):
            if name in self.host_converters:
                return False
            b = base_name(node.func)
            if b is not None and b in self.device \
                    and node.func.attr not in ("item", "tolist"):
                return True
        return False

    # ------------------------------------------------------------ updates
    def observe(self, stmt: ast.stmt) -> None:
        """Update bindings from one statement (call BEFORE judging reads
        in the NEXT statement; same-statement reads use the pre-state)."""
        if isinstance(stmt, ast.Assign) and len(stmt.targets) >= 1:
            value = stmt.value
            is_dev = self.expr_is_device(value)
            is_sm = (isinstance(value, ast.Call)
                     and call_name(value).split(".")[-1] == "shard_map")
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    self.device.discard(t.id)
                    self.shardmap_fns.discard(t.id)
                    if is_sm:
                        self.shardmap_fns.add(t.id)
                    elif is_dev:
                        self.device.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)) and is_dev:
                    # unpacking a device-producing call taints every leaf
                    for name in assign_targets(stmt):
                        self.device.add(name)
                else:
                    for name in assign_targets(stmt):
                        self.device.discard(name)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(stmt.target, ast.Name):
                value = getattr(stmt, "value", None)
                if value is not None and self.expr_is_device(value):
                    self.device.add(stmt.target.id)
                elif isinstance(stmt, ast.AnnAssign):
                    self.device.discard(stmt.target.id)


# ---------------------------------------------------------------------------
# Static rank inference (TPU007)
# ---------------------------------------------------------------------------

_SHAPED_CTORS = ("zeros", "ones", "full", "empty")


def _tuple_len(node: ast.AST) -> Optional[int]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return len(node.elts)
    return None


def infer_rank(node: ast.AST, ranks: Dict[str, int]) -> Optional[int]:
    """Array rank of an expression when statically certain, else None."""
    if isinstance(node, ast.Name):
        return ranks.get(node.id)
    if isinstance(node, ast.Call):
        name = call_name(node)
        leaf = name.split(".")[-1]
        if any(name.startswith(p) for p in _JNP_PREFIXES):
            if leaf in _SHAPED_CTORS and node.args:
                n = _tuple_len(node.args[0])
                if n is not None:
                    return n
                if isinstance(node.args[0], (ast.Constant, ast.Name,
                                             ast.BinOp)):
                    return 1  # scalar shape arg: rank-1
            if leaf == "arange":
                return 1
            if leaf == "asarray" and node.args:
                depth = _literal_depth(node.args[0])
                if depth is not None:
                    return depth
                return infer_rank(node.args[0], ranks)
        if leaf == "reshape" and isinstance(node.func, ast.Attribute):
            if len(node.args) == 1:
                n = _tuple_len(node.args[0])
                return n if n is not None else None
            if node.args:
                return len(node.args)
    return None


def _literal_depth(node: ast.AST) -> Optional[int]:
    if isinstance(node, (ast.List, ast.Tuple)):
        if not node.elts:
            return 1
        inner = _literal_depth(node.elts[0])
        return None if inner is None else inner + 1
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)):
        return 0
    return None


# ---------------------------------------------------------------------------
# PartitionSpec extraction (TPU007)
# ---------------------------------------------------------------------------

_SPEC_NAMES = ("P", "PartitionSpec")


def spec_rank(node: ast.AST) -> Optional[int]:
    """Rank a literal `P(...)`/`PartitionSpec(...)` call describes —
    one axis entry per positional argument."""
    if isinstance(node, ast.Call) \
            and call_name(node).split(".")[-1] in _SPEC_NAMES:
        return len(node.args)
    return None


_MESH_HELPERS = frozenset({"make_mesh", "serving_mesh",
                           "mesh_for_shards"})
_REPO_MESH_AXES = frozenset({"dp", "shard"})


def mesh_axes_of(node: ast.AST,
                 mesh_bindings: Dict[str, frozenset]) -> Optional[frozenset]:
    """Statically-known axis names of a mesh expression: a Name bound to
    a known mesh earlier in the function, a literal
    `Mesh(grid, ("dp", "shard"))` construction (positional or
    `axis_names=`), or one of the repo's policy-owned builders (which
    always produce the ("dp", "shard") serving mesh). None = unknown."""
    if isinstance(node, ast.Name):
        return mesh_bindings.get(node.id)
    if not isinstance(node, ast.Call):
        return None
    leaf = call_name(node).split(".")[-1]
    if leaf in _MESH_HELPERS:
        return _REPO_MESH_AXES
    if leaf == "Mesh":
        names = node.args[1] if len(node.args) >= 2 else None
        for kw in node.keywords:
            if kw.arg == "axis_names":
                names = kw.value
        if isinstance(names, (ast.Tuple, ast.List)) and names.elts and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in names.elts):
            return frozenset(e.value for e in names.elts)
    return None


def spec_axis_names(node: ast.AST, tuple_bindings: Dict[str, ast.AST]
                    ) -> List[Tuple[str, ast.AST]]:
    """(axis name, spec node) pairs for every string axis named inside
    the P()/PartitionSpec() literals of an in_specs/out_specs
    expression (axis entries may be strings or tuples of strings)."""
    if isinstance(node, ast.Name) and node.id in tuple_bindings:
        node = tuple_bindings[node.id]
    out: List[Tuple[str, ast.AST]] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and call_name(sub).split(".")[-1] in _SPEC_NAMES:
            for arg in sub.args:
                for leaf in ast.walk(arg):
                    if isinstance(leaf, ast.Constant) \
                            and isinstance(leaf.value, str):
                        out.append((leaf.value, sub))
    return out


def spec_ranks(node: ast.AST,
               tuple_bindings: Dict[str, ast.AST]) -> Optional[
                   List[Optional[int]]]:
    """Per-argument spec ranks of an `in_specs=` expression. Accepts a
    literal tuple/list of P() calls, a single P() call, or a Name bound
    to such a tuple earlier in the same function; None per-position when
    that spec isn't a literal, None overall when nothing is literal."""
    if isinstance(node, ast.Name) and node.id in tuple_bindings:
        node = tuple_bindings[node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = [spec_rank(e) for e in node.elts]
        return out if any(r is not None for r in out) else None
    r = spec_rank(node)
    if r is not None:
        return [r]
    return None
