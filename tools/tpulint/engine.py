"""tpulint engine: file walker, pragma suppression, baseline machinery.

The engine is rule-agnostic: it parses each file once, hands every rule a
`ModuleContext` (tree + source + parent links + pragma table + hot-path
classification) and a `ProjectIndex` (cross-file facts such as which
dispatcher kernels donate which argument positions), then filters the
returned findings through pragmas and the checked-in baseline.

Suppression model (both are deliberate, reviewed artifacts):

* pragma — `# tpulint: disable=TPU00x(reason)` on the offending line, or
  on a standalone comment line directly above it. The reason is part of
  the syntax: a bare `disable=TPU00x` suppresses nothing, so a
  suppression can never be quieter than the finding it hides.
* baseline — `tools/tpulint/baseline.json` holds pre-existing justified
  sites keyed on (rule, path, scope, normalized source line); line
  numbers stay OUT of the key so unrelated edits don't churn the file.
  `--baseline write` regenerates entries, preserving written reasons.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

# modules whose device work sits on the serving hot path: host syncs here
# stall a batch that other requests coalesced into (TPU002's scope)
DEFAULT_HOT_PATH_GLOBS = (
    "*/ops/*.py",
    "*/parallel/*.py",
    "*/serving/*.py",
    "*/vectors/*.py",
    "*/search/*_plan.py",
)

# the one module allowed to build raw executables (TPU001): every other
# compile routes through its shape-bucketed AOT cache
DEFAULT_RAW_JIT_ALLOWED = ("*/ops/dispatch.py",)
# the one module allowed to import jax's raw shard_map: the version-
# portable wrapper every sharded kernel builds through
DEFAULT_RAW_SHARD_MAP_ALLOWED = ("*/parallel/sharded_knn.py",)
# the one module allowed to enter enable_x64 (TPU006): the dispatcher's
# scoped-x64 path (`register(..., x64=True)`)
DEFAULT_X64_ALLOWED = ("*/ops/dispatch.py",)
# the one package allowed to hold per-segment extraction caches
# (TPU011): the shared segment block store every consumer reads through
DEFAULT_SEG_CACHE_ALLOWED = ("*/columnar/*.py",)
# the one package allowed to hand-roll quantize/dequantize arithmetic
# (TPU013): the vector codec registry every encoding routes through
DEFAULT_QUANT_ALLOWED = ("*/quant/*.py",)
# the modules allowed to mutate sealed-generation durable state
# (TPU014): the engine that owns the commit point, the merge machinery,
# and the recovery assembler that rebuilds commits byte-identically
DEFAULT_DURABILITY_ALLOWED = (
    "*/index/engine.py",
    "*/segments/*.py",
    "*/recovery/*.py",
)
# the modules whose handlers run ON an asyncio event loop (TPU015): the
# TCP transport tier and the cluster nodes it serves — one blocking call
# there stalls every in-flight RPC and keepalive on that node's loop
DEFAULT_ASYNC_ACTOR_GLOBS = (
    "*/transport/*.py",
    "*/cluster/*.py",
)

BASELINE_DEFAULT = os.path.join(os.path.dirname(__file__), "baseline.json")

_PRAGMA_RE = re.compile(r"#\s*tpulint:\s*(?P<body>.+)$")
_DISABLE_ITEM_RE = re.compile(r"(TPU\d{3})\s*(?:\(([^()]*)\))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # posix-relative to the lint root
    line: int
    col: int
    message: str
    scope: str         # module-level: "<module>"; else Class.func qualname
    snippet: str       # stripped source line the finding anchors to

    def baseline_key(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.scope, self.snippet)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.scope}] {self.message}")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Config:
    hot_path_globs: Sequence[str] = DEFAULT_HOT_PATH_GLOBS
    raw_jit_allowed: Sequence[str] = DEFAULT_RAW_JIT_ALLOWED
    raw_shard_map_allowed: Sequence[str] = DEFAULT_RAW_SHARD_MAP_ALLOWED
    x64_allowed: Sequence[str] = DEFAULT_X64_ALLOWED
    seg_cache_allowed: Sequence[str] = DEFAULT_SEG_CACHE_ALLOWED
    quant_allowed: Sequence[str] = DEFAULT_QUANT_ALLOWED
    durability_allowed: Sequence[str] = DEFAULT_DURABILITY_ALLOWED
    async_actor_globs: Sequence[str] = DEFAULT_ASYNC_ACTOR_GLOBS
    select: Optional[Sequence[str]] = None   # rule ids; None = all


class ModuleContext:
    """One parsed file plus everything rules need to judge it."""

    def __init__(self, path: str, rel_path: str, source: str,
                 config: Config):
        self.path = path
        self.rel_path = rel_path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.config = config
        self.tree = ast.parse(source, filename=path)
        # parent links: rules climb from a node to its enclosing
        # subscript/call/with to judge context
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.pragmas = _parse_pragmas(self.lines)
        # a module opts into TPU002's hot-path scope with a pragma whose
        # whole body is exactly `hot-path` (`# tpulint: hot-path`) — a
        # substring match would let a disable-reason MENTIONING hot-path
        # flip the classification at a distance
        self.hot_path = (
            any(fnmatch.fnmatch("/" + self.rel_path, g)
                or fnmatch.fnmatch(self.rel_path, g)
                for g in config.hot_path_globs)
            or any(body.strip() == "hot-path"
                   for _, body in self.pragmas["raw"]))

    # ------------------------------------------------------------ helpers
    def matches(self, globs: Sequence[str]) -> bool:
        return any(fnmatch.fnmatch("/" + self.rel_path, g)
                   or fnmatch.fnmatch(self.rel_path, g) for g in globs)

    def scope_of(self, node: ast.AST) -> str:
        """Qualname of the innermost enclosing function/class."""
        parts: List[str] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"

    def snippet_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=rule, path=self.rel_path, line=line,
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message, scope=self.scope_of(node),
                       snippet=self.snippet_at(line))

    def suppressed(self, finding: Finding) -> Optional[str]:
        """Reason string when a pragma covers this finding, else None.
        (Standalone-comment pragmas were already re-targeted to the next
        line at parse time, so one lookup covers both placements.)"""
        return self.pragmas["by_line"].get((finding.line, finding.rule))


def _parse_pragmas(lines: List[str]) -> dict:
    """Pragma table: {(line, rule): reason}. A pragma on a standalone
    comment line covers the next source line; on a code line, that line.
    Reasons are MANDATORY — `disable=TPU001` with no `(reason)` parses to
    reason None and suppresses nothing."""
    by_line: Dict[Tuple[int, str], str] = {}
    raw: List[Tuple[int, str]] = []
    for i, text in enumerate(lines, start=1):
        m = _PRAGMA_RE.search(text)
        if not m:
            continue
        body = m.group("body").strip()
        raw.append((i, body))
        if "disable=" not in body:
            continue
        target = i + 1 if text.lstrip().startswith("#") else i
        for rule, reason in _DISABLE_ITEM_RE.findall(
                body.split("disable=", 1)[1]):
            if reason and reason.strip():
                by_line[(target, rule)] = reason.strip()
    return {"by_line": by_line, "raw": raw}


# ---------------------------------------------------------------------------
# Project-level pre-pass
# ---------------------------------------------------------------------------

class ProjectIndex:
    """Cross-file facts collected before rules run.

    donated_kernels: kernel name -> donated positional indices, read from
    every `*.register("name", fn, donate_argnums=(...))` call in the tree
    set — TPU004 maps them onto `dispatch.call("name", *args)` sites
    (arg position = donated argnum + 1; position 0 is the kernel name).
    """

    def __init__(self) -> None:
        self.donated_kernels: Dict[str, Tuple[int, ...]] = {}

    def scan(self, ctx: ModuleContext) -> None:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "register"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            donate: Tuple[int, ...] = ()
            for kw in node.keywords:
                if kw.arg == "donate_argnums" and isinstance(
                        kw.value, (ast.Tuple, ast.List)):
                    try:
                        donate = tuple(
                            int(e.value) for e in kw.value.elts
                            if isinstance(e, ast.Constant))
                    except (TypeError, ValueError):
                        donate = ()
            if donate:
                self.donated_kernels[node.args[0].value] = donate


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[Tuple[str, str, str, str],
                                     Tuple[str, int]]:
    """Baseline entries as {key: (reason, count)}; missing file = empty.
    `count` is how many identical findings the entry covers — an entry
    must not silently absorb NEW copy-pasted occurrences of the same
    line (entries without a count, from older files, cover one)."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[Tuple[str, str, str, str], Tuple[str, int]] = {}
    for e in data.get("entries", []):
        out[(e["rule"], e["path"], e["scope"], e["snippet"])] = \
            (e.get("reason", ""), max(int(e.get("count", 1)), 1))
    return out


def write_baseline(findings: Sequence[Finding], path: str,
                   linted_paths: Optional[Sequence[str]] = None,
                   selected_rules: Optional[Sequence[str]] = None) -> int:
    """Regenerate the baseline from current findings. Reasons of entries
    whose key still matches are preserved; new entries get a TODO reason
    the lint-clean test rejects until a human writes one.

    A partial run must not wipe what it didn't look at: old entries for
    files outside `linted_paths` or rules outside `selected_rules` are
    carried over untouched (reason and count included)."""
    old = load_baseline(path)
    counts: Dict[Tuple[str, str, str, str], int] = {}
    order: List[Tuple[str, str, str, str]] = []
    meta: Dict[Tuple[str, str, str, str], Finding] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        key = f.baseline_key()
        if key not in counts:
            order.append(key)
            meta[key] = f
        counts[key] = counts.get(key, 0) + 1
    lp = set(linted_paths) if linted_paths is not None else None
    sr = set(selected_rules) if selected_rules is not None else None
    for key, (reason, count) in old.items():
        rule, kpath = key[0], key[1]
        in_scope = ((lp is None or kpath in lp)
                    and (sr is None or rule in sr))
        if not in_scope and key not in counts:
            order.append(key)
            counts[key] = count
    entries = []
    for key in order:
        rule, kpath, scope, snippet = key
        old_reason = old.get(key, ("", 0))[0]
        entries.append({
            "rule": rule, "path": kpath, "scope": scope,
            "snippet": snippet, "count": counts[key],
            "reason": old_reason or "TODO: justify this baseline entry",
        })
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=2,
                  sort_keys=False)
        f.write("\n")
    return len(entries)


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------

def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if not p.endswith(".py"):
                # walking a regular file yields nothing — a typoed CI
                # argument must be a loud usage error, not a green no-op
                raise SystemExit(f"tpulint: not a python file: {p}")
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs if d != "__pycache__"
                       and not d.startswith(".")]
            out.extend(os.path.join(root, f) for f in sorted(files)
                       if f.endswith(".py"))
    return sorted(set(out))


def linted_rel_paths(paths: Sequence[str], root: str) -> List[str]:
    """Root-relative posix paths a lint over `paths` will cover — the
    scope `write_baseline` needs to avoid wiping entries a partial run
    never looked at."""
    out = []
    for fp in _iter_py_files(paths):
        rel = os.path.relpath(fp, root)
        out.append((fp if rel.startswith("..") else rel)
                   .replace(os.sep, "/"))
    return out


def lint_paths(paths: Sequence[str], config: Optional[Config] = None,
               baseline_path: Optional[str] = None,
               root: Optional[str] = None):
    """Lint every .py under `paths`.

    Returns (unsuppressed, pragma_suppressed, baselined) finding lists —
    pragma-suppressed and baselined findings ride along so the CLI's JSON
    report and the baseline writer can see the full picture.
    """
    from tools.tpulint.rules import ALL_RULES

    config = config or Config()
    root = root or os.getcwd()
    rules = [r for r in ALL_RULES
             if config.select is None or r.rule_id in config.select]

    contexts: List[ModuleContext] = []
    for fp in _iter_py_files(paths):
        rel = os.path.relpath(fp, root)
        if rel.startswith(".."):  # outside the root: key on the abs path
            rel = fp
        with open(fp, "r", encoding="utf-8") as f:
            source = f.read()
        try:
            contexts.append(ModuleContext(fp, rel, source, config))
        except SyntaxError as exc:
            raise SystemExit(f"tpulint: cannot parse {fp}: {exc}")

    index = ProjectIndex()
    for ctx in contexts:
        index.scan(ctx)

    baseline = load_baseline(baseline_path) if baseline_path else {}
    used: Dict[Tuple[str, str, str, str], int] = {}
    unsuppressed: List[Finding] = []
    by_pragma: List[Tuple[Finding, str]] = []
    by_baseline: List[Tuple[Finding, str]] = []
    for ctx in contexts:
        for rule in rules:
            for finding in rule.run(ctx, index):
                reason = ctx.suppressed(finding)
                if reason is not None:
                    by_pragma.append((finding, reason))
                    continue
                key = finding.baseline_key()
                entry = baseline.get(key)
                # an entry covers `count` occurrences — a NEW copy-paste
                # of an already-baselined line is a new finding
                if entry is not None and used.get(key, 0) < entry[1]:
                    used[key] = used.get(key, 0) + 1
                    by_baseline.append((finding, entry[0]))
                    continue
                unsuppressed.append(finding)
    unsuppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return unsuppressed, by_pragma, by_baseline
