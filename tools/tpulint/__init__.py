"""tpulint — AST-based JAX-discipline analyzer for elasticsearch_tpu.

The reference Elasticsearch enforces correctness at BUILD time: forbidden-
APIs checks, logger-usage checks, bootstrap checks. This engine's JAX
discipline (everything compiles through the shape-bucketed dispatcher,
host syncs stay out of hot loops, caches never key on recycled addresses)
was until now enforced only dynamically — the `ES_TPU_DISPATCH_STRICT=1`
closed-grid gate — and every serving PR shipped a review-round fix for a
*statically detectable* bug. tpulint turns those historical bug classes
into enforced rules (see `rules.py`; each rule's docstring cites the bug
it encodes) and runs over `elasticsearch_tpu/` as a tier-1 test
(`tests/test_tpulint.py::test_repo_is_lint_clean`) and a CLI:

    python -m tools.tpulint [paths...] [--json] [--baseline write]

Exit codes: 0 = no unsuppressed findings, 1 = findings, 2 = usage error.

Suppression: `# tpulint: disable=TPU00x(reason)` on the finding's line or
the standalone comment line directly above it — the reason is mandatory;
a bare `disable=TPU00x` suppresses nothing. Pre-existing justified sites
live in the checked-in baseline (`tools/tpulint/baseline.json`), keyed on
(rule, file, enclosing scope, normalized source line) so unrelated edits
don't churn it; every entry carries a written reason.
"""

from tools.tpulint.engine import (  # noqa: F401
    Config,
    Finding,
    lint_paths,
    load_baseline,
    write_baseline,
)
from tools.tpulint.rules import ALL_RULES  # noqa: F401
