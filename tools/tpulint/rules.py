"""tpulint rules: our historical JAX bug classes as AST checks.

Every rule docstring cites the concrete bug it encodes — these are not
style opinions, each one shipped (or nearly shipped) as a serving defect
and cost a review round to catch by hand. Rules return findings only on
statically certain facts (the dataflow helpers answer "unknown" freely),
so suppressions stay rare and meaningful.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.tpulint.dataflow import (
    DeviceTaint,
    assign_targets,
    base_name,
    call_name,
    dotted,
    infer_rank,
    is_dispatch_call,
    iter_functions,
    mesh_axes_of,
    numpy_aliases,
    spec_axis_names,
    spec_ranks,
)
from tools.tpulint.engine import Finding, ModuleContext, ProjectIndex


def _body_statements(body, *, in_loop: bool = False):
    """Yield (stmt, in_loop) linearly through nested blocks, NOT entering
    nested function/class definitions (they get their own analysis)."""
    for stmt in body:
        yield stmt, in_loop
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            yield from _body_statements(stmt.body, in_loop=True)
            yield from _body_statements(stmt.orelse, in_loop=in_loop)
        elif isinstance(stmt, ast.If):
            yield from _body_statements(stmt.body, in_loop=in_loop)
            yield from _body_statements(stmt.orelse, in_loop=in_loop)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            yield from _body_statements(stmt.body, in_loop=in_loop)
        elif isinstance(stmt, ast.Try):
            yield from _body_statements(stmt.body, in_loop=in_loop)
            for h in stmt.handlers:
                yield from _body_statements(h.body, in_loop=in_loop)
            yield from _body_statements(stmt.orelse, in_loop=in_loop)
            yield from _body_statements(stmt.finalbody, in_loop=in_loop)


def _stmt_expressions(stmt: ast.stmt):
    """Walk one statement's OWN expression trees (nested defs excluded,
    nested compound-statement bodies excluded — _body_statements already
    visits those as separate statements)."""
    blocks = ("body", "orelse", "finalbody", "handlers")
    todo: List[ast.AST] = []
    for field, value in ast.iter_fields(stmt):
        if field in blocks:
            continue
        if isinstance(value, ast.AST):
            todo.append(value)
        elif isinstance(value, list):
            todo.extend(v for v in value if isinstance(v, ast.AST))
    while todo:
        node = todo.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        todo.extend(ast.iter_child_nodes(node))


class Rule:
    rule_id = "TPU000"
    summary = ""

    def run(self, ctx: ModuleContext,
            index: ProjectIndex) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


# ---------------------------------------------------------------------------
# TPU001 — raw compilation outside the dispatcher
# ---------------------------------------------------------------------------

class RawJitRule(Rule):
    """TPU001: no raw `jax.jit` / `pjit` / raw-JAX `shard_map` outside
    `ops/dispatch.py` registrations.

    Historical bug (BENCH_MATRIX_r06 → PR 4): every distinct (batch, k,
    corpus) shape hit `jax.jit`'s tracing path in the serving hot loop —
    batch=4 ran at 149 ms p50 vs batch=16 at 31.6 ms, all of it XLA
    recompilation. The fix was the shape-bucketed dispatcher: ONE module
    owns `jax.jit(...).lower(...).compile()`, a closed bucket grid, and
    strict-mode enforcement. A raw `jax.jit` anywhere else is a second,
    unbucketed compile path the strict gate cannot see. Raw-JAX
    `shard_map` imports are confined to the version-portable wrapper in
    `parallel/sharded_knn.py` for the same reason (plus the 0.4.37 import
    split the seed tripped over); building programs THROUGH that wrapper
    and registering them is the sanctioned pattern.
    """

    rule_id = "TPU001"
    summary = "raw jit/pjit/shard_map compilation outside the dispatcher"

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        findings: List[Finding] = []
        jit_ok = ctx.matches(ctx.config.raw_jit_allowed)
        sm_ok = ctx.matches(ctx.config.raw_shard_map_allowed)
        # `import jax as j` must not evade the rule (same alias blindness
        # TPU002 had for numpy): every name the jax module is bound to
        jax_mods = {"jax"}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax":
                        jax_mods.add(a.asname or "jax")
        jit_names = {f"{m}.jit" for m in jax_mods}
        sm_names = {f"{m}.shard_map" for m in jax_mods} | {
            f"{m}.experimental.shard_map.shard_map" for m in jax_mods}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                name = dotted(node)
                if not jit_ok and name in jit_names:
                    findings.append(ctx.finding(
                        self.rule_id, node,
                        "raw jax.jit compiles outside the shape-bucketed "
                        "dispatcher (register the kernel in ops/dispatch "
                        "and route through dispatch.call)"))
                elif not jit_ok and name.endswith(".pjit"):
                    findings.append(ctx.finding(
                        self.rule_id, node,
                        "raw pjit compiles outside the dispatcher"))
                elif not sm_ok and name in sm_names:
                    findings.append(ctx.finding(
                        self.rule_id, node,
                        "raw JAX shard_map reference — use the "
                        "parallel/sharded_knn wrapper"))
            elif isinstance(node, ast.Name) and node.id == "pjit" \
                    and isinstance(node.ctx, ast.Load) and not jit_ok:
                findings.append(ctx.finding(
                    self.rule_id, node,
                    "raw pjit compiles outside the dispatcher"))
            elif isinstance(node, ast.ImportFrom) and node.module:
                if not jit_ok and node.module.endswith("pjit"):
                    findings.append(ctx.finding(
                        self.rule_id, node,
                        "raw pjit import outside the dispatcher"))
                elif not jit_ok and node.module == "jax" \
                        and any(a.name in ("jit", "pjit")
                                for a in node.names):
                    # `from jax import jit` (any alias) is the most
                    # common idiom for the same unbucketed compile path
                    findings.append(ctx.finding(
                        self.rule_id, node,
                        "raw jit import outside the dispatcher — "
                        "register the kernel in ops/dispatch and route "
                        "through dispatch.call"))
                elif not sm_ok and node.module in (
                        "jax", "jax.experimental.shard_map",
                        "jax.experimental") \
                        and any(a.name == "shard_map"
                                for a in node.names):
                    findings.append(ctx.finding(
                        self.rule_id, node,
                        "raw JAX shard_map import — build sharded "
                        "programs through the version-portable wrapper "
                        "(parallel/sharded_knn.shard_map) and register "
                        "them with the dispatcher"))
        return findings


# ---------------------------------------------------------------------------
# TPU002 — host syncs on device arrays in hot paths
# ---------------------------------------------------------------------------

_SCALAR_PULLS = ("item", "tolist")


class HostSyncRule(Rule):
    """TPU002: host-sync calls on device arrays inside hot-path modules.

    Historical bug (PR 6): the host agg walkers resolved doc values
    through a per-row `get_doc_value` loop — thousands of tiny host
    round-trips where one columnar gather was value-identical and orders
    of magnitude faster. On the serving path a host sync is worse: it
    stalls a batch that OTHER requests coalesced into.

    The rule is structural about what "response assembly" means: one bulk
    device→host transfer (`np.asarray` on a whole board) or one
    `block_until_ready` at result time, OUTSIDE any loop, is the
    sanctioned pattern — exactly how `vectors/store.py` lands mesh
    results. What fires is (a) any sync inside a for/while loop — the
    per-row round-trip shape — and (b) scalar pulls (`.item()`,
    `.tolist()`, `float()`, `int()`) on device arrays anywhere in a hot
    module: a scalar pull per element is the loop, just written inline.
    """

    rule_id = "TPU002"
    summary = "host sync on a device array in a hot-path module"

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        if not ctx.hot_path:
            return []
        findings: List[Finding] = []
        np_mods, np_fns = numpy_aliases(ctx.tree)
        for fn in iter_functions(ctx.tree):
            taint = DeviceTaint(np_mods, np_fns)
            for stmt, in_loop in _body_statements(fn.body):
                for node in _stmt_expressions(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    f = self._judge(node, taint, in_loop)
                    if f is not None:
                        findings.append(ctx.finding(self.rule_id, node, f))
                taint.observe(stmt)
        return findings

    @staticmethod
    def _judge(node: ast.Call, taint: DeviceTaint,
               in_loop: bool) -> Optional[str]:
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _SCALAR_PULLS \
                    and taint.expr_is_device(node.func.value):
                return (f".{attr}() pulls a device array to host "
                        "element-by-element — keep reductions on device "
                        "and land results with one bulk np.asarray at "
                        "response-assembly time")
            if attr == "block_until_ready" and in_loop \
                    and taint.expr_is_device(node.func.value):
                return ("block_until_ready inside a loop serializes "
                        "device dispatches — sync once, outside the "
                        "loop, at response-assembly time")
            if call_name(node) in taint.host_converters \
                    and in_loop and node.args \
                    and taint.expr_is_device(node.args[0]):
                return ("device→host transfer inside a loop — batch the "
                        "work and land it with one bulk np.asarray "
                        "outside the loop")
        elif isinstance(node.func, ast.Name):
            if node.func.id in ("float", "int") \
                    and len(node.args) == 1 \
                    and taint.expr_is_device(node.args[0]):
                return (f"{node.func.id}() on a device array is a "
                        "blocking scalar pull — convert whole result "
                        "boards with np.asarray at response-assembly "
                        "time")
            if node.func.id in taint.np_fn_converters and in_loop \
                    and node.args \
                    and taint.expr_is_device(node.args[0]):
                return ("device→host transfer inside a loop — batch the "
                        "work and land it with one bulk np.asarray "
                        "outside the loop")
        return None


# ---------------------------------------------------------------------------
# TPU003 — id()-keyed caches
# ---------------------------------------------------------------------------

_KEYISH = re.compile(r"key|sig", re.IGNORECASE)


class IdKeyedCacheRule(Rule):
    """TPU003: caches keyed on `id(...)` of long-lived objects.

    Historical bug (PR 5 review round): the lexical mesh-CSR cache keyed
    on `id(mesh)`. CPython recycles addresses — after the mesh was GC'd
    and a new Mesh allocated at the same address, the cache handed back
    arrays laid out for a DEAD mesh. The fix holds the mesh OBJECT
    (identity compare keeps the referent alive). `id()` in a cache key is
    only sound if the key also pins the object, which `id()` by
    construction does not; fire on every id() that flows into a
    subscript key, a cache `.get/.setdefault/.pop`, or a key/sig-named
    binding, and let the one deliberate site carry its pragma.
    """

    rule_id = "TPU003"
    summary = "cache keyed on id() of a long-lived object"

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "id" and len(node.args) == 1):
                continue
            why = self._key_context(ctx, node)
            if why:
                findings.append(ctx.finding(
                    self.rule_id, node,
                    f"id() used as a cache-key component ({why}) — "
                    "addresses recycle after GC; key on the object "
                    "itself (holding it alive) or a stable fingerprint"))
        return findings

    @staticmethod
    def _key_context(ctx: ModuleContext, node: ast.AST) -> Optional[str]:
        child = node
        cur = ctx.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.Subscript) and cur.slice is child:
                return "subscript key"
            if isinstance(cur, ast.Call) \
                    and isinstance(cur.func, ast.Attribute) \
                    and cur.func.attr in ("get", "setdefault", "pop") \
                    and child in cur.args \
                    and "cache" in dotted(cur.func.value).lower():
                return f"cache .{cur.func.attr}()"
            if isinstance(cur, ast.Assign) and cur.value is child:
                for t in cur.targets:
                    tname = base_name(t) or ""
                    if _KEYISH.search(tname):
                        return f"assigned to {tname!r}"
            if isinstance(cur, ast.Return):
                fn = ctx.parents.get(cur)
                while fn is not None and not isinstance(
                        fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = ctx.parents.get(fn)
                if fn is not None and _KEYISH.search(fn.name):
                    return f"returned from {fn.name}()"
            child = cur
            cur = ctx.parents.get(cur)
        return None


# ---------------------------------------------------------------------------
# TPU004 — read-after-donate
# ---------------------------------------------------------------------------

class ReadAfterDonateRule(Rule):
    """TPU004: re-reading an argument after passing it to a kernel
    registered with `donate_argnums`.

    Historical bug (PR 5 review round): `mesh.append` donated the old
    shard buffers while a search dispatched against the previously-
    installed FieldCorpus was still reading them — donated-then-deleted
    arrays and torn slot_map bookkeeping, visible only under concurrent
    refresh+search. XLA reuses a donated buffer's HBM for the outputs;
    ANY later read of that Python name is a read of freed memory. The
    donated positions come from the project-wide registration index
    (`register("bm25.topk", ..., donate_argnums=(0, 1))` →
    `dispatch.call("bm25.topk", board, count, ...)` consumes board and
    count).
    """

    rule_id = "TPU004"
    summary = "argument read again after donation to a kernel"

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        if not index.donated_kernels:
            return []
        findings: List[Finding] = []
        for fn in iter_functions(ctx.tree):
            consumed: Dict[str, Tuple[str, int]] = {}
            for stmt, _ in _body_statements(fn.body):
                if consumed:
                    for node in _stmt_expressions(stmt):
                        if isinstance(node, ast.Name) \
                                and isinstance(node.ctx, ast.Load) \
                                and node.id in consumed \
                                and node.lineno > consumed[node.id][1]:
                            kernel, line = consumed[node.id]
                            findings.append(ctx.finding(
                                self.rule_id, node,
                                f"{node.id!r} was donated to kernel "
                                f"[{kernel}] on line {line} "
                                f"(donate_argnums) — its buffer is "
                                "freed/reused by XLA; reading it is "
                                "use-after-free on HBM"))
                            del consumed[node.id]
                new_consumed: List[Tuple[str, str, int]] = []
                for node in _stmt_expressions(stmt):
                    if not (isinstance(node, ast.Call)
                            and is_dispatch_call(node) and node.args):
                        continue
                    head = node.args[0]
                    if not (isinstance(head, ast.Constant)
                            and isinstance(head.value, str)):
                        continue
                    donated = index.donated_kernels.get(head.value)
                    if not donated:
                        continue
                    for argnum in donated:
                        pos = argnum + 1  # args[0] is the kernel name
                        if pos < len(node.args) and isinstance(
                                node.args[pos], ast.Name):
                            new_consumed.append(
                                (node.args[pos].id, head.value,
                                 node.lineno))
                for name, kernel, line in new_consumed:
                    consumed[name] = (kernel, line)
                # rebinds clear consumption LAST: `x = call("k", x)` binds
                # x to the fresh result, not the donated buffer
                for name in assign_targets(stmt):
                    consumed.pop(name, None)
        return findings


# ---------------------------------------------------------------------------
# TPU005 — unscrubbed request payloads in cache keys
# ---------------------------------------------------------------------------

_REQUEST_NAMES = frozenset(
    {"body", "bodies", "request", "requests", "req", "payload",
     "aggs_spec", "query"})
_SANCTIONED_WRAPPER = re.compile(r"key|normali[sz]e|scrub|fingerprint",
                                 re.IGNORECASE)
# reader-identity evidence inside a request-cache key expression: a
# fingerprint/epoch-named value, a reader generation, or a call to the
# sanctioned `search/caches.request_cache_key` helper (which REQUIRES
# the fingerprint argument)
_READER_IDENTITY = re.compile(r"fingerprint|reader_gen|epoch"
                              r"|request_cache_key", re.IGNORECASE)


class UnscrubbedCacheKeyRule(Rule):
    """TPU005: cache keys built from raw request-payload values without a
    `plan_cache_key`-style normalizer.

    Historical bug (PR 4): the hybrid plan cache hashed the WHOLE request
    body — including the query vector and match text — so 108 identical-
    shape dashboard bodies produced `plan_cache_hits: 0` and the plan
    compiler ran per request. The fix (`hybrid_plan.plan_cache_key`)
    scrubs per-query values down to shapes/placeholders before hashing;
    the agg plan cache (PR 6) reuses the same trick. Any cache access
    whose key expression touches a request-payload name (`body`,
    `request`, `aggs_spec`, ...) without passing it through a
    key/normalize/scrub/fingerprint-named function rebuilds that bug.

    Second check (PR 16): REQUEST caches on the device read paths must
    key on reader identity. A request-cache access whose key is built
    INLINE (a tuple or call right in the get/put) with no reader
    fingerprint / reader gen / epoch in it — and no call to the
    sanctioned `search/caches.request_cache_key` helper, which requires
    the fingerprint argument — caches query-phase results across
    refreshes: stale hits after every ingest/delete/merge. Keys bound
    to a variable first are out of scope (provenance unknowable
    intra-module); the inline form is the one that reads plausibly
    correct in review and isn't.
    """

    rule_id = "TPU005"
    summary = "cache key built from a raw request payload"

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            key_expr = None
            where = None
            target = ""
            if isinstance(node, ast.Subscript) \
                    and "cache" in (dotted(node.value) or "").lower():
                key_expr, where = node.slice, "subscript"
                target = (dotted(node.value) or "").lower()
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("get", "put", "setdefault") \
                    and node.args \
                    and "cache" in dotted(node.func.value).lower():
                key_expr, where = node.args[0], f".{node.func.attr}()"
                target = dotted(node.func.value).lower()
            if key_expr is None:
                continue
            name = self._raw_payload_name(ctx, key_expr)
            if name:
                findings.append(ctx.finding(
                    self.rule_id, key_expr,
                    f"cache {where} keys on raw request payload "
                    f"{name!r} — per-query values (vectors, match text) "
                    "in the key defeat the cache and leak payload data "
                    "into key storage; scrub through a plan_cache_key-"
                    "style normalizer first"))
            elif "request" in target \
                    and isinstance(key_expr, (ast.Tuple, ast.Call)) \
                    and not self._has_reader_identity(key_expr):
                findings.append(ctx.finding(
                    self.rule_id, key_expr,
                    f"request cache {where} keyed without a reader "
                    "fingerprint — a key that ignores reader identity "
                    "serves stale query-phase results across refresh/"
                    "delete/merge; build the key with search/caches."
                    "request_cache_key (fingerprint required) or "
                    "include the reader fingerprint/gen explicitly"))
        return findings

    @staticmethod
    def _has_reader_identity(key_expr: ast.AST) -> bool:
        for node in ast.walk(key_expr):
            if isinstance(node, ast.Name) \
                    and _READER_IDENTITY.search(node.id):
                return True
            if isinstance(node, ast.Attribute) \
                    and (node.attr == "gen"
                         or _READER_IDENTITY.search(node.attr)):
                return True
            if isinstance(node, ast.keyword) and node.arg \
                    and _READER_IDENTITY.search(node.arg):
                return True
            if isinstance(node, ast.Call) \
                    and _READER_IDENTITY.search(call_name(node)):
                return True
        return False

    @staticmethod
    def _raw_payload_name(ctx: ModuleContext,
                          key_expr: ast.AST) -> Optional[str]:
        for node in ast.walk(key_expr):
            if not (isinstance(node, ast.Name)
                    and node.id in _REQUEST_NAMES):
                continue
            cur = ctx.parents.get(node)
            sanctioned = False
            while cur is not None and cur is not key_expr:
                if isinstance(cur, ast.Call) and _SANCTIONED_WRAPPER.search(
                        call_name(cur).split(".")[-1]):
                    sanctioned = True
                    break
                cur = ctx.parents.get(cur)
            if not sanctioned:
                return node.id
        return None


# ---------------------------------------------------------------------------
# TPU006 — enable_x64 outside the dispatcher
# ---------------------------------------------------------------------------

class ScopedX64Rule(Rule):
    """TPU006: `enable_x64` entered outside the dispatcher's scoped-x64
    path.

    Historical context (PR 6): the agg kernels need int64 counts and f64
    sums (date millis don't fit int32/f32), but the process default must
    stay 32-bit — the serving kernels are f32 by design, and a global
    x64 flip silently doubles every buffer and retraces every cached
    executable. The dispatcher's `register(..., x64=True)` scopes the
    flag around BOTH lower() and execution (`_x64_scope`), which is the
    only sound placement: tracing canonicalization and the AOT arg-aval
    check both read the active config. An `enable_x64` (or
    `jax.config.update("jax_enable_x64", ...)`) anywhere else either
    leaks process-wide or desyncs trace-time from call-time dtypes.
    """

    rule_id = "TPU006"
    summary = "enable_x64 outside the dispatcher's scoped path"

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        if ctx.matches(ctx.config.x64_allowed):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) \
                    and any(a.name == "enable_x64" for a in node.names):
                findings.append(ctx.finding(
                    self.rule_id, node,
                    "enable_x64 import outside ops/dispatch.py — x64 "
                    "kernels must register with dispatch.register(..., "
                    "x64=True) so the flag scopes trace AND execution"))
            elif isinstance(node, ast.Attribute) \
                    and dotted(node).endswith("enable_x64"):
                findings.append(ctx.finding(
                    self.rule_id, node,
                    "enable_x64 reference outside the dispatcher's "
                    "scoped-x64 path"))
            elif isinstance(node, ast.Call) \
                    and call_name(node).endswith("config.update") \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value == "jax_enable_x64":
                findings.append(ctx.finding(
                    self.rule_id, node,
                    "global jax_enable_x64 flip — doubles every buffer "
                    "and invalidates the AOT executable cache; use "
                    "dispatch.register(..., x64=True)"))
        return findings


# ---------------------------------------------------------------------------
# TPU007 — PartitionSpec rank mismatches
# ---------------------------------------------------------------------------

class SpecRankRule(Rule):
    """TPU007: statically inferable PartitionSpec-rank vs array-rank
    mismatches at `shard_map` call sites.

    Historical bug (PR 5 review round): the sharded BM25 kernel's int8
    tile-scales spec was `P(None, None)` — rank 2 — for a rank-1 scales
    array, so EVERY mesh-routed BM25 dispatch on an `impact_dtype: int8`
    index raised inside shard_map. The mismatch was fully visible in the
    source: the spec literal and the array construction were lines
    apart. This rule checks exactly that: where both the spec tuple and
    the argument's rank are statically certain, they must agree — and
    the positional arity of the call must match the spec tuple.

    The dp-axis extension (PR 11): where the MESH being mapped over has
    statically-known axis names (a literal `Mesh(grid, ("dp", "shard"))`
    or one of the policy-owned builders), every string axis named in
    in_specs/out_specs must be one of them — the dp-axis TYPO class
    (`P("pd", None)`, or an axis left over from a renamed mesh), which
    shard_map only rejects at dispatch time.
    """

    rule_id = "TPU007"
    summary = "PartitionSpec rank does not match array rank in shard_map"

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        findings: List[Finding] = []
        for fn in iter_functions(ctx.tree):
            ranks: Dict[str, int] = {}
            tuples: Dict[str, ast.AST] = {}
            sharded: Dict[str, List[Optional[int]]] = {}
            meshes: Dict[str, frozenset] = {}
            for stmt, _ in _body_statements(fn.body):
                # judge calls of previously-bound shard_map programs
                for node in _stmt_expressions(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    if self._is_shard_map(node):
                        findings.extend(self._axis_findings(
                            ctx, node, meshes, tuples))
                    specs = None
                    label = None
                    if isinstance(node.func, ast.Name) \
                            and node.func.id in sharded:
                        specs, label = sharded[node.func.id], node.func.id
                    elif isinstance(node.func, ast.Call) \
                            and self._is_shard_map(node.func):
                        specs = self._specs_of(node.func, tuples)
                        label = "shard_map(...)"
                    if specs is None:
                        continue
                    if not any(isinstance(a, ast.Starred)
                               for a in node.args) \
                            and len(node.args) != len(specs):
                        findings.append(ctx.finding(
                            self.rule_id, node,
                            f"{label} declares {len(specs)} in_specs but "
                            f"is called with {len(node.args)} arguments"))
                        continue
                    for i, (arg, srank) in enumerate(
                            zip(node.args, specs)):
                        if srank is None:
                            continue
                        arank = infer_rank(arg, ranks)
                        if arank is not None and arank != srank:
                            findings.append(ctx.finding(
                                self.rule_id, arg,
                                f"in_specs[{i}] of {label} is rank "
                                f"{srank} but the argument is rank "
                                f"{arank} — shard_map raises on rank "
                                "mismatch at dispatch time (the PR 5 "
                                "int8 tile-scales bug)"))
                # then update bindings
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    tname = stmt.targets[0].id
                    ranks.pop(tname, None)
                    tuples.pop(tname, None)
                    sharded.pop(tname, None)
                    meshes.pop(tname, None)
                    value = stmt.value
                    if isinstance(value, (ast.Tuple, ast.List)):
                        tuples[tname] = value
                    elif isinstance(value, ast.Call) \
                            and self._is_shard_map(value):
                        specs = self._specs_of(value, tuples)
                        if specs is not None:
                            sharded[tname] = specs
                    else:
                        axes = mesh_axes_of(value, meshes)
                        if axes is not None:
                            meshes[tname] = axes
                        r = infer_rank(value, ranks)
                        if r is not None:
                            ranks[tname] = r
        return findings

    def _axis_findings(self, ctx: ModuleContext, node: ast.Call,
                       meshes: Dict[str, frozenset],
                       tuples: Dict[str, ast.AST]) -> List[Finding]:
        """dp-axis typo check at one shard_map construction: every
        string axis named in in_specs/out_specs must be an axis of the
        (statically known) mesh being mapped over."""
        mesh_kw = next((kw.value for kw in node.keywords
                        if kw.arg == "mesh"), None)
        axes = (mesh_axes_of(mesh_kw, meshes)
                if mesh_kw is not None else None)
        if not axes:
            return []
        out: List[Finding] = []
        for kw in node.keywords:
            if kw.arg not in ("in_specs", "out_specs"):
                continue
            for name, spec_node in spec_axis_names(kw.value, tuples):
                if name not in axes:
                    out.append(ctx.finding(
                        self.rule_id, spec_node,
                        f"PartitionSpec names axis '{name}' absent from "
                        f"the mesh being mapped over (axes "
                        f"{sorted(axes)}) — shard_map raises at "
                        "dispatch time (the dp-axis typo class)"))
        return out

    @staticmethod
    def _is_shard_map(node: ast.Call) -> bool:
        return call_name(node).split(".")[-1] == "shard_map"

    @staticmethod
    def _specs_of(node: ast.Call, tuples: Dict[str, ast.AST]):
        for kw in node.keywords:
            if kw.arg == "in_specs":
                return spec_ranks(kw.value, tuples)
        return None


# ---------------------------------------------------------------------------
# TPU008 — unlocked module-level cache mutation
# ---------------------------------------------------------------------------

_MUTATORS = frozenset({"append", "add", "setdefault", "pop", "popitem",
                       "clear", "update", "remove", "discard", "extend",
                       "insert"})
_CONTAINER_CTORS = frozenset({"dict", "list", "set", "defaultdict",
                              "OrderedDict", "Counter", "deque"})


class ModuleCacheLockRule(Rule):
    """TPU008: module-level mutable caches mutated without the module's
    declared lock.

    Historical context: every process-wide cache in this engine is
    mutated from multiple threads by construction — the serving batcher
    coalesces requests from N REST threads, warmup runs on a background
    thread, refresh listeners run on the flush path. The dispatcher's
    executable cache and `parallel/policy.py`'s config/counters each
    pair their module/instance state with one lock and take it on every
    mutation; PR 5's review round still found the double-build race in
    `serving_mesh()` (two first callers caching distinct equal Meshes,
    forcing identity-keyed caches through a redundant corpus re-upload).
    This rule makes the convention checkable at the module level: a
    module-level mutable container mutated inside any function must hold
    a module-level lock while doing it — and a module with such caches
    and NO lock declared is itself a finding.
    """

    rule_id = "TPU008"
    summary = "module-level cache mutated outside the module's lock"

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        locks: Set[str] = set()
        containers: Set[str] = set()
        for stmt in ctx.tree.body:
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            value = stmt.value
            if value is None:
                continue
            for name in assign_targets(stmt):
                if isinstance(value, ast.Call):
                    cname = call_name(value)
                    if cname.split(".")[-1] in ("Lock", "RLock"):
                        locks.add(name)
                    elif cname.split(".")[-1] in _CONTAINER_CTORS:
                        containers.add(name)
                elif isinstance(value, (ast.Dict, ast.List, ast.Set,
                                        ast.DictComp, ast.ListComp,
                                        ast.SetComp)):
                    containers.add(name)
        if not containers:
            return []
        findings: List[Finding] = []
        for fn in iter_functions(ctx.tree):
            # THIS function's own `global` declarations (nested functions
            # are analyzed separately — _body_statements stops at them,
            # so a helper's `global` can't un-shadow our local)
            declared_global = {
                n for s, _ in _body_statements(fn.body)
                if isinstance(s, ast.Global) for n in s.names}
            local_names: set = set()
            for stmt, _ in _body_statements(fn.body):
                # a local shadowing the module name is not the cache —
                # unless declared global
                local_names |= set(assign_targets(stmt)) - declared_global
                for node in _stmt_expressions(stmt):
                    target = self._mutation_target(node, ctx)
                    if target is None or target not in containers \
                            or target in local_names:
                        continue
                    if self._under_lock(ctx, node, locks):
                        continue
                    if locks:
                        lock_list = ", ".join(sorted(locks))
                        msg = (f"module-level cache {target!r} mutated "
                               f"without holding the module's lock "
                               f"({lock_list}) — serving threads, warmup "
                               "and refresh listeners all reach "
                               "module state concurrently")
                    else:
                        msg = (f"module-level cache {target!r} is mutated "
                               "from functions but the module declares "
                               "no lock — add a module-level "
                               "threading.Lock and take it on every "
                               "mutation")
                    findings.append(ctx.finding(self.rule_id, node, msg))
        return findings

    @staticmethod
    def _mutation_target(node: ast.AST,
                         ctx: ModuleContext) -> Optional[str]:
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            return base_name(node)
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            return base_name(node.func.value)
        return None

    @staticmethod
    def _under_lock(ctx: ModuleContext, node: ast.AST,
                    locks: Set[str]) -> bool:
        if not locks:
            return False
        cur = ctx.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Name) and sub.id in locks:
                            return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            cur = ctx.parents.get(cur)
        return False


# ---------------------------------------------------------------------------
# TPU009 — blocking sync inside a lock-held critical section
# ---------------------------------------------------------------------------

_FUTURISH = re.compile(r"fut", re.IGNORECASE)


class LockedSyncRule(Rule):
    """TPU009: blocking syncs while holding a serving lock (the batcher
    lock / drain critical section).

    Historical context (PR 8): the continuous-batching rewrite's whole
    point is that the scheduler lock is held only for the UN-SYNCED
    device dispatch — device sync, `Future.result`, and d2h transfers
    happen at response-assembly time, outside the lock, so batch N's
    host work overlaps batch N+1's dispatch. A blocking sync inside a
    `with <lock>:` body silently re-serializes the pipeline: every
    request queued on that lock stalls behind one batch's device wait,
    which is exactly the closed-loop convoy the r06 p99/p50 = 6.2 gate
    failure measured. Fires on `block_until_ready()`, `.item()` on a
    device array, `.result()` on a future-named receiver, and bulk
    device→host transfers (`np.asarray` on a device array) lexically
    inside a with-block whose context manager is lock-named. Scoped to
    hot-path modules like TPU002.
    """

    rule_id = "TPU009"
    summary = "blocking sync while holding a serving lock"

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        if not ctx.hot_path:
            return []
        findings: List[Finding] = []
        np_mods, np_fns = numpy_aliases(ctx.tree)
        for fn in iter_functions(ctx.tree):
            taint = DeviceTaint(np_mods, np_fns)
            self._walk(fn.body, False, taint, ctx, findings)
        return findings

    def _walk(self, body, in_lock: bool, taint, ctx, findings) -> None:
        """Linear statement walk carrying the lock-held flag; taint
        observes statements in source order so device-array facts are
        current when a sync site is judged."""
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if in_lock:
                for node in _stmt_expressions(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    msg = self._judge(node, taint)
                    if msg is not None:
                        findings.append(
                            ctx.finding(self.rule_id, node, msg))
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._walk(stmt.body,
                           in_lock or self._locks_a_lock(stmt), taint,
                           ctx, findings)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self._walk(stmt.body, in_lock, taint, ctx, findings)
                self._walk(stmt.orelse, in_lock, taint, ctx, findings)
            elif isinstance(stmt, ast.If):
                self._walk(stmt.body, in_lock, taint, ctx, findings)
                self._walk(stmt.orelse, in_lock, taint, ctx, findings)
            elif isinstance(stmt, ast.Try):
                self._walk(stmt.body, in_lock, taint, ctx, findings)
                for h in stmt.handlers:
                    self._walk(h.body, in_lock, taint, ctx, findings)
                self._walk(stmt.orelse, in_lock, taint, ctx, findings)
                self._walk(stmt.finalbody, in_lock, taint, ctx, findings)
            taint.observe(stmt)

    @staticmethod
    def _locks_a_lock(stmt) -> bool:
        """`with self._run_lock:` / `with lock, other:` — any context
        manager whose dotted name's last component is lock-named. A
        Condition used as a context manager counts (it wraps its lock)."""
        for item in stmt.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                expr = expr.func
            name = dotted(expr) if isinstance(
                expr, (ast.Name, ast.Attribute)) else ""
            last = name.split(".")[-1].lower()
            if last.endswith("lock") or last.endswith("cond") \
                    or last.endswith("condition"):
                return True
        return False

    def _judge(self, node: ast.Call, taint) -> Optional[str]:
        if not isinstance(node.func, ast.Attribute):
            if isinstance(node.func, ast.Name) \
                    and node.func.id in taint.np_fn_converters \
                    and node.args \
                    and taint.expr_is_device(node.args[0]):
                return ("device→host transfer while holding a lock — "
                        "every request queued on this lock stalls behind "
                        "the sync; dispatch under the lock, land results "
                        "outside it at response-assembly time")
            return None
        attr = node.func.attr
        if attr == "block_until_ready":
            return ("block_until_ready while holding a lock serializes "
                    "the dispatch pipeline — sync outside the critical "
                    "section, at response-assembly time")
        if attr == "item" and taint.expr_is_device(node.func.value):
            return (".item() on a device array while holding a lock is a "
                    "blocking scalar pull inside the drain critical "
                    "section — land results outside the lock")
        if attr == "result" and _FUTURISH.search(dotted(node.func.value)):
            return ("Future.result() while holding a lock blocks the "
                    "scheduler — wait on futures outside the critical "
                    "section (the combining batcher's submit tail)")
        if call_name(node) in taint.host_converters and node.args \
                and taint.expr_is_device(node.args[0]):
            return ("device→host transfer while holding a lock — every "
                    "request queued on this lock stalls behind the sync; "
                    "dispatch under the lock, land results outside it at "
                    "response-assembly time")
        return None


class UnguardedFanoutRule(Rule):
    """TPU010: transport fan-outs that can hang on a silent drop.

    Historical context (PR 12): `cluster_node._query_phase` waited for
    `pending == 0` with NO timer while fanning QUERY-phase RPCs — one
    slow or dead data node hung the whole search accumulator forever
    (the deterministic transport drops messages silently, exactly like
    a real network partition; neither `on_response` nor `on_failure`
    ever fires). The same idiom had spread to the scroll, refresh, and
    replication fan-outs. The fix is serving/fanout.py's ScatterGather
    (per-item timers make completion structural); this rule keeps the
    idiom from growing back. Two patterns fire:

    * a `transport.send(...)` call site with no `on_failure` handler —
      a failed delivery is silently lost, so the caller's completion
      accounting can never see the error;
    * a function that fans out over `transport.send` and joins on a
      mutable pending-counter dict (`pending = {"count": len(...)}`
      ... `pending["count"] -= 1` ... `== 0`) without arming ANY
      scheduler timer (`schedule_in`/`schedule_at`) — the unbounded
      coordinator wait. Route the fan-out through
      `serving.fanout.ScatterGather` (or arm an explicit timeout).
    """

    rule_id = "TPU010"
    summary = "transport fan-out without failure handling or a timer"

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        findings: List[Finding] = []
        analyzed: Set[ast.AST] = set()
        for fn in iter_functions(ctx.tree):
            # analyze OUTERMOST functions whole (the pending-counter
            # idiom spans the nested response closures), skipping
            # functions already covered by an enclosing analysis
            cur = ctx.parents.get(fn)
            nested = False
            while cur is not None:
                if cur in analyzed:
                    nested = True
                    break
                cur = ctx.parents.get(cur)
            if nested:
                continue
            analyzed.add(fn)
            findings.extend(self._judge_function(fn, ctx))
        return findings

    @staticmethod
    def _is_transport_send(node: ast.Call) -> bool:
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "send"):
            return False
        return "transport" in dotted(node.func.value).lower()

    def _judge_function(self, fn, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        sends: List[ast.Call] = []
        counters: Dict[str, ast.stmt] = {}   # var -> defining Assign
        decremented: Set[str] = set()
        zero_tested: Set[str] = set()
        has_timer = False

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if self._is_transport_send(node):
                    sends.append(node)
                    kws = {kw.arg for kw in node.keywords}
                    # positional form carries on_failure as the 6th arg
                    if "on_failure" not in kws and len(node.args) < 6:
                        findings.append(ctx.finding(
                            self.rule_id, node,
                            "transport.send without an on_failure "
                            "handler: a failed delivery is silently "
                            "lost and the fan-out's completion "
                            "accounting can never see it"))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("schedule_in",
                                               "schedule_at"):
                    has_timer = True
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Dict) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                # `pending = {"count": len(targets)}` — a fan-out join
                # counter seeded from the target-set size
                if any(isinstance(c, ast.Call)
                       and isinstance(c.func, ast.Name)
                       and c.func.id == "len"
                       for v in node.value.values if v is not None
                       for c in ast.walk(v)):
                    counters[node.targets[0].id] = node
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.op, ast.Sub) \
                    and isinstance(node.target, ast.Subscript):
                name = base_name(node.target)
                if name:
                    decremented.add(name)
            elif isinstance(node, ast.Compare) \
                    and isinstance(node.left, ast.Subscript) \
                    and len(node.ops) == 1 \
                    and isinstance(node.ops[0], ast.Eq) \
                    and len(node.comparators) == 1 \
                    and isinstance(node.comparators[0], ast.Constant) \
                    and node.comparators[0].value == 0:
                name = base_name(node.left)
                if name:
                    zero_tested.add(name)

        if sends and not has_timer:
            for name, assign in counters.items():
                if name in decremented and name in zero_tested:
                    findings.append(ctx.finding(
                        self.rule_id, assign,
                        f"fan-out joins on pending counter [{name}] "
                        "with no scheduler timer: a silently dropped "
                        "response hangs the accumulator forever — "
                        "route through serving.fanout.ScatterGather "
                        "(per-item timers) or arm schedule_in as a "
                        "backstop"))
        return findings


# ---------------------------------------------------------------------------
# TPU011 — private per-segment extraction caches outside columnar/
# ---------------------------------------------------------------------------

_SEG_KEY_ATTRS = frozenset({"seg_id", "fingerprint"})
_SEG_KEY_NAMES = frozenset({"seg_id", "fingerprint", "fp"})
_DICT_READERS = frozenset({"get", "setdefault", "pop"})


class PrivateSegmentCacheRule(Rule):
    """TPU011: private per-segment extraction caches outside
    `elasticsearch_tpu/columnar/`.

    Historical context (PR 13): three subsystems each grew a private
    per-segment extraction cache — the vector store's per-refresh
    extract, `ops/aggs.py`'s `_seg_cache`, `ops/bm25.py`'s
    `_seg_cache` — with three sets of fingerprint semantics and three
    lifetimes. The duplication is why refresh paid an O(corpus) host
    memcpy per vector field and why every `Generation` pinned its own
    corpus-sized `host_vectors`. The columnar segment block store now
    owns per-(segment, field) extraction: blocks extract once, share
    across consumers, and evict with the segment. This rule keeps a
    fourth private cache from growing back: in hot-path modules outside
    `columnar/`, a PERSISTENT dict (an instance attribute on `self` or
    a module-level container) read or written with a key derived from
    `seg_id`/`fingerprint` — or whose very name says segment-cache — is
    a finding; read through `columnar.STORE` instead. Transient locals
    keyed by seg_id inside one pass are fine (they cache nothing across
    refreshes).
    """

    rule_id = "TPU011"
    summary = "private per-segment extraction cache outside columnar/"

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        if not ctx.hot_path or ctx.matches(ctx.config.seg_cache_allowed):
            return []
        module_containers: Set[str] = set()
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)) \
                    and stmt.value is not None \
                    and isinstance(stmt.value, (ast.Dict, ast.DictComp)):
                module_containers |= set(assign_targets(stmt))
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            recv = key = None
            if isinstance(node, ast.Subscript):
                recv, key = node.value, node.slice
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _DICT_READERS and node.args:
                recv, key = node.func.value, node.args[0]
            if recv is None or not self._persistent(recv,
                                                    module_containers):
                continue
            if self._cache_named(recv):
                findings.append(ctx.finding(
                    self.rule_id, node,
                    f"private per-segment cache [{dotted(recv)}] — "
                    "per-(segment, field) extraction belongs in the "
                    "shared segment block store (columnar.STORE): one "
                    "extraction, every consumer, evicted with the "
                    "segment"))
            elif self._seg_keyed(key):
                findings.append(ctx.finding(
                    self.rule_id, node,
                    f"persistent dict [{dotted(recv)}] keyed by "
                    "seg_id/fingerprint is a private per-segment "
                    "extraction cache — read through columnar.STORE "
                    "(one extraction, every consumer, evicted with "
                    "the segment)"))
        return findings

    @staticmethod
    def _persistent(recv: ast.AST, module_containers: Set[str]) -> bool:
        """Instance state (`self.X`, any depth) or a module-level
        container — the shapes that outlive one pass. Plain locals are
        transient and stay out of scope."""
        if isinstance(recv, ast.Attribute):
            base = base_name(recv)
            return base == "self"
        if isinstance(recv, ast.Name):
            return recv.id in module_containers
        return False

    @staticmethod
    def _cache_named(recv: ast.AST) -> bool:
        name = dotted(recv).split(".")[-1].lower()
        return "seg" in name and "cache" in name

    @staticmethod
    def _seg_keyed(key: Optional[ast.AST]) -> bool:
        if key is None:
            return False
        for sub in ast.walk(key):
            if isinstance(sub, ast.Attribute) \
                    and sub.attr in _SEG_KEY_ATTRS:
                return True
            if isinstance(sub, ast.Name) and sub.id in _SEG_KEY_NAMES:
                return True
        return False


# ---------------------------------------------------------------------------
# TPU012 — wall-clock durations in hot modules & leaked telemetry spans
# ---------------------------------------------------------------------------

class TelemetryDisciplineRule(Rule):
    """TPU012: two telemetry bug classes from ISSUE 14's always-on
    observability layer.

    (a) `time.time()` in a HOT-PATH module. Telemetry made duration
    measurement ubiquitous (every request records queue-wait / dispatch /
    sync / took), and a wall-clock duration is wrong twice: NTP steps it
    (negative or wildly long "latencies" polluting the log2 histograms
    that now feed `_nodes/stats telemetry` p99), and it costs a VDSO
    gettimeofday on every hot-path call for less guarantee than
    `time.monotonic()`/`perf_counter()` give. Epoch TIMESTAMPS for
    display belong outside hot modules (Task.start_ms lives in
    node_admin for exactly this reason).

    (b) a live telemetry span opened via `begin_span(...)`/
    `start_span(...)` and bound to a local variable with NO structural
    close in the enclosing function — no `end_span(x)`, no
    `x.end()`/`x.finish()`, not a `with` item. A leaked span stays open
    forever: the tasks API reports it as the request's `current_span`
    after the request finished, and the trace ring shows a span with
    `dur_ns: null` that sums into nothing. The fix is the `span()`
    context manager, `end_span` in a `finally:`, or — for durations
    measured at existing sync points — the retroactive
    `record_span(name, dur_ns)`, which is born closed and cannot leak.
    Spans stored onto objects (attributes, dict slots) are cross-thread
    handoffs the analysis cannot follow and stay out of scope, like
    TPU004's aliasing rules.
    """

    rule_id = "TPU012"
    summary = "wall-clock duration in hot module / leaked telemetry span"

    _SPAN_OPENERS = frozenset({"begin_span", "start_span"})

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        findings: List[Finding] = []
        if ctx.hot_path:
            self._wall_clock_findings(ctx, findings)
        analyzed: Set[ast.AST] = set()
        for fn in iter_functions(ctx.tree):
            # outermost functions whole: the open and its close may live
            # in different closures of one coordinator function (the
            # scatter-gather launch/resolve shape)
            cur = ctx.parents.get(fn)
            nested = False
            while cur is not None:
                if cur in analyzed:
                    nested = True
                    break
                cur = ctx.parents.get(cur)
            if nested:
                continue
            analyzed.add(fn)
            self._leaked_span_findings(fn, ctx, findings)
        return findings

    def _wall_clock_findings(self, ctx: ModuleContext,
                             findings: List[Finding]) -> None:
        time_mods: Set[str] = set()
        time_fns: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "time":
                        time_mods.add(alias.asname or "time")
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for alias in node.names:
                    if alias.name == "time":
                        time_fns.add(alias.asname or "time")
        if not time_mods and not time_fns:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or node.args or node.keywords:
                continue
            fn = node.func
            hit = (isinstance(fn, ast.Attribute) and fn.attr == "time"
                   and isinstance(fn.value, ast.Name)
                   and fn.value.id in time_mods) \
                or (isinstance(fn, ast.Name) and fn.id in time_fns)
            if hit:
                findings.append(ctx.finding(
                    self.rule_id, node,
                    "time.time() in a hot-path module: wall clocks step "
                    "under NTP, so durations built from them poison the "
                    "telemetry histograms — use time.monotonic() / "
                    "time.perf_counter_ns() for durations (epoch "
                    "timestamps belong outside hot modules)"))

    def _leaked_span_findings(self, fn, ctx: ModuleContext,
                              findings: List[Finding]) -> None:
        opens: Dict[str, ast.Call] = {}
        closed: Set[str] = set()
        with_items: Set[ast.AST] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    with_items.add(item.context_expr)
            elif isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr in self._SPAN_OPENERS:
                opens[node.targets[0].id] = node.value
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                if node.func.attr == "end_span" and node.args \
                        and isinstance(node.args[0], ast.Name):
                    closed.add(node.args[0].id)
                elif node.func.attr in ("end", "finish"):
                    base = base_name(node.func.value)
                    if base:
                        closed.add(base)
        for name, call in opens.items():
            if call in with_items or name in closed:
                continue
            findings.append(ctx.finding(
                self.rule_id, call,
                f"span [{name}] opened with "
                f"{call.func.attr}() but never closed in this function "
                "(leaked-span class): the tasks API keeps reporting it "
                "as current_span and the trace ring shows dur_ns: null "
                "— use the span() context manager, end_span in a "
                "finally:, or the retroactive record_span(name, dur_ns)"))


# ---------------------------------------------------------------------------
# TPU013 — hand-rolled quantization arithmetic outside quant/
# ---------------------------------------------------------------------------

_ROUND_NAMES = frozenset({"round", "rint"})


class HandRolledQuantRule(Rule):
    """TPU013: quantize/dequantize arithmetic outside the vector codec
    registry (`elasticsearch_tpu/quant/`).

    Historical context (ISSUE 15): by PR 14 the int8 recipe existed in
    four hand-rolled copies — `ops/quantization` (the nominal owner),
    the binned Pallas kernel's in-trace query quantization, the host
    VNNI mirror's packer, and the bench harness's jit — and the int4 /
    binary rungs would have added four more each. A recipe drift between
    any pair breaks byte parity between host twins and device kernels,
    which the two-phase rescore contract depends on. The codec registry
    (`quant/codec.py`) now owns every encode/decode, with np+jnp twins
    pinned byte-identical by test; this rule keeps a fifth copy from
    growing back. Two patterns fire outside `quant/`:

    * scale-divide-round-clip — a `clip(...)` call whose first argument
      contains a `round`/`rint` of a division: the symmetric scalar
      quantization idiom (`clip(round(x / scale), lo, hi)`), however the
      calls are spelled (np/jnp/method form);
    * sign-bit packing — `packbits(...)`, or a left-shift whose left
      operand derives from a sign comparison against zero
      (`(x >= 0) << j`): the binary-encoding idiom;
    * nibble-plane packing — a bitwise-or of a `<< 4` where the
      expression carries array evidence (an `.astype(...)` cast or a
      step-2 plane slice like `q[:, 0::2]`): the int4 token-block
      idiom `lo | (hi << 4)` that `quant/tokens.py` owns for
      `rank_vectors` fields. Scalar nibble pairs built from plain ints
      (the Uid `_id` encoding) carry neither signal and stay clean.

    Route through `quant.codec.get(name).encode_np/encode_jnp` or
    `quant.tokens.encode_tokens` (or the codec helpers for in-kernel
    unpack) instead.
    """

    rule_id = "TPU013"
    summary = "hand-rolled quantization arithmetic outside quant/"

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        if ctx.matches(ctx.config.quant_allowed):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = call_name(node).split(".")[-1]
                if name == "clip" and node.args \
                        and self._has_round_of_div(node.args[0]):
                    findings.append(ctx.finding(
                        self.rule_id, node,
                        "scale-divide-round-clip quantization outside "
                        "elasticsearch_tpu/quant/ — the codec registry "
                        "owns every encoding recipe (quant.codec.get("
                        "...).encode_np / encode_jnp); a drifted copy "
                        "breaks host-twin/device byte parity"))
                elif name == "packbits":
                    findings.append(ctx.finding(
                        self.rule_id, node,
                        "sign-bit packing outside elasticsearch_tpu/"
                        "quant/ — the binary codec owns the bit layout "
                        "(quant.codec.get('binary') / "
                        "pack_sign_bits_jnp)"))
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.LShift) \
                    and self._has_sign_compare(node.left):
                findings.append(ctx.finding(
                    self.rule_id, node,
                    "sign-bit packing ((x >= 0) << ...) outside "
                    "elasticsearch_tpu/quant/ — the binary codec owns "
                    "the bit layout (quant.codec.get('binary') / "
                    "pack_sign_bits_jnp)"))
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.BitOr) \
                    and self._is_nibble_pack(node):
                findings.append(ctx.finding(
                    self.rule_id, node,
                    "nibble-plane packing (lo | (hi << 4) on array "
                    "data) outside elasticsearch_tpu/quant/ — "
                    "quant.tokens.encode_tokens owns the int4 "
                    "token-block layout; a drifted plane order breaks "
                    "the fused MaxSim kernel's even/odd dim convention"))
        return findings

    @staticmethod
    def _has_round_of_div(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and call_name(sub).split(".")[-1] in _ROUND_NAMES \
                    and any(isinstance(inner, ast.BinOp)
                            and isinstance(inner.op, ast.Div)
                            for arg in sub.args
                            for inner in ast.walk(arg)):
                return True
        return False

    @staticmethod
    def _is_nibble_pack(node: ast.BinOp) -> bool:
        """`x | (y << 4)` (either order) with array evidence somewhere
        in the expression: an `.astype(...)` call, or an extended slice
        whose step is the literal 2 (the `q[:, 0::2]` plane split).
        Plain-int nibble pairs (`(b1 << 4) | b2` in the Uid encoder)
        carry neither signal."""
        shift = None
        for side in (node.left, node.right):
            if isinstance(side, ast.BinOp) \
                    and isinstance(side.op, ast.LShift) \
                    and isinstance(side.right, ast.Constant) \
                    and side.right.value == 4:
                shift = side
        if shift is None:
            return False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "astype":
                return True
            if isinstance(sub, ast.Slice) and sub.step is not None \
                    and isinstance(sub.step, ast.Constant) \
                    and sub.step.value == 2:
                return True
        return False

    @staticmethod
    def _has_sign_compare(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Compare) and len(sub.ops) == 1 \
                    and isinstance(sub.ops[0], (ast.GtE, ast.Lt)) \
                    and len(sub.comparators) == 1 \
                    and isinstance(sub.comparators[0], ast.Constant) \
                    and sub.comparators[0].value == 0:
                return True
        return False


# ---------------------------------------------------------------------------
# TPU014 — durability discipline: verify content blobs, don't mutate
# sealed-generation state outside its owners
# ---------------------------------------------------------------------------

class DurabilityRule(Rule):
    """TPU014: durable-elasticity discipline (ISSUE 17).

    Every byte in the content-addressed areas — repository `blobs/` and
    the peer-recovery block cache — is named by its sha256, and every
    consumer between the wire and an `Engine` re-verifies it: a torn
    upload, a bit-rotted file, or a truncated chunk must surface as a
    retryable digest failure, never as a silently corrupt commit the
    shard then serves. Likewise the sealed-generation trio the commit
    point captures (`segments` list, `deleted_rows`, `version_map`) is
    mutated ONLY by its owners — the engine (indexing/merge), the
    segments machinery, and the recovery assembler that rebuilds commits
    byte-identically; a mutation anywhere else desyncs the live state
    from the durable one, and the divergence only shows up after the
    next restore. Two patterns fire:

    * a `read_blob(...)` call whose key names the content-addressed
      `blobs/` area, in a function with no digest-verification call
      (sha256/digest/verify/crc32 in the callee name) — size probes and
      "just a peek" reads included: route through the repository's
      verified `get_bytes`, or verify inline;
    * assignment to / deletion of / a mutating method call on an
      attribute named `segments`, `deleted_rows` or `version_map`
      outside the owning modules (`durability_allowed` globs).
    """

    rule_id = "TPU014"
    summary = ("unverified content-blob read, or sealed-generation "
               "state mutated outside its owners")

    _SEALED = frozenset({"segments", "deleted_rows", "version_map"})
    _MUTATORS = frozenset({"append", "add", "update", "pop", "popitem",
                           "clear", "setdefault", "discard", "remove",
                           "extend", "insert"})
    _VERIFY_TOKENS = ("sha256", "digest", "verify", "crc32")

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        findings = self._unverified_blob_reads(ctx)
        if not ctx.matches(ctx.config.durability_allowed):
            findings.extend(self._sealed_mutations(ctx))
        return findings

    # -- unverified reads of content-addressed blobs ------------------------

    def _unverified_blob_reads(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and call_name(node).split(".")[-1] == "read_blob"
                    and node.args
                    and self._names_blob_area(node.args[0])):
                continue
            if self._scope_verifies(ctx, node):
                continue
            findings.append(ctx.finding(
                self.rule_id, node,
                "content-addressed blob read without digest "
                "verification — a torn or bit-rotted blob flows "
                "straight into the caller; route through the "
                "repository's get_bytes (sha256-verified, raises "
                "RepositoryError on mismatch) or verify the digest "
                "in this function"))
        return findings

    @staticmethod
    def _names_blob_area(arg: ast.AST) -> bool:
        """The key expression mentions the content-addressed `blobs/`
        prefix (plain string or any piece of an f-string)."""
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                    and "blobs/" in sub.value:
                return True
        return False

    def _scope_verifies(self, ctx: ModuleContext, node: ast.AST) -> bool:
        """Does the enclosing function (or the module, for top-level
        code) CALL anything that verifies bytes? Mentioning a digest is
        not enough — only a sha256/…/verify call counts as evidence."""
        scope: ast.AST = ctx.tree
        cur = ctx.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = cur
                break
            cur = ctx.parents.get(cur)
        for sub in ast.walk(scope):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not scope:
                continue
            if isinstance(sub, ast.Call):
                callee = call_name(sub).split(".")[-1].lower()
                if any(tok in callee for tok in self._VERIFY_TOKENS):
                    return True
        return False

    # -- sealed-generation state mutated outside its owners -----------------

    def _sealed_mutations(self, ctx: ModuleContext) -> List[Finding]:
        findings: List[Finding] = []

        def sealed_attr(expr: ast.AST):
            """The sealed attribute an expression reaches through (e.g.
            `eng.deleted_rows[k]` or `eng.version_map`), if any."""
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Attribute) \
                        and sub.attr in self._SEALED:
                    return sub.attr
            return None

        def fire(node: ast.AST, attr: str, how: str) -> None:
            findings.append(ctx.finding(
                self.rule_id, node,
                f"{how} of sealed-generation state [.{attr}] outside "
                "its owners (index/engine.py, segments/, recovery/) — "
                "the commit point no longer matches the live state, "
                "and the divergence surfaces only after the next "
                "restore; go through the engine's API instead"))

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and t.attr in self._SEALED:
                        fire(node, t.attr, "assignment")
                    elif isinstance(t, ast.Subscript):
                        attr = sealed_attr(t.value)
                        if attr is not None:
                            fire(node, attr, "item assignment")
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    attr = sealed_attr(t)
                    if attr is not None:
                        fire(node, attr, "deletion")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in self._MUTATORS:
                attr = sealed_attr(node.func.value)
                if attr is not None:
                    fire(node, attr, f"{node.func.attr}() mutation")
        return findings


class EventLoopBlockingRule(Rule):
    """TPU015: blocking IO / sleeps lexically on an asyncio event loop.

    The multi-process cluster serves ALL of a node's RPCs on one asyncio
    loop (`transport/tcp.py`): a single `time.sleep` or synchronous
    socket/file/subprocess call inside an `async def` — or inside a
    callback handed to the loop's own scheduling primitives
    (`call_soon`/`call_later`/`call_at`) — parks every in-flight
    request, response, and keepalive on that node. The symptom is a
    cross-node p99 spike with no device work to blame; the first
    real-socket bench run surfaced exactly this shape. Blocking work
    belongs on a worker thread (`run_in_executor`, or the recovery tier's
    upload pools).

    Scope is `async_actor_globs` (transport/, cluster/) and the rule is
    LEXICAL: it only judges code that demonstrably runs on the loop.
    Plain sync helpers in the same files — thread-loop bodies, CLI
    entry points, `AsyncioScheduler.schedule` callbacks (which run
    engine work by design, on the sim queue and loop alike) — are out
    of scope: being in the file is not evidence of running on the loop.
    """

    rule_id = "TPU015"

    _BLOCKING = {
        "time.sleep": "parks the whole event loop for the duration",
        "socket.create_connection": "synchronous connect stalls the loop",
        "subprocess.run": "waiting on a child process stalls the loop",
        "subprocess.check_output":
            "waiting on a child process stalls the loop",
        "subprocess.check_call":
            "waiting on a child process stalls the loop",
        "urllib.request.urlopen": "synchronous HTTP stalls the loop",
    }
    _BARE = {"open": "synchronous file IO stalls the loop"}
    _LOOP_SCHEDULERS = {"call_soon", "call_soon_threadsafe",
                        "call_later", "call_at"}

    def run(self, ctx: ModuleContext, index: ProjectIndex) -> List[Finding]:
        if not ctx.matches(getattr(ctx.config, "async_actor_globs", ())):
            return []
        # local sync defs by name, to resolve `loop.call_soon(pump)`
        local_defs: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef):
                local_defs.setdefault(node.name, node)
        targets: List[Tuple[ast.AST, str]] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                targets.append((node, f"async handler [{node.name}]"))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in self._LOOP_SCHEDULERS:
                args = list(node.args) + [kw.value for kw in node.keywords]
                for arg in args:
                    if isinstance(arg, ast.Lambda):
                        targets.append(
                            (arg, f"{node.func.attr}() callback"))
                    elif isinstance(arg, ast.Name) \
                            and arg.id in local_defs:
                        targets.append((local_defs[arg.id],
                                        f"{node.func.attr}() callback "
                                        f"[{arg.id}]"))
        findings: List[Finding] = []
        seen: Set[int] = set()
        for fn, how in targets:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            findings.extend(self._scan(ctx, fn, how))
        findings.sort(key=lambda f: (f.line, f.col))
        return findings

    def _scan(self, ctx: ModuleContext, fn: ast.AST,
              how: str) -> List[Finding]:
        # lexically inside THIS function only: nested defs get their own
        # judgment (a nested sync def may run on a thread)
        if isinstance(fn, ast.Lambda):
            exprs: List[ast.AST] = list(ast.walk(fn.body))
        else:
            exprs = []
            for stmt, _ in _body_statements(fn.body):
                exprs.extend(_stmt_expressions(stmt))
        out: List[Finding] = []
        for node in exprs:
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            why = self._BLOCKING.get(name) or self._BARE.get(name)
            if why is None:
                continue
            out.append(ctx.finding(
                self.rule_id, node,
                f"blocking call [{name}] inside {how} — {why}; every "
                "in-flight RPC and keepalive on this node's loop stalls "
                "behind it. Move it to a worker thread "
                "(run_in_executor) or make it async"))
        return out


ALL_RULES: List[Rule] = [
    RawJitRule(), HostSyncRule(), IdKeyedCacheRule(), ReadAfterDonateRule(),
    UnscrubbedCacheKeyRule(), ScopedX64Rule(), SpecRankRule(),
    ModuleCacheLockRule(), LockedSyncRule(), UnguardedFanoutRule(),
    PrivateSegmentCacheRule(), TelemetryDisciplineRule(),
    HandRolledQuantRule(), DurabilityRule(), EventLoopBlockingRule(),
]
