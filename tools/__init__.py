"""Build-time tooling (static analysis, lint gates). Not shipped with the
engine package — `elasticsearch_tpu/` must never import from here."""
