"""North-star benchmark: exact cosine kNN on a SIFT-1M-shaped corpus.

Measures the TPU batched matmul + top-k path (BASELINE.md config 1:
SIFT-1M-like, 128-d, cosine, single shard/chip) against a model of the
reference's execution: a per-document scripted scoring loop
(`ScoreScriptUtils.cosineSimilarity` invoked per doc per query from the
Lucene collector, `QueryPhase.java:171`), emulated here as a per-doc numpy
dot loop over a subsample and extrapolated. Recall@10 is computed against
exact f32 search (ours is exact brute force, so recall measures only bf16
rounding, and must stay >= 0.95 to count — same gate as BASELINE).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.ops import similarity as sim

    small = os.environ.get("BENCH_SMALL") == "1"
    n = 100_000 if small else 1_000_000
    d = 128
    k = 10
    batch = 128
    n_batches = 4 if small else 8
    n_queries = batch * n_batches

    rng = np.random.default_rng(1234)
    # SIFT-like: clustered data so near-neighbor structure exists
    centers = rng.standard_normal((256, d)).astype(np.float32) * 2.0
    assign = rng.integers(0, 256, size=n)
    vectors = centers[assign] + rng.standard_normal((n, d)).astype(np.float32)
    q_assign = rng.integers(0, n, size=n_queries)
    queries = vectors[q_assign] + 0.3 * rng.standard_normal((n_queries, d)).astype(np.float32)

    corpus = knn_ops.build_corpus(vectors, metric=sim.COSINE, dtype="bf16")
    qdev = jnp.asarray(queries)
    jax.block_until_ready(corpus)

    def search(qb):
        return knn_ops.knn_search(qb, corpus, k=k, metric=sim.COSINE, precision="bf16")

    # warmup/compile
    s, i = search(qdev[:batch])
    jax.block_until_ready((s, i))

    # timed: per-batch latencies
    lat = []
    all_ids = []
    for b in range(n_batches):
        qb = qdev[b * batch:(b + 1) * batch]
        t0 = time.perf_counter()
        s, ids = search(qb)
        jax.block_until_ready(ids)
        lat.append(time.perf_counter() - t0)
        all_ids.append(np.asarray(ids))
    total_time = sum(lat)
    qps = n_queries / total_time
    p50_ms = float(np.median(lat) * 1000.0)

    # recall@10 of the bf16 path vs exact f32 (one batch)
    s_ref, ids_ref = knn_ops.knn_search(qdev[:batch], corpus, k=k,
                                        metric=sim.COSINE, precision="f32")
    ids_ref = np.asarray(ids_ref)
    hits = sum(len(set(all_ids[0][r]) & set(ids_ref[r])) for r in range(batch))
    recall = hits / (batch * k)

    # baseline: per-doc scripted loop emulation (reference's per-doc
    # CosineSimilarity call), measured on a subsample and scaled to n docs
    sub = 20_000
    subv = vectors[:sub]
    sub_norms = np.linalg.norm(subv, axis=1)
    q0 = queries[0]
    q0n = np.linalg.norm(q0)
    t0 = time.perf_counter()
    scores = np.empty(sub, dtype=np.float32)
    for j in range(sub):
        v = subv[j]
        scores[j] = float(np.dot(q0, v)) / (q0n * sub_norms[j])
    np.argpartition(-scores, k)[:k]
    t_loop = time.perf_counter() - t0
    baseline_qps = 1.0 / (t_loop * (n / sub))

    out = {
        "metric": "exact_knn_qps_sift1m_cosine",
        "value": round(qps, 2),
        "unit": "qps",
        "vs_baseline": round(qps / baseline_qps, 1),
        "recall_at_10": round(recall, 4),
        "p50_batch_ms": round(p50_ms, 2),
        "batch_size": batch,
        "n_docs": n,
        "dims": d,
        "baseline_qps_scripted_loop": round(baseline_qps, 4),
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(out))
    if recall < 0.95:
        sys.exit(1)


if __name__ == "__main__":
    main()
