"""North-star benchmark: kNN QPS @ recall@10 >= 0.95 on a SIFT-1M-shaped corpus.

Measures the TPU device path (BASELINE.md config 1: SIFT-1M-like, 128-d,
cosine, single chip): the binned-reduction Pallas kernel
(`ops/pallas_knn_binned.py` — matmul + in-VMEM bin-max, one small top-k)
driven through the one-dispatch multi-batch harness (this environment adds a
~68 ms tunnel round-trip per dispatch, so batches are scanned inside a
single compiled program, as a production search node would batch concurrent
queries).

Baseline model: the reference's execution is a per-document scripted scoring
loop (`ScoreScriptUtils.cosineSimilarity` per doc per query from the Lucene
collector, `QueryPhase.java:171`), emulated as a per-doc numpy dot loop over
a subsample and extrapolated to the full corpus.

Recall@10 is measured against the exact f32 result and gates the metric
(same recall >= 0.95 gate as BASELINE).

Resilience: the TPU backend here lives behind a tunnel that can be
transiently UNAVAILABLE or hang on first contact (round 2 lost its official
capture to exactly that). The parent process therefore never imports jax:
it probes the backend in a killable subprocess with a bounded timeout and
retries with backoff, runs the measurement itself in a watchdogged child,
and on final failure emits ONE diagnostic JSON line instead of a stack
trace. A global 15-minute deadline bounds total runtime: every stage's
timeout is clipped to the time remaining.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import time

METRIC = "knn_qps_sift1m_cosine_recall_gated"


def _last_known_good() -> dict:
    """Freshest committed config-1 capture, so a tunnel outage at snapshot
    time reports THIS round's numbers when a mid-round capture landed
    (VERDICT r4 weak #7: the official record should never regress to an
    old round's figures just because the final probe lost the race)."""
    import glob
    import re
    best = {"qps": 126472.3, "recall_at_10": 0.9925,
            "source": "BENCH_MATRIX_r02.json config 1"}
    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for path in glob.glob(os.path.join(here, "BENCH_MATRIX_r*.json")):
        m = re.search(r"_r(\d+)\.json$", path)
        if m:
            rounds.append((int(m.group(1)), path))
    for _rnum, path in sorted(rounds, reverse=True):
        newest = None
        try:
            with open(path) as f:
                for line in f:
                    row = json.loads(line)
                    if str(row.get("config", "")).startswith("1") \
                            and row.get("qps"):
                        newest = row  # LAST matching line = freshest capture
        except (OSError, ValueError):
            continue
        if newest is not None:
            return {"qps": newest["qps"],
                    "recall_at_10": newest.get("recall_at_10"),
                    "source": f"{os.path.basename(path)} "
                              f"config {newest['config']}"}
    return best

_PROBE_CODE = r"""
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = jnp.zeros((256, 256), dtype=jnp.bfloat16)
(x @ x).block_until_ready()
print("PROBE_OK", d.platform, flush=True)
"""


def _probe_backend(timeout_s: float) -> tuple[bool, str]:
    """Touch the backend (device query + one MXU op) in a killable child."""
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                           capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"backend probe hung > {timeout_s:.0f}s (killed)"
    if r.returncode != 0 or "PROBE_OK" not in r.stdout:
        tail = (r.stderr or r.stdout).strip().splitlines()[-3:]
        return False, " | ".join(tail) or f"probe rc={r.returncode}"
    return True, r.stdout.split("PROBE_OK", 1)[1].strip()


def _run_child(timeout_s: float, extra_env: dict | None = None
               ) -> tuple[int, str, str]:
    env = dict(os.environ, _BENCH_CHILD="1", **(extra_env or {}))
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           capture_output=True, text=True, timeout=timeout_s,
                           env=env)
        return r.returncode, r.stdout, r.stderr
    except subprocess.TimeoutExpired as e:
        def _txt(b):
            return b.decode(errors="replace") if isinstance(b, bytes) else (b or "")
        return -1, _txt(e.stdout), _txt(e.stderr) or \
            f"bench child hung > {timeout_s:.0f}s (killed)"


def _cpu_floor_line(reason: str, errors: list, remaining_s: float) -> bool:
    """TPU acquisition failed: measure the same program on the CPU backend
    and emit it clearly labeled `"backend": "cpu_floor"` — a lower bound on
    the metric instead of an evidence-free `value: 0` (three of five past
    rounds went evidence-free exactly here). Returns True if a line was
    printed."""
    budget = min(420, remaining_s - 10)
    if budget < 120:  # not enough wall clock left for a meaningful floor
        return False
    try:
        _rc, out, _err = _run_child(
            budget, extra_env={"JAX_PLATFORMS": "cpu", "BENCH_SMALL": "1"})
    except Exception:  # the floor is best-effort: never mask the
        return False   # diagnostic line below
    line = next((l for l in reversed(out.splitlines())
                 if l.startswith("{")), None)
    if line is None:
        return False
    try:
        parsed = json.loads(line)
    except ValueError:
        return False
    if not parsed.get("value"):
        return False
    parsed["backend"] = "cpu_floor"
    parsed["cpu_floor_note"] = (
        "TPU backend unavailable; CPU-backend lower bound on a "
        f"{parsed.get('n_docs')}-doc subsample — NOT the device number")
    parsed["error"] = reason
    parsed["probe_errors"] = errors[-2:]
    parsed["last_known_good"] = _last_known_good()
    print(json.dumps(parsed))
    return True


def main():
    small = os.environ.get("BENCH_SMALL") == "1"
    # global wall-clock cap; BENCH_ACQUIRE_S widens it (e.g. a driver that
    # can afford to wait out a tunnel outage sets 3600). Malformed values
    # must not crash before the JSON line: default to 0
    try:
        acquire_s = int(float(os.environ.get("BENCH_ACQUIRE_S", "0") or 0))
    except ValueError:
        acquire_s = 0
    budget_s = 1140 + acquire_s
    deadline = time.monotonic() + budget_s

    def remaining():
        return deadline - time.monotonic()

    # --- phase 1: bounded backend acquisition, exponential backoff --------
    # retries ride whatever window the caller gave us: with the default
    # budget ~4 probes; with BENCH_ACQUIRE_S=3600 the probe loop spans the
    # whole hour before giving up (VERDICT r4: retry across the round, not
    # two probes at snapshot time)
    platform = None
    errors = []
    max_attempts = 4 + acquire_s // 120
    for attempt in range(1, max_attempts + 1):
        if remaining() < 150:
            break
        ok, info = _probe_backend(timeout_s=min(120, max(30, remaining())))
        if ok:
            platform = info
            break
        errors.append(f"attempt {attempt}: {info}")
        if attempt < max_attempts and remaining() > 300:
            time.sleep(min(120, 10 * 2 ** min(attempt - 1, 4)))
    if platform is None:
        if _cpu_floor_line("tpu_backend_unavailable", errors, remaining()):
            sys.exit(1)  # still a failed capture — but with evidence
        print(json.dumps({
            "metric": METRIC, "value": 0, "unit": "qps", "vs_baseline": 0,
            "error": "tpu_backend_unavailable",
            "backend": "none",
            "probe_errors": errors[-2:],
            "last_known_good": _last_known_good(),
        }))
        sys.exit(1)

    # --- phase 2: watchdogged measurement ---------------------------------
    child_timeout = 420 if small else 720
    last_err = ""
    for attempt in range(2):
        budget = min(child_timeout, max(120, remaining()))
        rc, out, err = _run_child(budget)
        line = next((l for l in reversed(out.splitlines())
                     if l.startswith("{")), None)
        if line is not None:
            print(line)
            if rc >= 0:
                sys.exit(rc)
            # child timed out AFTER printing its result (e.g. a hang in
            # runtime teardown over the tunnel): a well-formed success
            # line is still a successful capture
            try:
                parsed = json.loads(line)
            except ValueError:
                parsed = {}
            ok = "error" not in parsed and parsed.get("value", 0) > 0
            sys.exit(0 if ok else 1)
        last_err = (err or "").strip().splitlines()[-3:] if err else \
            [f"child rc={rc} with no JSON output"]
        last_err = " | ".join(last_err) if isinstance(last_err, list) else last_err
        if remaining() < 150:
            break
    if _cpu_floor_line("bench_child_failed", [last_err], remaining()):
        sys.exit(1)
    print(json.dumps({
        "metric": METRIC, "value": 0, "unit": "qps", "vs_baseline": 0,
        "error": "bench_child_failed", "detail": last_err,
        "platform": platform,
        "last_known_good": _last_known_good(),
    }))
    sys.exit(1)


def child_main():
    import numpy as np
    import jax
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.ops import similarity as sim
    from elasticsearch_tpu.ops.pallas_knn_binned import binned_knn_search

    small = os.environ.get("BENCH_SMALL") == "1"
    n = 131_072 if small else 1_000_000
    d = 128
    k = 10
    batch = 256  # Q=256 saturates the v5e pipeline (~2x the QPS of Q=128)
    # enough batches per dispatch that the tunnel round-trip (~40-70 ms in
    # this environment; ~µs on a TPU-attached host) amortizes below the
    # per-batch kernel time
    n_batches = 16 if small else 64
    n_queries = batch * n_batches

    rng = np.random.default_rng(1234)
    # SIFT-like: clustered data so near-neighbor structure exists
    centers = rng.standard_normal((256, d)).astype(np.float32) * 2.0
    assign = rng.integers(0, 256, size=n)
    vectors = centers[assign] + rng.standard_normal((n, d)).astype(np.float32)
    q_assign = rng.integers(0, n, size=n_queries)
    queries = vectors[q_assign] + 0.3 * rng.standard_normal((n_queries, d)).astype(np.float32)

    corpus = knn_ops.build_corpus(vectors, metric=sim.COSINE, dtype="bf16")
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    qstack = jnp.asarray(queries.reshape(n_batches, batch, d))
    jax.block_until_ready(corpus)

    if on_tpu:
        @functools.partial(jax.jit, static_argnames=("kk",))
        def search_all(qs, c, kk):
            def body(carry, qb):
                return carry, binned_knn_search(qb, c, kk)
            _, out = jax.lax.scan(body, None, qs)
            return out
    else:
        @functools.partial(jax.jit, static_argnames=("kk",))
        def search_all(qs, c, kk):
            def body(carry, qb):
                return carry, knn_ops.knn_search(qb, c, kk, metric=sim.COSINE)
            _, out = jax.lax.scan(body, None, qs)
            return out

    # warmup/compile
    out = search_all(qstack, corpus, k)
    np.asarray(out[1])

    # timed runs: whole stack in one dispatch; report amortized throughput
    # (min over runs — the steady-state device rate, matching bench_matrix)
    runs = []
    for _ in range(3 if not small else 2):
        t0 = time.perf_counter()
        out = search_all(qstack, corpus, k)
        all_ids = np.asarray(out[1])
        runs.append(time.perf_counter() - t0)
    total_time = float(np.min(runs))
    qps = n_queries / total_time
    batch_ms = total_time / n_batches * 1000.0

    # recall@10 of the fast path vs exact f32 (first batch)
    s_ref, ids_ref = knn_ops.knn_search(qstack[0], corpus, k=k,
                                        metric=sim.COSINE, precision="f32")
    ids_ref = np.asarray(ids_ref)
    hits = sum(len(set(all_ids[0][r]) & set(ids_ref[r])) for r in range(batch))
    recall = hits / (batch * k)

    # baseline: per-doc scripted loop emulation (the reference's per-doc
    # CosineSimilarity call), measured on a subsample and scaled to n docs
    sub = 20_000
    subv = vectors[:sub]
    sub_norms = np.linalg.norm(subv, axis=1)
    q0 = queries[0]
    q0n = np.linalg.norm(q0)
    t0 = time.perf_counter()
    scores = np.empty(sub, dtype=np.float32)
    for j in range(sub):
        v = subv[j]
        scores[j] = float(np.dot(q0, v)) / (q0n * sub_norms[j])
    np.argpartition(-scores, k)[:k]
    t_loop = time.perf_counter() - t0
    baseline_qps = 1.0 / (t_loop * (n / sub))

    out = {
        "metric": METRIC,
        "value": round(qps, 2),
        "unit": "qps",
        "vs_baseline": round(qps / baseline_qps, 1),
        "recall_at_10": round(recall, 4),
        "amortized_batch_ms": round(batch_ms, 2),
        "batch_size": batch,
        "n_docs": n,
        "dims": d,
        "kernel": "pallas_binned" if on_tpu else "xla_exact",
        "baseline_qps_scripted_loop": round(baseline_qps, 4),
        "device": str(jax.devices()[0]),
    }

    # the 10Mx768 int8 NORTH STAR on the official record (VERDICT r3 item
    # 7): generated+measured on-device, recall-gated against exact f32
    # ground truth; best-effort — a failure here must never lose the
    # config-1 headline
    if on_tpu:
        try:
            import bench_matrix
            ns = bench_matrix.run_north_star_10m_int8(
                n=1_000_000 if small else 10_000_000, emit=False,
                extra=False)
            out["north_star"] = ns
        except Exception as e:  # noqa: BLE001 — diagnostic, not fatal
            out["north_star"] = {"error": str(e)[:200]}
        try:
            # recall-headroom row: residual level doubles corpus HBM, so
            # it runs at 5M (16 GB chip) — the packed rescore's recall
            # target is >=0.97 at <=20% QPS cost (VERDICT r5 item 2). Its
            # OWN try: an OOM here must never lose the 10M headline above
            nsr = bench_matrix.run_north_star_10m_int8(
                n=1_000_000 if small else 5_000_000, emit=False,
                extra=False, residual=True)
            out["north_star_residual"] = {
                "n_docs": nsr["n_docs"],
                "base_qps": nsr["qps"],
                "base_recall": nsr["recall_at_10"],
                **nsr.get("packed_residual_rescore", {})}
        except Exception as e:  # noqa: BLE001 — diagnostic, not fatal
            out["north_star_residual"] = {"error": str(e)[:200]}

    print(json.dumps(out))
    if recall < 0.95:
        sys.exit(1)
    ns_recall = (out.get("north_star") or {}).get("recall_at_10")
    if ns_recall is not None and ns_recall < 0.95:
        sys.exit(1)


if __name__ == "__main__":
    if os.environ.get("_BENCH_CHILD") == "1":
        child_main()
    else:
        main()
