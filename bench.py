"""North-star benchmark: kNN QPS @ recall@10 >= 0.95 on a SIFT-1M-shaped corpus.

Measures the TPU device path (BASELINE.md config 1: SIFT-1M-like, 128-d,
cosine, single chip): the binned-reduction Pallas kernel
(`ops/pallas_knn_binned.py` — matmul + in-VMEM bin-max, one small top-k)
driven through the one-dispatch multi-batch harness (this environment adds a
~68 ms tunnel round-trip per dispatch, so batches are scanned inside a
single compiled program, as a production search node would batch concurrent
queries).

Baseline model: the reference's execution is a per-document scripted scoring
loop (`ScoreScriptUtils.cosineSimilarity` per doc per query from the Lucene
collector, `QueryPhase.java:171`), emulated as a per-doc numpy dot loop over
a subsample and extrapolated to the full corpus.

Recall@10 is measured against the exact f32 result and gates the metric
(same recall >= 0.95 gate as BASELINE).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.ops import similarity as sim
    from elasticsearch_tpu.ops.pallas_knn_binned import binned_knn_search

    small = os.environ.get("BENCH_SMALL") == "1"
    n = 131_072 if small else 1_000_000
    d = 128
    k = 10
    batch = 256  # Q=256 saturates the v5e pipeline (~2x the QPS of Q=128)
    # enough batches per dispatch that the tunnel round-trip (~40-70 ms in
    # this environment; ~µs on a TPU-attached host) amortizes below the
    # per-batch kernel time
    n_batches = 16 if small else 150
    n_queries = batch * n_batches

    rng = np.random.default_rng(1234)
    # SIFT-like: clustered data so near-neighbor structure exists
    centers = rng.standard_normal((256, d)).astype(np.float32) * 2.0
    assign = rng.integers(0, 256, size=n)
    vectors = centers[assign] + rng.standard_normal((n, d)).astype(np.float32)
    q_assign = rng.integers(0, n, size=n_queries)
    queries = vectors[q_assign] + 0.3 * rng.standard_normal((n_queries, d)).astype(np.float32)

    corpus = knn_ops.build_corpus(vectors, metric=sim.COSINE, dtype="bf16")
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    qstack = jnp.asarray(queries.reshape(n_batches, batch, d))
    jax.block_until_ready(corpus)

    if on_tpu:
        @functools.partial(jax.jit, static_argnames=("kk",))
        def search_all(qs, c, kk):
            def body(carry, qb):
                return carry, binned_knn_search(qb, c, kk)
            _, out = jax.lax.scan(body, None, qs)
            return out
    else:
        @functools.partial(jax.jit, static_argnames=("kk",))
        def search_all(qs, c, kk):
            def body(carry, qb):
                return carry, knn_ops.knn_search(qb, c, kk, metric=sim.COSINE)
            _, out = jax.lax.scan(body, None, qs)
            return out

    # warmup/compile
    out = search_all(qstack, corpus, k)
    np.asarray(out[1])

    # timed runs: whole stack in one dispatch; report amortized throughput
    # and the single-dispatch wall time
    runs = []
    for _ in range(3 if not small else 2):
        t0 = time.perf_counter()
        out = search_all(qstack, corpus, k)
        all_ids = np.asarray(out[1])
        runs.append(time.perf_counter() - t0)
    total_time = float(np.median(runs))
    qps = n_queries / total_time
    batch_ms = total_time / n_batches * 1000.0

    # recall@10 of the fast path vs exact f32 (first batch)
    s_ref, ids_ref = knn_ops.knn_search(qstack[0], corpus, k=k,
                                        metric=sim.COSINE, precision="f32")
    ids_ref = np.asarray(ids_ref)
    hits = sum(len(set(all_ids[0][r]) & set(ids_ref[r])) for r in range(batch))
    recall = hits / (batch * k)

    # baseline: per-doc scripted loop emulation (the reference's per-doc
    # CosineSimilarity call), measured on a subsample and scaled to n docs
    sub = 20_000
    subv = vectors[:sub]
    sub_norms = np.linalg.norm(subv, axis=1)
    q0 = queries[0]
    q0n = np.linalg.norm(q0)
    t0 = time.perf_counter()
    scores = np.empty(sub, dtype=np.float32)
    for j in range(sub):
        v = subv[j]
        scores[j] = float(np.dot(q0, v)) / (q0n * sub_norms[j])
    np.argpartition(-scores, k)[:k]
    t_loop = time.perf_counter() - t0
    baseline_qps = 1.0 / (t_loop * (n / sub))

    out = {
        "metric": "knn_qps_sift1m_cosine_recall_gated",
        "value": round(qps, 2),
        "unit": "qps",
        "vs_baseline": round(qps / baseline_qps, 1),
        "recall_at_10": round(recall, 4),
        "amortized_batch_ms": round(batch_ms, 2),
        "batch_size": batch,
        "n_docs": n,
        "dims": d,
        "kernel": "pallas_binned" if on_tpu else "xla_exact",
        "baseline_qps_scripted_loop": round(baseline_qps, 4),
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(out))
    if recall < 0.95:
        sys.exit(1)


if __name__ == "__main__":
    main()
