cd /root/repo
python _exp11.py doc none 2>/dev/null
