"""Round-long TPU backend acquisition daemon → BENCH_MATRIX_r{N}.json.

Three of five past rounds ended evidence-free because the benchmark ran
once, at snapshot time, against a tunnel that happened to be dark
(VERDICT r5 Next #1). This daemon inverts that: started at round open
(`python bench_daemon.py --round 6 &`), it

  1. polls for the TPU backend with the same killable-subprocess probe +
     exponential backoff as `bench.py`, for up to `--max-wait-s` seconds;
  2. the MOMENT acquisition succeeds, captures the full matrix
     (`bench_matrix.py`) and writes `BENCH_MATRIX_r{N}.json` immediately —
     not at snapshot time, so a mid-round window of tunnel health is
     enough to put device rows on the record;
  3. if the tunnel stays dark past the deadline, runs the SAME configs on
     the CPU backend (BENCH_SMALL shapes) and writes them clearly labeled
     `"backend": "cpu"` — relative claims (batcher p99 fix, fused hybrid
     row, admission control) get demonstrated on one backend instead of
     staying unproven for another round.

Every emitted row is augmented with `backend`, and a `_meta` header line
records which path produced the file. Partial captures are kept: each
bench_matrix row prints (flushed) as it completes, so a mid-run hang
still leaves every finished config on the record.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))


def _load_bench():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(HERE, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def acquire_backend(max_wait_s: float, poll_s: float = 120.0,
                    probe=None, sleep=time.sleep) -> tuple:
    """Poll for a live TPU backend until the deadline. Returns
    (platform_info | None, [probe error strings])."""
    bench = _load_bench()
    probe = probe or bench._probe_backend
    deadline = time.monotonic() + max_wait_s
    errors = []
    attempt = 0
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        ok, info = probe(timeout_s=max(30.0, min(120.0, remaining)))
        if ok and not any(p in str(info).lower()
                          for p in ("tpu", "axon")):
            # jax booted but only found the host CPU: that is NOT an
            # acquisition — a mislabeled full-size "tpu" capture on the
            # CPU backend is worse than the honest labeled floor
            ok, info = False, f"probe found non-accelerator [{info}]"
        if ok:
            return info, errors
        errors.append(f"attempt {attempt}: {info}")
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return None, errors
        # existing bench.py backoff discipline: exponential, capped, and
        # never sleeping past the deadline
        sleep(min(poll_s, 10 * 2 ** min(attempt - 1, 4), remaining))


def run_matrix(extra_env: dict, timeout_s: float) -> list:
    """Run bench_matrix.py in a watchdogged child; return every JSON row
    it managed to print (rows flush as they complete, so a hang after
    config N still yields configs 1..N)."""
    env = dict(os.environ, **extra_env)
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(HERE, "bench_matrix.py")],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=HERE)
        out = r.stdout
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode(errors="replace") \
            if isinstance(e.stdout, bytes) else (e.stdout or "")
    rows = []
    for line in out.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rows.append(json.loads(line))
        except ValueError:
            continue
    return rows


def label_rows(rows: list, backend: str, note: str = "") -> list:
    """Stamp every row with its backend; rows must never be mistaken for
    device numbers they are not."""
    out = []
    for row in rows:
        row = dict(row)
        row["backend"] = backend
        if note:
            row["backend_note"] = note
        out.append(row)
    return out


def write_matrix(path: str, meta: dict, rows: list) -> None:
    with open(path, "w") as f:
        f.write(json.dumps({"_meta": meta}) + "\n")
        for row in rows:
            f.write(json.dumps(row) + "\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--round", type=int, required=True,
                    help="round number N for BENCH_MATRIX_r{N:02d}.json")
    ap.add_argument("--max-wait-s", type=float, default=3600.0,
                    help="how long to poll for the TPU backend")
    ap.add_argument("--poll-s", type=float, default=120.0)
    ap.add_argument("--matrix-timeout-s", type=float, default=3600.0)
    ap.add_argument("--once", action="store_true",
                    help="probe once; no polling loop")
    ap.add_argument("--cpu-only", action="store_true",
                    help="skip probing, emit the labeled CPU matrix now")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    out_path = args.out or os.path.join(
        HERE, f"BENCH_MATRIX_r{args.round:02d}.json")

    platform, errors = (None, ["cpu-only requested"]) if args.cpu_only \
        else acquire_backend(0 if args.once else args.max_wait_s,
                             poll_s=args.poll_s)
    started = time.time()
    if platform is not None:
        rows = run_matrix({}, args.matrix_timeout_s)
        meta = {"round": args.round, "backend": "tpu",
                "platform": platform, "captured_unix": int(started),
                "wall_s": round(time.time() - started, 1)}
        write_matrix(out_path, meta, label_rows(rows, "tpu"))
        print(json.dumps({"daemon": "captured", "backend": "tpu",
                          "rows": len(rows), "path": out_path}))
        return 0

    # tunnel stayed dark: same configs, CPU backend, honestly labeled
    note = ("TPU tunnel dark for the whole acquisition window; "
            "CPU-backend row on BENCH_SMALL shapes — relative claims "
            "only, NOT a device number")
    rows = run_matrix({"JAX_PLATFORMS": "cpu", "BENCH_SMALL": "1"},
                      args.matrix_timeout_s)
    meta = {"round": args.round, "backend": "cpu",
            "probe_errors": errors[-3:], "captured_unix": int(started),
            "wall_s": round(time.time() - started, 1),
            "note": note}
    write_matrix(out_path, meta, label_rows(rows, "cpu", note))
    print(json.dumps({"daemon": "captured", "backend": "cpu",
                      "rows": len(rows), "path": out_path,
                      "probe_errors": errors[-2:]}))
    return 0 if rows else 1


if __name__ == "__main__":
    sys.exit(main())
