"""Admin/observability REST surface: cluster settings, reroute, allocation
explain, hot threads, breakers, slow logs, deprecations, point-in-time,
termvectors, segments/recovery/shard_stores, resolve, extra _cat APIs.

Reference handlers: `rest/action/admin/cluster/*` (RestClusterUpdateSettings,
RestClusterRerouteAction, RestClusterAllocationExplainAction,
RestNodesHotThreadsAction), `rest/action/admin/indices/*` (segments,
recovery, shard stores, resolve), `rest/action/cat/*`, `action/termvectors`,
point-in-time (`RestOpenPointInTimeAction`), x-pack deprecation checks.
"""

from __future__ import annotations

import time
import uuid
from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.common.settings import parse_time_value
from elasticsearch_tpu.monitor import hot_threads_report
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.controller import RestController
from elasticsearch_tpu.version import __version__


def register_admin(rc: RestController, node: Node) -> None:
    # ------------------------------------------------------ cluster settings
    def get_cluster_settings(req):
        out = dict(node.cluster_settings)
        if req.bool_param("include_defaults"):
            out["defaults"] = {"cluster.name": node.cluster_name}
        return 200, out

    def put_cluster_settings(req):
        body = req.json() or {}
        applied = {"acknowledged": True, "persistent": {}, "transient": {}}
        for scope in ("persistent", "transient"):
            for key, value in _flatten(body.get(scope, {})).items():
                if value is None:
                    node.cluster_settings[scope].pop(key, None)
                else:
                    node.cluster_settings[scope][key] = value
                applied[scope][key] = value
        return 200, applied

    rc.register("GET", "/_cluster/settings", get_cluster_settings)
    rc.register("PUT", "/_cluster/settings", put_cluster_settings)

    # ------------------------------------------------- reroute + allocation
    def reroute(req):
        body = req.json() or {}
        # single-node facade: commands validate + ack (real moves happen in
        # the multi-node cluster layer, cluster/allocation.py)
        for cmd in body.get("commands", []):
            kind = next(iter(cmd))
            if kind not in ("move", "cancel", "allocate_replica",
                            "allocate_stale_primary", "allocate_empty_primary"):
                raise IllegalArgumentError(f"unknown reroute command [{kind}]")
        return 200, {"acknowledged": True, "state": {
            "cluster_uuid": node.node_id,
            "nodes": {node.node_id: {"name": node.node_name}}}}

    def allocation_explain(req):
        body = req.json() or {}
        index = body.get("index")
        services = node.indices.resolve(index) if index else \
            list(node.indices.indices.values())
        if not services:
            return 200, {"note": "no shards to explain"}
        svc = services[0]
        unassigned = svc.num_replicas > 0
        out = {
            "index": svc.name,
            "shard": int(body.get("shard", 0)),
            "primary": bool(body.get("primary", True)),
            "current_state": "started",
        }
        if not out["primary"] and unassigned:
            out.update({
                "current_state": "unassigned",
                "unassigned_info": {"reason": "REPLICA_ADDED",
                                    "last_allocation_status": "no_attempt"},
                "can_allocate": "no",
                "allocate_explanation":
                    "cannot allocate because allocation is not permitted to "
                    "any of the nodes",
                "node_allocation_decisions": [{
                    "node_name": node.node_name, "node_decision": "no",
                    "deciders": [{
                        "decider": "same_shard",
                        "decision": "NO",
                        "explanation":
                            "a copy of this shard is already allocated to "
                            "this node"}]}],
            })
        else:
            out.update({"can_remain_on_current_node": "yes",
                        "current_node": {"name": node.node_name,
                                         "id": node.node_id}})
        return 200, out

    rc.register("POST", "/_cluster/reroute", reroute)
    rc.register("GET", "/_cluster/allocation/explain", allocation_explain)
    rc.register("POST", "/_cluster/allocation/explain", allocation_explain)

    # ------------------------------------------------------------ monitoring
    def hot_threads(req):
        interval = float(req.param("interval", "50ms").rstrip("ms")) / 1000 \
            if str(req.param("interval", "50ms")).endswith("ms") else 0.05
        return 200, hot_threads_report(interval_s=min(interval, 0.5),
                                       node_name=node.node_name)

    rc.register("GET", "/_nodes/hot_threads", hot_threads)
    rc.register("GET", "/_nodes/{node_id}/hot_threads", hot_threads)

    def slowlog(req):
        return 200, {"search": node.search_slow_log.entries,
                     "indexing": node.indexing_slow_log.entries}

    rc.register("GET", "/_slowlog", slowlog)

    def deprecations(req):
        # reference: x-pack deprecation plugin runs checks over settings
        issues = []
        for svc in node.indices.indices.values():
            if svc.settings.get("index.frozen"):
                issues.append({
                    "level": "warning",
                    "message": f"index [{svc.name}] is frozen",
                    "details": "frozen indices are deprecated in favor of "
                               "searchable snapshots"})
        return 200, {"cluster_settings": [], "ml_settings": [],
                     "node_settings": [],
                     "index_settings": {svc.name: [] for svc in
                                        node.indices.indices.values()},
                     "deprecations": issues}

    rc.register("GET", "/_migration/deprecations", deprecations)

    # -------------------------------------------------------- point in time
    pits = {}

    def _reap_expired_pits() -> None:
        """Drop PITs past their keep_alive so abandoned readers are freed
        (reference: SearchService keepalive reaper thread)."""
        now = time.time()
        for pid in [p for p, e in pits.items() if e["expires"] <= now]:
            del pits[pid]

    def open_pit(req):
        _reap_expired_pits()
        index = req.params["index"]
        keep_alive = parse_time_value(req.param("keep_alive", "5m"),
                                      "keep_alive")
        pit_id = uuid.uuid4().hex
        readers = [(svc, svc.combined_reader())
                   for svc in node.indices.resolve(index)]
        pits[pit_id] = {"index": index, "readers": readers,
                        "keep_alive": keep_alive,
                        "expires": time.time() + keep_alive}
        return 200, {"id": pit_id}

    def close_pit(req):
        _reap_expired_pits()
        body = req.json() or {}
        pit_id = body.get("id")
        found = pits.pop(pit_id, None)
        return 200, {"succeeded": found is not None,
                     "num_freed": 1 if found else 0}

    rc.register("POST", "/{index}/_pit", open_pit)
    rc.register("DELETE", "/_pit", close_pit)

    # ----------------------------------------------------------- termvectors
    def termvectors(req):
        index = req.params["index"]
        doc_id = req.params.get("id")
        body = req.json() or {}
        svc = node.indices.get(index)
        source = None
        if doc_id is not None:
            got = node.get_doc(index, doc_id)
            if not got.get("found"):
                return 404, {"_index": index, "_id": doc_id, "found": False}
            source = got["_source"]
        else:
            source = (body.get("doc") or {})
        fields = body.get("fields")
        reader = svc.combined_reader()
        out_fields = {}
        for fname, value in source.items():
            if fields and fname not in fields:
                continue
            mapper = svc.mapper_service.get(fname)
            if mapper is None or not hasattr(mapper, "analyze"):
                continue
            tokens = mapper.analyze(str(value))
            terms: dict = {}
            for pos, t in enumerate(tokens):
                entry = terms.setdefault(t, {"term_freq": 0, "tokens": []})
                entry["term_freq"] += 1
                entry["tokens"].append({"position": pos})
            if body.get("term_statistics"):
                for t, entry in terms.items():
                    entry["doc_freq"] = reader.doc_freq(fname, t)
            out_fields[fname] = {
                "field_statistics": {
                    "sum_doc_freq": sum(e["term_freq"] for e in terms.values()),
                    "doc_count": reader.num_docs,
                    "sum_ttf": sum(e["term_freq"] for e in terms.values())},
                "terms": terms}
        return 200, {"_index": index, "_id": doc_id, "found": True,
                     "took": 0, "term_vectors": out_fields}

    rc.register("GET", "/{index}/_termvectors/{id}", termvectors)
    rc.register("POST", "/{index}/_termvectors/{id}", termvectors)
    rc.register("GET", "/{index}/_termvectors", termvectors)
    rc.register("POST", "/{index}/_termvectors", termvectors)

    # ------------------------------------------- segments/recovery/stores
    def segments(req):
        out = {}
        for svc in node.indices.resolve(req.params.get("index")):
            shards = {}
            for shard in svc.shards:
                reader = shard.engine.acquire_searcher()
                segs = []
                if reader is not None:
                    for i, view in enumerate(reader.views):
                        segs.append({
                            "segment": f"_{i}",
                            "num_docs": int(view.live_count),
                            "deleted_docs": int(view.segment.num_docs -
                                                view.live_count),
                            "committed": True, "search": True,
                            "compound": False})
                shards[str(shard.shard_id)] = [{"segments":
                                                {s["segment"]: s for s in segs}}]
            out[svc.name] = {"shards": shards}
        return 200, {"indices": out}

    def recovery(req):
        out = {}
        for svc in node.indices.resolve(req.params.get("index")):
            out[svc.name] = {"shards": [{
                "id": sh.shard_id, "type": "EMPTY_STORE", "stage": "DONE",
                "primary": True,
                "source": {}, "target": {"name": node.node_name},
                "index": {"size": {"total_in_bytes": 0},
                          "files": {"total": 0}},
            } for sh in svc.shards]}
        return 200, out

    def shard_stores(req):
        out = {}
        for svc in node.indices.resolve(req.params.get("index")):
            out[svc.name] = {"shards": {
                str(sh.shard_id): {"stores": [{
                    "allocation_id": uuid.uuid4().hex[:20],
                    "allocation": "primary",
                    node.node_id: {"name": node.node_name}}]}
                for sh in svc.shards}}
        return 200, {"indices": out}

    rc.register("GET", "/_segments", segments)
    rc.register("GET", "/{index}/_segments", segments)
    rc.register("GET", "/_recovery", recovery)
    rc.register("GET", "/{index}/_recovery", recovery)
    rc.register("GET", "/_shard_stores", shard_stores)
    rc.register("GET", "/{index}/_shard_stores", shard_stores)

    # --------------------------------------------------------- resolve index
    def resolve_index(req):
        import fnmatch
        expr = req.params["name"]
        indices = []
        aliases = {}
        for svc in node.indices.indices.values():
            if any(fnmatch.fnmatchcase(svc.name, p)
                   for p in expr.split(",")):
                indices.append({"name": svc.name,
                                "attributes": ["open"]})
            for alias in svc.aliases:
                if any(fnmatch.fnmatchcase(alias, p) for p in expr.split(",")):
                    aliases.setdefault(alias, []).append(svc.name)
        return 200, {"indices": indices,
                     "aliases": [{"name": a, "indices": sorted(ix)}
                                 for a, ix in sorted(aliases.items())],
                     "data_streams": []}

    rc.register("GET", "/_resolve/index/{name}", resolve_index)

    # ------------------------------------------------------------- _cat more
    from elasticsearch_tpu.rest.actions import _cat_table as _table

    def cat_allocation(req):
        n_shards = sum(s.num_shards for s in node.indices.indices.values())
        return _table(req, ["shards", "disk.indices", "host", "ip", "node"],
                      [[n_shards, "0b", "127.0.0.1", "127.0.0.1",
                        node.node_name]])

    def cat_templates(req):
        rows = [[name, str(t.get("index_patterns", [])), t.get("order", 0), ""]
                for name, t in node.templates.templates.items()]
        rows += [[name, str(t.get("index_patterns", [])),
                  t.get("priority", 0), "composable"]
                 for name, t in node.templates.index_templates.items()]
        return _table(req, ["name", "index_patterns", "order", "version"], rows)

    def cat_thread_pool(req):
        rows = [[node.node_name, name, s["active"], s["queue"], s["rejected"]]
                for name, s in node.thread_pool.stats().items()]
        return _table(req, ["node_name", "name", "active", "queue", "rejected"],
                      rows)

    def cat_plugins(req):
        rows = [[node.node_name, comp, __version__]
                for comp in ("sql", "eql", "ilm", "watcher", "transform",
                             "rollup", "ccr", "security", "ml")]
        rows += [[node.node_name, info["name"], info["version"]]
                 for info in node.plugins.info()]
        return _table(req, ["name", "component", "version"], rows)

    def cat_master(req):
        return _table(req, ["id", "host", "ip", "node"],
                      [[node.node_id, "127.0.0.1", "127.0.0.1",
                        node.node_name]])

    def cat_segments(req):
        rows = []
        for svc in node.indices.resolve(req.params.get("index")):
            for shard in svc.shards:
                reader = shard.engine.acquire_searcher()
                for i, view in enumerate(reader.views):
                    rows.append([svc.name, shard.shard_id, "p", f"_{i}",
                                 int(view.live_count),
                                 int(view.segment.num_docs - view.live_count)])
        return _table(req, ["index", "shard", "prirep", "segment",
                            "docs.count", "docs.deleted"], rows)

    def cat_recovery(req):
        rows = [[svc.name, sh.shard_id, "done", "empty_store", "100%"]
                for svc in node.indices.resolve(req.params.get("index"))
                for sh in svc.shards]
        return _table(req, ["index", "shard", "stage", "type", "files_percent"],
                      rows)

    def cat_pending_tasks(req):
        return _table(req, ["insertOrder", "timeInQueue", "priority", "source"],
                      [])

    def cat_repositories(req):
        rows = [[name, repo.type]
                for name, repo in node.snapshots.repositories.items()]
        return _table(req, ["id", "type"], rows)

    def cat_snapshots(req):
        repo = req.params.get("repository")
        rows = []
        for name, r in node.snapshots.repositories.items():
            if repo and name != repo:
                continue
            for snap in r.list_snapshots():
                rows.append([snap, "SUCCESS", name])
        return _table(req, ["id", "status", "repository"], rows)

    rc.register("GET", "/_cat/allocation", cat_allocation)
    rc.register("GET", "/_cat/templates", cat_templates)
    rc.register("GET", "/_cat/thread_pool", cat_thread_pool)
    rc.register("GET", "/_cat/plugins", cat_plugins)
    rc.register("GET", "/_cat/master", cat_master)
    rc.register("GET", "/_cat/segments", cat_segments)
    rc.register("GET", "/_cat/recovery", cat_recovery)
    rc.register("GET", "/_cat/pending_tasks", cat_pending_tasks)
    rc.register("GET", "/_cat/repositories", cat_repositories)
    rc.register("GET", "/_cat/snapshots", cat_snapshots)
    rc.register("GET", "/_cat/snapshots/{repository}", cat_snapshots)


def _flatten(obj: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in (obj or {}).items():
        if isinstance(v, dict):
            out.update(_flatten(v, prefix + k + "."))
        else:
            out[prefix + k] = v
    return out
