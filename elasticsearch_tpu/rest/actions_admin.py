"""Admin/observability REST surface: cluster settings, reroute, allocation
explain, hot threads, breakers, slow logs, deprecations, point-in-time,
termvectors, segments/recovery/shard_stores, resolve, extra _cat APIs.

Reference handlers: `rest/action/admin/cluster/*` (RestClusterUpdateSettings,
RestClusterRerouteAction, RestClusterAllocationExplainAction,
RestNodesHotThreadsAction), `rest/action/admin/indices/*` (segments,
recovery, shard stores, resolve), `rest/action/cat/*`, `action/termvectors`,
point-in-time (`RestOpenPointInTimeAction`), x-pack deprecation checks.
"""

from __future__ import annotations

import time
import uuid
from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.common.settings import parse_time_value
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.controller import RestController
from elasticsearch_tpu.version import __version__


def register_admin(rc: RestController, node: Node) -> None:
    # ------------------------------------------------------ cluster settings
    def get_cluster_settings(req):
        out = dict(node.cluster_settings)
        if req.bool_param("include_defaults"):
            out["defaults"] = {
                "cluster": {"name": node.cluster_name},
                "node": {"attr": dict(getattr(node, "node_attrs", {}) or {})},
            }
        return 200, out

    def put_cluster_settings(req):
        body = req.json() or {}
        applied = {"acknowledged": True, "persistent": {}, "transient": {}}
        changed = {}
        for scope in ("persistent", "transient"):
            for key, value in _flatten(body.get(scope, {})).items():
                if value is None:
                    node.cluster_settings[scope].pop(key, None)
                else:
                    node.cluster_settings[scope][key] = value
                applied[scope][key] = value
                changed[key] = value
        # dynamic remote-cluster reconfiguration
        # (RemoteClusterService.listenForUpdates)
        if any(k.startswith("cluster.remote.") for k in changed):
            node.remotes.apply_settings(changed)
        return 200, applied

    rc.register("GET", "/_cluster/settings", get_cluster_settings)
    rc.register("PUT", "/_cluster/settings", put_cluster_settings)

    # ------------------------------------------------- reroute + allocation
    def reroute(req):
        body = req.json() or {}
        # single-node facade: commands validate + ack (real moves happen in
        # the multi-node cluster layer, cluster/allocation.py)
        explanations = []
        for cmd in body.get("commands", []):
            kind = next(iter(cmd))
            if kind not in ("move", "cancel", "allocate_replica",
                            "allocate_stale_primary", "allocate_empty_primary"):
                raise IllegalArgumentError(f"unknown reroute command [{kind}]")
            params = dict(cmd[kind] or {})
            if kind == "cancel":
                params.setdefault("allow_primary", False)
            # ?explain=true: per-command allocation decision
            # (RoutingExplanations) — the facade reports why each command
            # cannot apply here, with the command-named decider
            explanations.append({
                "command": kind,
                "parameters": params,
                "decisions": [{
                    "decider": f"{kind}_allocation_command",
                    "decision": "NO",
                    "explanation": (
                        f"shard [{params.get('shard')}] in index "
                        f"[{params.get('index')}] is not assigned to node "
                        f"[{params.get('node')}] in this cluster state")}]})
        metrics = {m.strip() for m in
                   str(req.param("metric") or "").split(",") if m.strip()}
        state: dict = {"cluster_uuid": node.node_id}
        if not metrics or "nodes" in metrics or "_all" in metrics:
            state["nodes"] = {node.node_id: {"name": node.node_name}}
        if metrics and ("metadata" in metrics or "_all" in metrics):
            state["metadata"] = {
                "cluster_uuid": node.node_id,
                "indices": {svc.name: {"state": "close" if svc.closed
                                       else "open"}
                            for svc in node.indices.indices.values()}}
        out = {"acknowledged": True, "state": state}
        if req.bool_param("explain", False):
            out["explanations"] = explanations
        return 200, out

    def allocation_explain(req):
        """ClusterAllocationExplainAction: explain one shard's allocation.
        Explicit index/shard/primary explains that copy; an empty request
        picks the first UNASSIGNED shard or errors when none exist."""
        import time as _time

        body = req.json() or {}
        explicit = "index" in body

        def unassigned_entries():
            for svc in node.indices.indices.values():
                for sid in range(svc.num_shards):
                    for _ in range(svc.num_replicas):
                        yield svc, sid  # replicas can't assign single-node

        if not explicit:
            first = next(iter(unassigned_entries()), None)
            if first is None:
                raise IllegalArgumentError(
                    "unable to find any unassigned shards to explain "
                    "[ClusterAllocationExplainRequest] — specify the target "
                    "shard in the request")
            svc, sid = first
            out = {
                "index": svc.name, "shard": sid, "primary": False,
                "current_state": "unassigned",
                "unassigned_info": {
                    "reason": "INDEX_CREATED",
                    "at": _time.strftime("%Y-%m-%dT%H:%M:%S.000Z",
                                         _time.gmtime(svc.creation_date
                                                      / 1000)),
                    "last_allocation_status": "no_attempt"},
                "can_allocate": "no",
                "allocate_explanation":
                    "cannot allocate because allocation is not permitted "
                    "to any of the nodes",
                "node_allocation_decisions": [{
                    "node_id": node.node_id,
                    "node_name": node.node_name, "node_decision": "no",
                    "deciders": [{
                        "decider": "same_shard", "decision": "NO",
                        "explanation": "a copy of this shard is already "
                                       "allocated to this node"}]}],
            }
            if req.bool_param("include_disk_info", False):
                from elasticsearch_tpu.monitor.probes import fs_probe
                out["cluster_info"] = {
                    "nodes": {node.node_id: {
                        "node_name": node.node_name,
                        "least_available": fs_probe(node.indices.data_path),
                    }}}
            return 200, out

        svc = node.indices.get(str(body["index"]))
        primary = bool(body.get("primary", True))
        if not primary and svc.num_replicas > 0:
            # single-node: replica copies can never assign
            return 200, {
                "index": svc.name, "shard": int(body.get("shard", 0)),
                "primary": False, "current_state": "unassigned",
                "unassigned_info": {"reason": "REPLICA_ADDED",
                                    "last_allocation_status": "no_attempt"},
                "can_allocate": "no",
                "allocate_explanation":
                    "cannot allocate because allocation is not permitted to "
                    "any of the nodes",
                "node_allocation_decisions": [{
                    "node_id": node.node_id, "node_name": node.node_name,
                    "node_decision": "no",
                    "deciders": [{
                        "decider": "same_shard", "decision": "NO",
                        "explanation": "a copy of this shard is already "
                                       "allocated to this node"}]}],
            }
        out = {
            "index": svc.name,
            "shard": int(body.get("shard", 0)),
            "primary": primary,
            "current_state": "started",
            "current_node": {"name": node.node_name, "id": node.node_id,
                             "transport_address": "127.0.0.1:9300"},
            "can_remain_on_current_node": "yes",
            "can_rebalance_cluster": "yes",
            "can_rebalance_to_other_node": "no",
            "rebalance_explanation":
                "cannot rebalance as no target node exists that can both "
                "allocate this shard and improve the cluster balance",
        }
        return 200, out

    rc.register("POST", "/_cluster/reroute", reroute)
    rc.register("GET", "/_cluster/allocation/explain", allocation_explain)
    rc.register("POST", "/_cluster/allocation/explain", allocation_explain)

    # ------------------------------------------------------------ monitoring
    def hot_threads(req):
        interval = float(req.param("interval", "50ms").rstrip("ms")) / 1000 \
            if str(req.param("interval", "50ms")).endswith("ms") else 0.05
        top_n = req.int_param("threads", 3)
        return 200, node.hot_threads_api(interval, top_n=top_n)

    rc.register("GET", "/_nodes/hot_threads", hot_threads)
    rc.register("GET", "/_nodes/{node_id}/hot_threads", hot_threads)

    def node_traces(req):
        """`GET _nodes/traces` (telemetry): every node's bounded ring of
        completed traces, most recent first — coordinator traces on the
        coordinating node, shard segments on each data node, joined by
        trace_id."""
        return 200, node.traces_api(limit=req.int_param("size", 50))

    rc.register("GET", "/_nodes/traces", node_traces)
    rc.register("GET", "/_nodes/{node_id}/traces", node_traces)

    def slowlog(req):
        return 200, {"search": node.search_slow_log.entries,
                     "indexing": node.indexing_slow_log.entries}

    rc.register("GET", "/_slowlog", slowlog)

    def deprecations(req):
        # reference: x-pack deprecation plugin runs checks over settings
        issues = []
        for svc in node.indices.indices.values():
            if svc.settings.get("index.frozen"):
                issues.append({
                    "level": "warning",
                    "message": f"index [{svc.name}] is frozen",
                    "details": "frozen indices are deprecated in favor of "
                               "searchable snapshots"})
        return 200, {"cluster_settings": [], "ml_settings": [],
                     "node_settings": [],
                     "index_settings": {svc.name: [] for svc in
                                        node.indices.indices.values()},
                     "deprecations": issues}

    rc.register("GET", "/_migration/deprecations", deprecations)

    # -------------------------------------------------------- point in time
    pits = {}

    def _reap_expired_pits() -> None:
        """Drop PITs past their keep_alive so abandoned readers are freed
        (reference: SearchService keepalive reaper thread)."""
        now = time.time()
        for pid in [p for p, e in pits.items() if e["expires"] <= now]:
            del pits[pid]

    def open_pit(req):
        _reap_expired_pits()
        index = req.params["index"]
        keep_alive = parse_time_value(req.param("keep_alive", "5m"),
                                      "keep_alive")
        pit_id = uuid.uuid4().hex
        readers = [(svc, svc.combined_reader())
                   for svc in node.indices.resolve(index)]
        pits[pit_id] = {"index": index, "readers": readers,
                        "keep_alive": keep_alive,
                        "expires": time.time() + keep_alive}
        return 200, {"id": pit_id}

    def close_pit(req):
        _reap_expired_pits()
        body = req.json() or {}
        pit_id = body.get("id")
        found = pits.pop(pit_id, None)
        return 200, {"succeeded": found is not None,
                     "num_freed": 1 if found else 0}

    rc.register("POST", "/{index}/_pit", open_pit)
    rc.register("DELETE", "/_pit", close_pit)

    # ----------------------------------------------------------- termvectors
    def termvectors(req):
        body = req.json() or {}
        if req.param("realtime") is not None:
            body.setdefault("realtime", req.param("realtime"))
        if req.param("term_statistics") is not None:
            body.setdefault("term_statistics", req.param("term_statistics"))
        out = node.termvectors_api(req.params["index"],
                                   req.params.get("id"), body)
        return 200, out

    rc.register("GET", "/{index}/_termvectors/{id}", termvectors)
    rc.register("POST", "/{index}/_termvectors/{id}", termvectors)
    rc.register("GET", "/{index}/_termvectors", termvectors)
    rc.register("POST", "/{index}/_termvectors", termvectors)

    # ------------------------------------------- segments/recovery/stores
    def segments(req):
        from elasticsearch_tpu.common.errors import IndexNotFoundError
        expr = req.params.get("index")
        ignore = req.param("ignore_unavailable") in ("true", "", True)
        allow_no = req.param("allow_no_indices") not in ("false", False)
        services = node.indices.resolve(expr)
        if not services and not allow_no:
            raise IndexNotFoundError(f"no such index [{expr or '_all'}]")
        out = {}
        n = 0
        for svc in services:
            if svc.closed:
                if ignore:
                    continue
                raise IllegalArgumentError(
                    f"Trying to query 1 indices with 0 maximum shards: "
                    f"index [{svc.name}] is closed")
            shards = {}
            for shard in svc.shards:
                n += 1
                reader = shard.engine.acquire_searcher()
                segs = []
                if reader is not None:
                    for i, view in enumerate(reader.views):
                        segs.append({
                            "segment": f"_{i}",
                            "num_docs": int(view.live_count),
                            "deleted_docs": int(view.segment.num_docs -
                                                view.live_count),
                            "committed": True, "search": True,
                            "compound": False})
                shards[str(shard.shard_id)] = [{
                    "routing": {"state": "STARTED", "primary": True,
                                "node": node.node_id},
                    "num_committed_segments": len(segs),
                    "num_search_segments": len(segs),
                    "segments": {s["segment"]: s for s in segs}}]
            out[svc.name] = {"shards": shards}
        return 200, {"_shards": {"total": n, "successful": n, "failed": 0},
                     "indices": out}

    def recovery(req):
        """RecoveryResponse: per-shard provenance + file/translog progress
        (all recoveries here are DONE; type tracks IndexService
        .recovery_source, EXISTING_STORE for closed/reopened indices)."""
        import os as _os
        import time as _time

        detailed = req.param("detailed") in ("true", "", True)
        me = {"id": node.node_id, "host": "127.0.0.1", "ip": "127.0.0.1",
              "transport_address": "127.0.0.1:9300", "name": node.node_name}
        out = {}
        for svc in node.indices.resolve(req.params.get("index")):
            rsrc = getattr(svc, "recovery_source",
                           {"type": "EMPTY_STORE"})
            rtype = "EXISTING_STORE" if svc.closed else rsrc["type"]
            started = svc.creation_date
            iso = _time.strftime("%Y-%m-%dT%H:%M:%S.000Z",
                                 _time.gmtime(started / 1000))
            bstats = getattr(svc, "recovery_block_stats", None) or {}
            shards_out = []
            for sh in svc.shards:
                files = []
                size = 0
                base = sh.engine.path
                for root, dirs, fnames in _os.walk(base):
                    if "translog" in dirs:
                        dirs.remove("translog")
                    for f in fnames:
                        fp = _os.path.join(root, f)
                        try:
                            sz = _os.path.getsize(fp)
                        except OSError:
                            continue
                        files.append({"name": _os.path.relpath(fp, base),
                                      "length": sz, "recovered": sz})
                        size += sz
                from_snapshot = rtype == "SNAPSHOT"
                recovered_files = len(files) if from_snapshot else 0
                recovered_bytes = size if from_snapshot else 0
                source = dict(me)
                if from_snapshot:
                    source = {"repository": rsrc.get("repository"),
                              "snapshot": rsrc.get("snapshot"),
                              "version": rsrc.get("version"),
                              "index": rsrc.get("index")}
                elif rtype == "EMPTY_STORE":
                    source = {}
                bs = bstats.get(sh.shard_id)
                if bs:
                    # block-level restore: the unit of transfer is the
                    # content-addressed block, not the walked file tree
                    findex = {"total": int(bs.get("blocks_total", 0)),
                              "reused": int(bs.get("blocks_reused", 0)),
                              "recovered": int(bs.get("blocks_shipped", 0)),
                              "percent": "100.0%"}
                    size = int(bs.get("bytes_total", size))
                    recovered_bytes = int(bs.get("bytes_shipped", 0))
                else:
                    findex = {"total": len(files),
                              "reused": len(files) - recovered_files,
                              "recovered": recovered_files,
                              "percent": "100.0%"}
                if detailed:
                    findex["details"] = files if from_snapshot else []
                shard_out = {
                    "id": sh.shard_id, "type": rtype, "stage": "DONE",
                    "primary": True,
                    "start_time": iso, "start_time_in_millis": started,
                    "stop_time": iso, "stop_time_in_millis": started,
                    "total_time": "0ms", "total_time_in_millis": 0,
                    "source": source, "target": dict(me),
                    "index": {
                        "files": findex,
                        "size": {"total_in_bytes": size,
                                 "reused_in_bytes": size - recovered_bytes,
                                 "recovered_in_bytes": recovered_bytes,
                                 "percent": "100.0%"},
                        "source_throttle_time_in_millis": 0,
                        "target_throttle_time_in_millis": 0,
                        "total_time_in_millis": 0},
                    "translog": {"recovered": 0, "total": 0,
                                 "percent": "100.0%", "total_on_start": 0,
                                 "total_time_in_millis": 0},
                    "verify_index": {"check_index_time_in_millis": 0,
                                     "total_time_in_millis": 0},
                }
                if bs:
                    shard_out["blocks"] = {
                        "total": int(bs.get("blocks_total", 0)),
                        "reused": int(bs.get("blocks_reused", 0)),
                        "shipped": int(bs.get("blocks_shipped", 0)),
                        "bytes_total": int(bs.get("bytes_total", 0)),
                        "bytes_shipped": int(bs.get("bytes_shipped", 0)),
                        "segments": int(bs.get("segments", 0)),
                        "cache_blocks": int(bs.get("cache_blocks", 0)),
                        "ivf_fields": list(bs.get("ivf_fields", []))}
                shards_out.append(shard_out)
            out[svc.name] = {"shards": shards_out}
        return 200, out

    def shard_stores(req):
        from elasticsearch_tpu.common.errors import IndexNotFoundError
        expr = req.params.get("index")
        services = node.indices.resolve(expr)
        if not services and req.param("allow_no_indices") in ("false", False):
            raise IndexNotFoundError(f"no such index [{expr or '_all'}]")
        out = {}
        for svc in services:
            out[svc.name] = {"shards": {
                str(sh.shard_id): {"stores": [{
                    "allocation_id": uuid.uuid4().hex[:20],
                    "allocation": "primary",
                    node.node_id: {"name": node.node_name}}]}
                for sh in svc.shards}}
        return 200, {"indices": out}

    rc.register("GET", "/_segments", segments)
    rc.register("GET", "/{index}/_segments", segments)
    rc.register("GET", "/_recovery", recovery)
    rc.register("GET", "/{index}/_recovery", recovery)
    rc.register("GET", "/_shard_stores", shard_stores)
    rc.register("GET", "/{index}/_shard_stores", shard_stores)

    # --------------------------------------------------------- resolve index
    def resolve_index(req):
        import fnmatch
        expr = req.params["name"]
        indices = []
        aliases = {}
        for svc in node.indices.indices.values():
            if any(fnmatch.fnmatchcase(svc.name, p)
                   for p in expr.split(",")):
                indices.append({"name": svc.name,
                                "attributes": ["open"]})
            for alias in svc.aliases:
                if any(fnmatch.fnmatchcase(alias, p) for p in expr.split(",")):
                    aliases.setdefault(alias, []).append(svc.name)
        return 200, {"indices": indices,
                     "aliases": [{"name": a, "indices": sorted(ix)}
                                 for a, ix in sorted(aliases.items())],
                     "data_streams": []}

    rc.register("GET", "/_resolve/index/{name}", resolve_index)

    # ------------------------------------------------------------- _cat more
    from elasticsearch_tpu.rest.cat import (
        Bytes, Col, Millis, dir_size, fmt_iso_millis, render as _render,
    )

    _ALLOC_COLS = [
        Col("shards", "s", "number of shards on node", right=True),
        Col("disk.indices", "di,diskIndices", "disk used by ES indices", right=True),
        Col("disk.used", "du,diskUsed", "disk used (total, not just ES)", right=True),
        Col("disk.avail", "da,diskAvail", "disk available", right=True),
        Col("disk.total", "dt,diskTotal", "total capacity of all volumes", right=True),
        Col("disk.percent", "dp,diskPercent", "percent disk used", right=True),
        Col("host", "h", "host of node"),
        Col("ip", "", "ip of node"),
        Col("node", "n", "name of node"),
    ]

    def cat_allocation(req):
        node_expr = req.params.get("node_id")
        if node_expr and node_expr not in ("_master", "*", "_all"):
            parts = [p.strip() for p in node_expr.split(",")]
            if not any("*" in p or p in (node.node_name, node.node_id)
                       or p.startswith("_") for p in parts):
                return _render(req, _ALLOC_COLS, [])
        import shutil as _sh
        du = _sh.disk_usage(node.data_path)
        n_shards = sum(s.num_shards for s in node.indices.indices.values())
        disk_indices = sum(dir_size(s.engine.path)
                           for svc in node.indices.indices.values()
                           for s in svc.shards)
        row = [n_shards, Bytes(disk_indices), Bytes(du.used), Bytes(du.free),
               Bytes(du.total), int(du.used / du.total * 100),
               "127.0.0.1", "127.0.0.1", node.node_name]
        return _render(req, _ALLOC_COLS, [row])

    _TEMPLATES_COLS = [
        Col("name", "n", "template name"),
        Col("index_patterns", "t", "template index patterns"),
        Col("order", "o,p", "template application order/priority number", right=True),
        Col("version", "v", "version", right=True),
    ]

    def cat_templates(req):
        import fnmatch as _fn
        name_filter = req.params.get("name")

        def _keep(n):
            return (not name_filter or any(
                _fn.fnmatch(n, p.strip()) for p in name_filter.split(",")))

        def _pats(t):
            pats = t.get("index_patterns", [])
            if isinstance(pats, str):
                pats = [pats]
            return "[" + ", ".join(pats) + "]"
        rows = [[name, _pats(t), t.get("order", 0), t.get("version", "")]
                for name, t in node.templates.templates.items() if _keep(name)]
        rows += [[name, _pats(t), t.get("priority", 0), t.get("version", "")]
                 for name, t in node.templates.index_templates.items()
                 if _keep(name)]
        rows.sort(key=lambda r: r[0])
        return _render(req, _TEMPLATES_COLS, rows)

    _THREAD_POOL_COLS = [
        Col("node_name", "nn", "node name"),
        Col("node_id", "id", "persistent node id", default=False),
        Col("ephemeral_node_id", "eid", "ephemeral node id", default=False),
        Col("pid", "p", "process id", right=True, default=False),
        Col("host", "h", "host name", default=False),
        Col("ip", "i", "ip address", default=False),
        Col("port", "po", "bound transport port", right=True, default=False),
        Col("name", "n", "thread pool name"),
        Col("type", "t", "thread pool type", default=False),
        Col("active", "a", "number of active threads", right=True),
        Col("pool_size", "psz", "number of threads", right=True, default=False),
        Col("queue", "q", "number of tasks currently in queue", right=True),
        Col("queue_size", "qs", "maximum number of tasks permitted in queue", right=True, default=False),
        Col("rejected", "r", "number of rejected tasks", right=True),
        Col("largest", "l", "highest number of seen active threads", right=True, default=False),
        Col("completed", "c", "number of completed tasks", right=True, default=False),
        Col("core", "cr", "core number of threads in a scaling thread pool", right=True, default=False),
        Col("max", "mx", "maximum number of threads in a scaling thread pool", right=True, default=False),
        Col("size", "sz", "number of threads in a fixed thread pool", right=True, default=False),
        Col("keep_alive", "ka", "thread keep alive time", default=False),
    ]

    def cat_thread_pool(req):
        pool_filter = (req.params.get("pools")
                       or req.param("thread_pool_patterns"))
        rows = node.cat_threadpool_rows_api(pool_filter)
        return _render(req, _THREAD_POOL_COLS, rows)

    _PLUGINS_COLS = [
        Col("id", "", "unique node id", default=False),
        Col("name", "n", "node name"),
        Col("component", "c", "component"),
        Col("version", "v", "component version"),
        Col("description", "d", "plugin details", default=False),
    ]

    def cat_plugins(req):
        rows = [[node.node_id, node.node_name, comp, __version__,
                 f"built-in {comp} module"]
                for comp in ("sql", "eql", "ilm", "watcher", "transform",
                             "rollup", "ccr", "security", "ml")]
        rows += [[node.node_id, node.node_name, info["name"], info["version"],
                  info.get("description", "")]
                 for info in node.plugins.info()]
        return _render(req, _PLUGINS_COLS, rows)

    _MASTER_COLS = [
        Col("id", "", "node id"),
        Col("host", "h", "host name"),
        Col("ip", "", "ip address"),
        Col("node", "n", "node name"),
    ]

    def cat_master(req):
        return _render(req, _MASTER_COLS,
                       [[node.node_id, "127.0.0.1", "127.0.0.1",
                         node.node_name]])

    _SEGMENTS_COLS = [
        Col("index", "i,idx", "index name"),
        Col("shard", "s,sh", "shard name", right=True),
        Col("prirep", "p,pr,primaryOrReplica", "primary or replica"),
        Col("ip", "", "ip of node where it lives"),
        Col("id", "", "unique id of node where it lives", default=False),
        Col("segment", "seg", "segment name"),
        Col("generation", "g,gen", "segment generation", right=True),
        Col("docs.count", "dc,docsCount", "number of docs in segment", right=True),
        Col("docs.deleted", "dd,docsDeleted", "number of deleted docs in segment", right=True),
        Col("size", "si", "segment size in bytes", right=True),
        Col("size.memory", "sm,sizeMemory", "segment memory in bytes", right=True),
        Col("committed", "ic,isCommitted", "is segment committed"),
        Col("searchable", "is,isSearchable", "is segment searched"),
        Col("version", "v,ver", "version"),
        Col("compound", "ico,isCompound", "is segment compound"),
    ]

    def cat_segments(req):
        from elasticsearch_tpu.common.errors import IndexClosedError
        rows = []
        for svc in node.indices.resolve(req.params.get("index"),
                                        expand_hidden=True):
            if svc.closed:
                raise IndexClosedError(f"closed index [{svc.name}]",
                                       index=svc.name)
            for shard in svc.shards:
                reader = shard.engine.acquire_searcher()
                for i, view in enumerate(reader.views):
                    live = int(view.live_count)
                    deleted = int(view.segment.num_docs - view.live_count)
                    size = max(view.segment.num_docs * 64, 1)
                    rows.append([svc.name, shard.shard_id, "p", "127.0.0.1",
                                 node.node_id, f"_{i}", i, live, deleted,
                                 Bytes(size), 0, "true", "true",
                                 __version__, "false"])
        return _render(req, _SEGMENTS_COLS, rows)

    _RECOVERY_COLS = [
        Col("index", "i,idx", "index name"),
        Col("shard", "s,sh", "shard name", right=True),
        Col("start_time", "start", "recovery start time", default=False),
        Col("start_time_millis", "start_millis", "recovery start time in epoch milliseconds", right=True, default=False),
        Col("stop_time", "stop", "recovery stop time", default=False),
        Col("stop_time_millis", "stop_millis", "recovery stop time in epoch milliseconds", right=True, default=False),
        Col("time", "t,ti", "recovery time", right=True),
        Col("type", "ty", "recovery type"),
        Col("stage", "st", "recovery stage"),
        Col("source_host", "shost", "source host"),
        Col("source_node", "snode", "source node name"),
        Col("target_host", "thost", "target host"),
        Col("target_node", "tnode", "target node name"),
        Col("repository", "rep", "repository"),
        Col("snapshot", "snap", "snapshot"),
        Col("files", "f", "number of files to recover", right=True),
        Col("files_recovered", "fr", "files recovered", right=True),
        Col("files_percent", "fp", "percent of files recovered", right=True),
        Col("files_total", "tf", "total number of files", right=True),
        Col("bytes", "b", "number of bytes to recover", right=True),
        Col("bytes_recovered", "br", "bytes recovered", right=True),
        Col("bytes_percent", "bp", "percent of bytes recovered", right=True),
        Col("bytes_total", "tb", "total number of bytes", right=True),
        Col("translog_ops", "to", "number of translog ops to recover", right=True),
        Col("translog_ops_recovered", "tor", "translog ops recovered", right=True),
        Col("translog_ops_percent", "top", "percent of translog ops recovered", right=True),
        Col("blocks_total", "blt", "total content-addressed blocks in the shard manifest", right=True),
        Col("blocks_reused", "blr", "blocks already held (cache or repository dedup)", right=True),
        Col("blocks_shipped", "bls", "blocks transferred", right=True),
        Col("throttle_time", "tht", "time spent waiting in retry backoff", right=True, default=False),
    ]

    def cat_recovery(req):
        rows = []
        for svc in node.indices.resolve(req.params.get("index"),
                                        expand_hidden=True):
            bstats = getattr(svc, "recovery_block_stats", None) or {}
            rsrc = getattr(svc, "recovery_source", None) or {}
            for sh in svc.shards:
                import os as _os
                # a shard with committed state recovers from its own files
                # (existing_store); a brand-new one from empty_store
                has_commit = _os.path.exists(
                    _os.path.join(sh.engine.path, "commit.bin")) \
                    or sh.engine.local_checkpoint >= 0
                bs = bstats.get(sh.shard_id) or {}
                rtype = "snapshot" if bs or rsrc.get("type") == "SNAPSHOT" \
                    else ("existing_store" if has_commit else "empty_store")
                rows.append([
                    svc.name, sh.shard_id,
                    _fmt_time_of(svc.creation_date),
                    svc.creation_date,
                    _fmt_time_of(svc.creation_date),
                    svc.creation_date,
                    Millis(1),
                    rtype,
                    "done",
                    "n/a", "n/a", "127.0.0.1", node.node_name,
                    rsrc.get("repository", "n/a") if bs else "n/a",
                    rsrc.get("snapshot", "n/a") if bs else "n/a",
                    0, 0, "100.0%", 0,
                    Bytes(int(bs.get("bytes_total", 0))),
                    Bytes(int(bs.get("bytes_shipped", 0))),
                    "100.0%",
                    Bytes(int(bs.get("bytes_total", 0))),
                    0, 0, "100.0%",
                    int(bs.get("blocks_total", 0)),
                    int(bs.get("blocks_reused", 0)),
                    int(bs.get("blocks_shipped", 0)),
                    Millis(int(bs.get("throttle_ms", 0)))])
        return _render(req, _RECOVERY_COLS, rows)

    _fmt_time_of = fmt_iso_millis

    _PENDING_COLS = [
        Col("insertOrder", "o", "task insertion order", right=True),
        Col("timeInQueue", "t", "how long task has been in queue", right=True),
        Col("priority", "p", "task priority"),
        Col("source", "s", "task source"),
    ]

    def cat_pending_tasks(req):
        return _render(req, _PENDING_COLS, [])

    _REPO_COLS = [
        Col("id", "id,repoId", "unique repository id"),
        Col("type", "t", "repository type"),
    ]

    def cat_repositories(req):
        rows = [[name, repo.type]
                for name, repo in node.snapshots.repositories.items()]
        rows.sort(key=lambda r: r[0])
        return _render(req, _REPO_COLS, rows)

    _SNAPSHOTS_COLS = [
        Col("id", "snapshot", "unique snapshot"),
        Col("repository", "re,repo", "repository name"),
        Col("status", "s", "snapshot name"),
        Col("start_epoch", "ste,startEpoch", "start time in seconds since 1970-01-01 00:00:00", right=True),
        Col("start_time", "sti,startTime", "start time in HH:MM:SS"),
        Col("end_epoch", "ete,endEpoch", "end time in seconds since 1970-01-01 00:00:00", right=True),
        Col("end_time", "eti,endTime", "end time in HH:MM:SS"),
        Col("duration", "dur", "duration", right=True),
        Col("indices", "i", "number of indices", right=True),
        Col("successful_shards", "ss", "number of successful shards", right=True),
        Col("failed_shards", "fs", "number of failed shards", right=True),
        Col("total_shards", "ts", "number of total shards", right=True),
        Col("reason", "r", "reason for failures", default=False),
    ]

    def cat_snapshots(req):
        repo = req.params.get("repository")
        rows = []
        for name, r in node.snapshots.repositories.items():
            if repo and name != repo and not _fn_match(repo, name):
                continue
            for snap in sorted(r.list_snapshots()):
                try:
                    m = r.get_manifest(snap)
                except Exception:
                    m = {}
                indices = m.get("indices", {}) or {}
                sh = m.get("shards", {}) or {}
                shards = sh.get("total") or sum(
                    len(e.get("shards") or {}) or 1 if isinstance(e, dict)
                    else 1 for e in indices.values()) or len(indices)
                start = int(m.get("start_time_in_millis")
                            or time.time() * 1000)
                end = int(m.get("end_time_in_millis") or start)
                rows.append([
                    snap, name, m.get("state", "SUCCESS"),
                    start // 1000,
                    time.strftime("%H:%M:%S", time.gmtime(start / 1000)),
                    end // 1000,
                    time.strftime("%H:%M:%S", time.gmtime(end / 1000)),
                    Millis(end - start), len(indices),
                    sh.get("successful", shards), sh.get("failed", 0),
                    shards, ""])
        return _render(req, _SNAPSHOTS_COLS, rows)

    def _fn_match(pattern, name):
        import fnmatch as _fn
        return any(_fn.fnmatch(name, p.strip()) for p in pattern.split(","))

    _NODEATTRS_COLS = [
        Col("node", "name", "node name"),
        Col("id", "nodeId", "unique node id", default=False),
        Col("pid", "p", "process id", right=True, default=False),
        Col("host", "h", "host name"),
        Col("ip", "i", "ip address"),
        Col("port", "po", "bound transport port", right=True, default=False),
        Col("attr", "attr.name", "attribute description"),
        Col("value", "attr.value", "attribute value"),
    ]

    def cat_nodeattrs(req):
        return _render(req, _NODEATTRS_COLS, node.cat_nodeattrs_rows_api())

    _FIELDDATA_COLS = [
        Col("id", "", "node id"),
        Col("host", "h", "host name"),
        Col("ip", "", "ip address"),
        Col("node", "n", "node name"),
        Col("field", "f", "field name"),
        Col("size", "s", "field data usage", right=True),
    ]

    def cat_fielddata(req):
        field_filter = req.params.get("fields") or req.param("fields")
        rows = [r[:5] + [Bytes(r[5])]
                for r in node.cat_fielddata_rows_api(field_filter)]
        return _render(req, _FIELDDATA_COLS, rows)

    _TASKS_COLS = [
        Col("action", "ac", "task action"),
        Col("task_id", "ti", "unique task id"),
        Col("parent_task_id", "pti", "parent task id"),
        Col("type", "ty", "task type"),
        Col("start_time", "start", "start time in ms", right=True),
        Col("timestamp", "ts,hms,hhmmss", "start time in HH:MM:SS"),
        Col("running_time_ns", "", "running time ns", right=True, default=False),
        Col("running_time", "time", "running time", right=True),
        Col("ip", "i", "ip address"),
        Col("node", "n", "node name"),
        Col("description", "desc", "task action", default=False),
    ]

    def cat_tasks(req):
        detailed = req.param("detailed") in ("true", "", True)
        rows = []
        for r in node.cat_tasks_rows_api():
            action, task_id, parent, ttype, start_ms, run_ns, ip, name, desc = r
            rows.append([action, task_id, parent, ttype, start_ms,
                         time.strftime("%H:%M:%S", time.gmtime(start_ms / 1000)),
                         run_ns, Millis(run_ns / 1e6), ip, name, desc])
        cols = _TASKS_COLS
        if detailed:
            cols = [Col(c.name, ",".join(c.aliases), c.desc, c.right,
                        True if c.name == "description" else c.default)
                    for c in _TASKS_COLS]
        return _render(req, cols, rows)

    rc.register("GET", "/_cat/allocation", cat_allocation)
    rc.register("GET", "/_cat/allocation/{node_id}", cat_allocation)
    rc.register("GET", "/_cat/templates", cat_templates)
    rc.register("GET", "/_cat/templates/{name}", cat_templates)
    rc.register("GET", "/_cat/thread_pool", cat_thread_pool)
    rc.register("GET", "/_cat/thread_pool/{pools}", cat_thread_pool)
    rc.register("GET", "/_cat/plugins", cat_plugins)
    rc.register("GET", "/_cat/master", cat_master)
    rc.register("GET", "/_cat/segments", cat_segments)
    rc.register("GET", "/_cat/segments/{index}", cat_segments)
    rc.register("GET", "/_cat/recovery", cat_recovery)
    rc.register("GET", "/_cat/recovery/{index}", cat_recovery)
    def cluster_pending_tasks(req):
        """GET /_cluster/pending_tasks (MasterService.pendingTasks): the
        batching queue's snapshot; single-node updates apply inline so
        the queue is empty here, the cluster adapter overrides with the
        coordinator's live queue."""
        return 200, {"tasks": node.pending_cluster_tasks()}

    rc.register("GET", "/_cluster/pending_tasks", cluster_pending_tasks)
    rc.register("GET", "/_cat/pending_tasks", cat_pending_tasks)
    rc.register("GET", "/_cat/repositories", cat_repositories)
    rc.register("GET", "/_cat/snapshots", cat_snapshots)
    rc.register("GET", "/_cat/snapshots/{repository}", cat_snapshots)
    rc.register("GET", "/_cat/nodeattrs", cat_nodeattrs)
    rc.register("GET", "/_cat/fielddata", cat_fielddata)
    rc.register("GET", "/_cat/fielddata/{fields}", cat_fielddata)
    rc.register("GET", "/_cat/tasks", cat_tasks)


def _flatten(obj: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in (obj or {}).items():
        if isinstance(v, dict):
            out.update(_flatten(v, prefix + k + "."))
        else:
            out[prefix + k] = v
    return out
