"""REST handlers for scripting: stored scripts + search templates.

Reference handlers: `rest/action/admin/cluster/RestPutStoredScriptAction`
(PUT `_scripts/{id}`), `RestGetStoredScriptAction`,
`RestDeleteStoredScriptAction`, and lang-mustache's
`RestSearchTemplateAction` (`_search/template`), `RestRenderSearchTemplateAction`
(`_render/template`), `RestMultiSearchTemplateAction` (`_msearch/template`).
"""

from __future__ import annotations

import json

from elasticsearch_tpu.common.errors import ParsingError, SearchEngineError
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.controller import RestController


def register_script(rc: RestController, node: Node) -> None:
    # ------------------------------------------------------- stored scripts
    def put_script(req):
        node.scripts.put_stored(req.params["id"], req.json() or {})
        return 200, {"acknowledged": True}

    def get_script(req):
        script = node.scripts.get_stored(req.params["id"])
        return 200, {"_id": req.params["id"], "found": True,
                     "script": script.to_dict()}

    def delete_script(req):
        node.scripts.delete_stored(req.params["id"])
        return 200, {"acknowledged": True}

    rc.register("PUT", "/_scripts/{id}", put_script)
    rc.register("POST", "/_scripts/{id}", put_script)
    rc.register("GET", "/_scripts/{id}", get_script)
    rc.register("DELETE", "/_scripts/{id}", delete_script)

    # ------------------------------------------------------ search templates
    def search_template(req):
        body = req.json() or {}
        rendered = node.scripts.render_template(body)
        index = req.params.get("index")
        if body.get("explain"):
            rendered["explain"] = True
        result = node.search(index, rendered)
        return 200, result

    def render_template(req):
        body = req.json() or {}
        if "id" in req.params and "id" not in body:
            body["id"] = req.params["id"]
        return 200, {"template_output": node.scripts.render_template(body)}

    def msearch_template(req):
        # NDJSON body: alternating header / template lines, like _msearch
        # (reference: RestMultiSearchTemplateAction).
        lines = req.ndjson()
        if len(lines) % 2 != 0:
            raise ParsingError("_msearch/template expects header/body line pairs")
        responses = []
        for i in range(0, len(lines), 2):
            header = lines[i]
            tmpl = lines[i + 1]
            index = header.get("index") or req.params.get("index")
            try:
                rendered = node.scripts.render_template(tmpl)
                responses.append({**node.search(index, rendered), "status": 200})
            except SearchEngineError as e:  # per-item failure, like _msearch
                responses.append({"error": e.to_dict(), "status": e.status})
        return 200, {"responses": responses}

    rc.register("GET", "/_search/template", search_template)
    rc.register("POST", "/_search/template", search_template)
    rc.register("GET", "/{index}/_search/template", search_template)
    rc.register("POST", "/{index}/_search/template", search_template)
    rc.register("GET", "/_render/template", render_template)
    rc.register("POST", "/_render/template", render_template)
    rc.register("GET", "/_render/template/{id}", render_template)
    rc.register("POST", "/_render/template/{id}", render_template)
    rc.register("GET", "/_msearch/template", msearch_template)
    rc.register("POST", "/_msearch/template", msearch_template)
    rc.register("GET", "/{index}/_msearch/template", msearch_template)
    rc.register("POST", "/{index}/_msearch/template", msearch_template)
