"""REST parity batch: routes the reference's YAML behavior suites exercise
that were missing from the surface (round-4 conformance burn-down).

Each handler names its reference action class; shapes follow the
`rest-api-spec/test/` contract the conformance harness replays.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError, ResourceNotFoundError, SnapshotMissingError,
)

if TYPE_CHECKING:
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.controller import RestController


def normalize_template_settings(settings: dict) -> dict:
    """Template settings render nested under "index" with STRING leaf values
    (`Settings#toXContent` of an index-scoped Settings object):
    {"number_of_shards": 1} -> {"index": {"number_of_shards": "1"}}."""
    nested: dict = {}
    for key, value in (settings or {}).items():
        parts = key.split(".")
        if parts[0] != "index":
            parts = ["index"] + parts
        cur = nested
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = _stringify(value)
    return nested


def _stringify(value):
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return str(value)
    if isinstance(value, dict):
        return {k: _stringify(v) for k, v in value.items()}
    return value


def register_conf(rc: "RestController", node: "Node") -> None:
    # --------------------------------------------------------- search_shards
    def search_shards(req):
        """TransportClusterSearchShardsAction: which shards a search hits,
        plus alias filters resolved at the coordinator."""
        expr = req.params.get("index")
        services = node.indices.resolve_open(expr)
        requested = [a.strip() for a in str(expr or "").split(",") if a]
        shards = []
        indices_out = {}
        for svc in services:
            for shard in svc.shards:
                shards.append([{
                    "index": svc.name, "shard": shard.shard_id,
                    "node": node.node_id, "primary": True,
                    "state": "STARTED",
                    "allocation_id": {"id": f"{svc.name}-{shard.shard_id}"},
                    "relocating_node": None}])
            from elasticsearch_tpu.common.patterns import (
                matches_csv_patterns)
            matching = [a for a in svc.aliases
                        if any(matches_csv_patterns(a, r)
                               for r in requested)]
            entry: dict = {}
            if matching:
                entry["aliases"] = sorted(matching)
                direct = any(matches_csv_patterns(svc.name, r)
                             for r in requested)
                unfiltered = any(not (svc.aliases[a] or {}).get("filter")
                                 for a in matching)
                filters = [] if direct or unfiltered else [
                    _normalize_filter(svc.aliases[a]["filter"])
                    for a in matching if (svc.aliases[a] or {}).get("filter")]
                if len(filters) == 1:
                    entry["filter"] = filters[0]
                elif filters:
                    entry["filter"] = {"bool": {"should": filters,
                                                "boost": 1.0}}
            indices_out[svc.name] = entry
        return 200, {"nodes": {node.node_id: {"name": node.node_name}},
                     "shards": shards, "indices": indices_out}

    def _normalize_filter(f: dict) -> dict:
        # term filters render in object form with an explicit boost
        # (QueryBuilder#toXContent): {"term": {"f": "v"}} ->
        # {"term": {"f": {"value": "v", "boost": 1.0}}}
        if not isinstance(f, dict):
            return f
        if "term" in f and isinstance(f["term"], dict):
            out = {}
            for field, v in f["term"].items():
                if isinstance(v, dict):
                    v = {"boost": 1.0, **v}
                else:
                    v = {"value": v, "boost": 1.0}
                out[field] = v
            return {"term": out}
        return f

    rc.register("GET", "/_search_shards", search_shards)
    rc.register("POST", "/_search_shards", search_shards)
    rc.register("GET", "/{index}/_search_shards", search_shards)
    rc.register("POST", "/{index}/_search_shards", search_shards)

    # -------------------------------------------------------- snapshot.status
    def snapshot_status(req):
        """TransportSnapshotsStatusAction: per-snapshot file stats."""
        repo_name = req.params["repo"]
        repo = node.snapshots.get_repository(repo_name)
        expr = req.params.get("snapshot")
        if expr is None:
            return 200, {"snapshots": []}  # no in-progress snapshots
        ignore = str(req.param("ignore_unavailable", "false")) in ("true", "")
        out = []
        for name in str(expr).split(","):
            try:
                m = repo.get_manifest(name)
            except ResourceNotFoundError:
                if ignore:
                    continue
                raise SnapshotMissingError(
                    f"[{repo_name}:{name}] is missing")
            file_count = 0
            size_bytes = 0
            shards_out = {}
            for iname, ientry in (m.get("indices") or {}).items():
                istats = {}
                for sid, sentry in (ientry.get("shards") or {}).items():
                    blocks = sentry.get("blocks")
                    if blocks is not None:
                        # block-manifest shard: sizes come from the
                        # manifest entries — no blob reads at all
                        uniq = {e["digest"]: int(e["size"]) for e in blocks}
                        fc = len(uniq)
                        sz = sum(uniq.values())
                    else:
                        files = sentry.get("files") or {}
                        fc = len(files)
                        sz = 0
                        for digest in files.values():
                            try:
                                sz += len(repo.get_bytes(digest))
                            except Exception:
                                pass
                    file_count += fc
                    size_bytes += sz
                    istats[sid] = {
                        "stage": "DONE",
                        "stats": {"incremental": {"file_count": fc,
                                                  "size_in_bytes": sz},
                                  "total": {"file_count": fc,
                                            "size_in_bytes": sz}}}
                shards_out[iname] = {"shards": istats}
            stats = {"incremental": {"file_count": file_count,
                                     "size_in_bytes": size_bytes},
                     "total": {"file_count": file_count,
                               "size_in_bytes": size_bytes},
                     "start_time_in_millis": m.get("start_time_in_millis"),
                     "time_in_millis": max(
                         (m.get("end_time_in_millis") or 0)
                         - (m.get("start_time_in_millis") or 0), 0)}
            out.append({"snapshot": name, "repository": repo_name,
                        "uuid": name, "state": m.get("state", "SUCCESS"),
                        "include_global_state": m.get("include_global_state",
                                                      True),
                        "shards_stats": {
                            "initializing": 0, "started": 0, "finalizing": 0,
                            "done": m.get("shards", {}).get("successful", 0),
                            "failed": m.get("shards", {}).get("failed", 0),
                            "total": m.get("shards", {}).get("total", 0)},
                        "stats": stats, "indices": shards_out})
        return 200, {"snapshots": out}

    rc.register("GET", "/_snapshot/{repo}/{snapshot}/_status", snapshot_status)
    rc.register("GET", "/_snapshot/{repo}/_status", snapshot_status)

    def cleanup_repository(req):
        node.snapshots.get_repository(req.params["repo"])  # 404 if missing
        return 200, {"results": {"deleted_bytes": 0, "deleted_blobs": 0}}

    rc.register("POST", "/_snapshot/{repo}/_cleanup", cleanup_repository)

    # --------------------------------------------- script contexts/languages
    def script_context(req):
        contexts = []
        for name in ("aggregation_selector", "aggs", "bucket_aggregation",
                     "field", "filter", "ingest", "number_sort", "processor",
                     "score", "script_heuristic", "similarity", "string_sort",
                     "template", "terms_set", "update"):
            contexts.append({"name": name, "methods": [
                {"name": "execute", "return_type": "java.lang.Object",
                 "params": []},
                {"name": "getParams", "return_type": "java.util.Map",
                 "params": []}]})
        return 200, {"contexts": contexts}

    def script_languages(req):
        return 200, {
            "types_allowed": ["inline", "stored"],
            "language_contexts": [
                {"language": "expression", "contexts": ["score"]},
                {"language": "mustache", "contexts": ["template"]},
                {"language": "painless", "contexts": [
                    "aggs", "field", "filter", "ingest", "score", "update"]},
            ]}

    rc.register("GET", "/_script_context", script_context)
    rc.register("GET", "/_script_language", script_languages)

    # ------------------------------------------------- nodes.stats/{metrics}
    STATS_METRICS = ("indices", "os", "process", "jvm", "thread_pool", "fs",
                     "transport", "http", "breaker", "breakers", "script",
                     "discovery", "ingest", "adaptive_selection",
                     "indexing_pressure", "_all")

    def nodes_stats_metrics(req):
        metrics = [m.strip()
                   for m in str(req.params.get("metrics", "")).split(",")
                   if m.strip()]
        for m in metrics:
            if m not in STATS_METRICS:
                import difflib
                hint = difflib.get_close_matches(m, STATS_METRICS, n=1)
                suffix = f" -> did you mean [{hint[0]}]?" if hint else ""
                raise IllegalArgumentError(
                    f"request [/_nodes/stats/{','.join(metrics)}] contains "
                    f"unrecognized metric: [{m}]{suffix}")
        from elasticsearch_tpu.common.settings import setting_bool
        full = node.nodes_stats_api(
            level=req.param("level"),
            include_segment_file_sizes=setting_bool(
                req.param("include_segment_file_sizes")))
        if metrics and "_all" not in metrics:
            keep = set(metrics) | {"name", "roles"}
            if "breaker" in keep:
                keep.add("breakers")
            full["nodes"] = {nid: {k: v for k, v in sec.items()
                                   if k in keep or k == "name"
                                   or (k == "transport"
                                       and "transport" in keep)}
                            for nid, sec in full["nodes"].items()}
            # always render requested sections, even when empty
            for sec in full["nodes"].values():
                for m in metrics:
                    key = "breakers" if m == "breaker" else m
                    sec.setdefault(key, {})
        return 200, full

    _INDEX_METRICS = {"docs", "store", "get", "merge", "search",
                      "indexing", "segments", "recovery", "query_cache",
                      "request_cache", "fielddata", "translog",
                      "completion", "refresh", "flush", "warmer", "_all"}

    def nodes_stats_index_metrics(req):
        # /_nodes/stats/indices/{index_metric,...}: keep only the named
        # sub-sections of the indices stats (RestNodesStatsAction's
        # index-metric filtering)
        wanted = [m.strip()
                  for m in str(req.params.get("index_metric", "")).split(",")
                  if m.strip()]
        for m in wanted:
            if m not in _INDEX_METRICS:
                raise IllegalArgumentError(
                    f"request [/_nodes/stats/indices/"
                    f"{','.join(wanted)}] contains unrecognized index "
                    f"metric: [{m}]")
        from elasticsearch_tpu.common.settings import setting_bool
        full = node.nodes_stats_api(
            level=req.param("level"),
            include_segment_file_sizes=setting_bool(
                req.param("include_segment_file_sizes")))
        # URL metric names map to response section names where they differ
        aliases = {"merge": "merges"}
        keys = {aliases.get(m, m) for m in wanted}
        for sec in full["nodes"].values():
            indices = sec.get("indices", {})
            if wanted and "_all" not in wanted:
                # "indices" is the per-index breakdown ?level=indices just
                # asked for — the metric filter must not discard it
                sec["indices"] = {k: v for k, v in indices.items()
                                  if k in keys or k == "indices"}
            keep_top = {"name", "roles", "indices"}
            for k in list(sec):
                if k not in keep_top:
                    del sec[k]
        return 200, full

    rc.register("GET", "/_nodes/stats/{metrics}", nodes_stats_metrics)
    rc.register("GET", "/_nodes/stats/indices/{index_metric}",
                nodes_stats_index_metrics)

    def reload_secure_settings(req):
        return 200, {"_nodes": {"total": 1, "successful": 1, "failed": 0},
                     "cluster_name": node.cluster_name,
                     "nodes": {node.node_id: {"name": node.node_name}}}

    rc.register("POST", "/_nodes/reload_secure_settings", reload_secure_settings)

    # ------------------------------------------------------------ cache clear
    def clear_cache(req):
        """TransportClearIndicesCacheAction: drop request/query caches."""
        expr = req.params.get("index")
        services = node.indices.resolve_open(expr)
        node.caches.request.clear()
        node.caches.query.clear()
        n_shards = sum(len(svc.shards) for svc in services)
        return 200, {"_shards": {"total": n_shards, "successful": n_shards,
                                 "failed": 0}}

    rc.register("POST", "/_cache/clear", clear_cache)
    rc.register("POST", "/{index}/_cache/clear", clear_cache)

    # ---------------------------------------------------------- validate (no index)
    def validate_all(req):
        from elasticsearch_tpu.node_admin import validate_query
        explain = str(req.param("explain", "false")) in ("true", "")
        return 200, validate_query(node, None, req.json(), explain=explain)

    rc.register("GET", "/_validate/query", validate_all)
    rc.register("POST", "/_validate/query", validate_all)

    # ---------------------------------------------------------- mtermvectors
    def mtermvectors(req):
        body = req.json() or {}
        default_index = req.params.get("index") \
            or req.param("index")
        for key in ("term_statistics", "fields", "realtime"):
            if req.param(key) is not None:
                body.setdefault(key, req.param(key))
        ids = body.get("ids") or req.param("ids")
        if isinstance(ids, str):
            ids = [i.strip() for i in ids.split(",")]
        docs_spec = body.get("docs") or []
        if not docs_spec and ids:
            docs_spec = [{"_id": i} for i in ids]
        defaults = {k: body[k] for k in ("term_statistics", "fields",
                                         "realtime") if k in body}
        out = []
        for spec in docs_spec:
            index = spec.get("_index", default_index)
            entry_req = {**defaults, **spec}
            tv = node.termvectors_api(index, spec.get("_id"), entry_req)
            out.append(tv)
        return 200, {"docs": out}

    rc.register("GET", "/_mtermvectors", mtermvectors)
    rc.register("POST", "/_mtermvectors", mtermvectors)
    rc.register("GET", "/{index}/_mtermvectors", mtermvectors)
    rc.register("POST", "/{index}/_mtermvectors", mtermvectors)

    # --------------------------------------------------------- tasks cancel-all
    def tasks_cancel_all(req):
        matched = node.tasks.list_tasks(req.param("actions"))
        if not matched:
            return 200, {"nodes": {}, "node_failures": []}
        # actually cancel, not just list: the task object doubles as the
        # cancellation token the continuous batcher's EDF queue observes
        # — a cancelled in-flight search's queued entries shed at
        # admission exactly like expired deadlines (serving/batcher.py)
        for t in matched:
            if t.cancellable:
                t.cancelled = True
        return 200, {"nodes": {node.node_id: {
            "name": node.node_name,
            "tasks": {t.task_id: t.to_dict(node.node_id)
                      for t in matched}}}}

    rc.register("POST", "/_tasks/_cancel", tasks_cancel_all)

    # --------------------------------------------------- component templates
    def put_component_template(req):
        name = req.params["name"]
        body = req.json() or {}
        if "template" not in body:
            raise IllegalArgumentError(
                "component template must define a [template]")
        node.component_templates[name] = body
        return 200, {"acknowledged": True}

    def get_component_template(req):
        name = req.params.get("name")
        store = node.component_templates
        if name is not None and name not in store \
                and "*" not in str(name):
            raise ResourceNotFoundError(
                f"component template matching [{name}] not found")
        from elasticsearch_tpu.common.patterns import matches_csv_patterns
        out = []
        for tname in sorted(store):
            if name is not None and not matches_csv_patterns(tname, name):
                continue
            body = dict(store[tname])
            tpl = dict(body.get("template") or {})
            if "settings" in tpl:
                tpl["settings"] = normalize_template_settings(tpl["settings"])
            body["template"] = tpl
            out.append({"name": tname, "component_template": body})
        return 200, {"component_templates": out}

    def delete_component_template(req):
        name = req.params["name"]
        if name not in node.component_templates:
            raise ResourceNotFoundError(
                f"component template matching [{name}] not found")
        del node.component_templates[name]
        return 200, {"acknowledged": True}

    rc.register("PUT", "/_component_template/{name}", put_component_template)
    rc.register("POST", "/_component_template/{name}", put_component_template)
    rc.register("GET", "/_component_template/{name}", get_component_template)
    rc.register("GET", "/_component_template", get_component_template)
    rc.register("DELETE", "/_component_template/{name}",
                delete_component_template)

    # -------------------------------------------------------- data streams
    def create_data_stream(req):
        name = req.params["name"]
        from elasticsearch_tpu.indices.service import IndicesService
        try:
            IndicesService.validate_index_name(name)
        except Exception as e:
            raise IllegalArgumentError(str(e))
        body = req.json() or {}
        node.data_streams[name] = {
            "name": name,
            "timestamp_field": body.get("timestamp_field", "@timestamp"),
            "indices": []}
        return 200, {"acknowledged": True}

    def get_data_streams(req):
        from elasticsearch_tpu.common.patterns import matches_csv_patterns
        name = req.params.get("name")
        out = [ds for n, ds in sorted(node.data_streams.items())
               if name is None or matches_csv_patterns(n, name)]
        return 200, out

    def delete_data_stream(req):
        name = req.params["name"]
        if name not in node.data_streams:
            raise ResourceNotFoundError(f"data_stream [{name}] not found")
        del node.data_streams[name]
        return 200, {"acknowledged": True}

    rc.register("PUT", "/_data_stream/{name}", create_data_stream)
    rc.register("GET", "/_data_stream", get_data_streams)
    rc.register("GET", "/_data_streams", get_data_streams)
    rc.register("GET", "/_data_stream/{name}", get_data_streams)
    rc.register("GET", "/_data_streams/{name}", get_data_streams)
    rc.register("DELETE", "/_data_stream/{name}", delete_data_stream)
    rc.register("DELETE", "/_data_streams/{name}", delete_data_stream)
