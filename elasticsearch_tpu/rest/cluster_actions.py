"""REST handlers for a clustered node: HTTP → ClusterNode transport actions.

The production wiring the reference does in `node/Node.java:502` (REST →
NodeClient → TransportAction → TransportService): REST handlers run on the
HTTP worker pool, bridge onto the node's event loop, and wait on the
callback-style ClusterNode client methods. Any node serves any request —
writes reroute to the primary, admin updates reroute to the elected
master, searches scatter-gather over the shard copies.
"""

from __future__ import annotations

import concurrent.futures
import time
import uuid
from typing import Any, Callable, Optional, Tuple

from elasticsearch_tpu.common.errors import SearchEngineError
from elasticsearch_tpu.rest.controller import RestController, RestRequest
from elasticsearch_tpu.version import __version__


class ClusterRestAdapter:
    """Bridges HTTP worker threads onto the node's asyncio event loop and
    back: ClusterNode callbacks always fire on the loop thread."""

    def __init__(self, cluster_node, loop):
        self.node = cluster_node
        self.loop = loop

    def call(self, fn: Callable, *args, timeout: float = 30.0,
             has_failure_cb: bool = False, **kw) -> Any:
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def on_done(result):
            if not fut.done():
                fut.set_result(result)

        def on_failure(err):
            if not fut.done():
                fut.set_exception(err if isinstance(err, Exception)
                                  else SearchEngineError(str(err)))

        def invoke():
            try:
                if has_failure_cb:
                    fn(*args, on_done=on_done, on_failure=on_failure, **kw)
                else:
                    fn(*args, on_done=on_done, **kw)
            except Exception as e:
                on_failure(e)

        self.loop.call_soon_threadsafe(invoke)
        return fut.result(timeout=timeout)

    # -- cluster health -------------------------------------------------------
    def health(self) -> dict:
        state = self.node.cluster_state
        status = "green"
        unassigned = 0
        for r in state.routing:
            started = r.state == "STARTED"
            if not started:
                unassigned += 1
                if r.primary:
                    status = "red"
                elif status == "green":
                    status = "yellow"
        # an index created but with no routing yet is not green
        shards_expected = 0
        for name, meta in state.metadata.items():
            if name.startswith("_"):  # reserved sections (registries)
                continue
            shards_expected += int(meta["settings"].get("index.number_of_shards", 1))
        primaries = sum(1 for r in state.routing if r.primary)
        if primaries < shards_expected:
            status = "red"
        if state.master_node_id is None:
            status = "red"
        return {
            "cluster_name": state.cluster_name,
            "status": status,
            "timed_out": False,
            "number_of_nodes": len(state.nodes),
            "number_of_data_nodes": len(state.nodes),
            "active_primary_shards": primaries,
            "active_shards": sum(1 for r in state.routing if r.state == "STARTED"),
            "unassigned_shards": unassigned,
            "master_node": state.master_node_id,
        }

    def wait_for_health(self, want: str, timeout_s: float) -> Tuple[dict, bool]:
        rank = {"red": 0, "yellow": 1, "green": 2}
        deadline = time.monotonic() + timeout_s
        while True:
            h = self.health()
            if rank[h["status"]] >= rank.get(want, 2):
                return h, False
            if time.monotonic() >= deadline:
                return h, True
            time.sleep(0.1)


def _parse_time_s(value) -> float:
    """ES time units → seconds ("30s", "1m", "500ms", bare number)."""
    s = str(value or "30s")
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("s"):
        return float(s[:-1])
    if s.endswith("m"):
        return float(s[:-1]) * 60.0
    return float(s)


def _doc_url_params(req: RestRequest) -> Tuple[str, Optional[str]]:
    return req.params["index"], req.params.get("id")


def register_cluster_overrides(rc: RestController,
                               adapter: ClusterRestAdapter,
                               aware=None) -> None:
    """Cluster-authoritative routes layered OVER the full single-node
    surface (`register_all`): a ClusterAwareNode serves every feature
    through its overridden data path, while these endpoints — the ones
    whose truth lives in the cluster state — dispatch to the master/
    coordination layer directly. Registration order matters: last wins.

    `aware`: the ClusterAwareNode whose node-local services (remote
    clusters) react to dynamic settings."""
    node = adapter.node

    def root(req):
        return 200, {
            "name": node.node_id,
            "cluster_name": node.cluster_state.cluster_name,
            "version": {"number": __version__, "build_flavor": "tpu",
                        "distributed": True},
            "tagline": "You Know, for (TPU) Search",
        }

    def cluster_health(req):
        want = req.param("wait_for_status")
        if want:
            h, timed_out = adapter.wait_for_health(
                want, _parse_time_s(req.param("timeout", "30s")))
            h["timed_out"] = timed_out
            return 200, h
        return 200, adapter.health()

    def cluster_state_(req):
        return 200, node.cluster_state.to_dict()

    def cat_nodes(req):
        state = node.cluster_state
        lines = []
        for n in sorted(state.nodes.values(), key=lambda x: x.node_id):
            marker = "*" if n.node_id == state.master_node_id else "-"
            lines.append(f"{n.node_id} {marker} {n.address or '-'}")
        return 200, "\n".join(lines) + "\n"

    def create_index(req):
        body = req.json() or {}
        index = req.params["index"]
        result = adapter.call(node.client_create_index, index,
                              settings=body.get("settings"),
                              mappings=body.get("mappings"))
        ack = bool(isinstance(result, dict) and result.get("acknowledged"))
        return (200 if ack else 503), {
            "acknowledged": ack, "shards_acknowledged": ack, "index": index}

    def delete_index(req):
        from elasticsearch_tpu.common.errors import IndexNotFoundError
        if req.params["index"] not in node.cluster_state.metadata:
            raise IndexNotFoundError(req.params["index"])
        adapter.call(node.client_delete_index, req.params["index"])
        return 200, {"acknowledged": True}

    def refresh(req):
        result = adapter.call(node.client_refresh, req.params.get("index"))
        return 200, result

    def update_settings(req):
        body = req.json() or {}
        merged = dict(body.get("persistent") or {},
                      **(body.get("transient") or {}))
        result = adapter.call(node.client_update_settings, merged)
        # dynamic remote-cluster reconfiguration on the serving node
        # (RemoteClusterService.listenForUpdates) — same hook as the
        # single-node handler in actions_admin.py
        if aware is not None:
            from elasticsearch_tpu.rest.actions_admin import _flatten
            flat = _flatten(merged)
            if any(k.startswith("cluster.remote.") for k in flat):
                aware.remotes.apply_settings(flat)
        return 200, {"acknowledged": bool(result.get("acknowledged")),
                     "persistent": result.get("persistent", {}),
                     "transient": {}}

    def get_index(req):
        from elasticsearch_tpu.common.errors import IndexNotFoundError
        name = req.params["index"]
        meta = node.cluster_state.metadata.get(name)
        if meta is None:
            raise IndexNotFoundError(name)
        return 200, {name: {"settings": meta.get("settings", {}),
                            "mappings": meta.get("mappings", {}),
                            "aliases": {}}}

    def get_mapping(req):
        from elasticsearch_tpu.common.errors import IndexNotFoundError
        name = req.params.get("index")
        meta_all = {n: m for n, m in node.cluster_state.metadata.items()
                    if not n.startswith("_")}
        names = [name] if name and name not in ("_all", "*") else sorted(meta_all)
        out = {}
        for n in names:
            meta = meta_all.get(n)
            if meta is None:
                raise IndexNotFoundError(n)
            out[n] = {"mappings": meta.get("mappings", {})}
        return 200, out

    def index_exists(req):
        ok = req.params["index"] in node.cluster_state.metadata
        return (200 if ok else 404), ({} if ok else None)

    def cat_indices(req):
        state = node.cluster_state
        lines = []
        for name in sorted(n for n in state.metadata
                           if not n.startswith("_")):
            shards = state.shards_of(name)
            started = sum(1 for s in shards
                          if s.state == "STARTED")
            health = "green" if started == len(shards) else (
                "yellow" if any(s.primary and s.state == "STARTED"
                                for s in shards) else "red")
            lines.append(f"{health} open {name} "
                         f"{sum(1 for s in shards if s.primary)} "
                         f"{sum(1 for s in shards if not s.primary)}")
        return 200, "\n".join(lines) + ("\n" if lines else "")

    rc.register("GET", "/", root)
    rc.register("GET", "/_cluster/health", cluster_health)
    rc.register("GET", "/_cluster/state", cluster_state_)
    rc.register("PUT", "/_cluster/settings", update_settings)
    rc.register("GET", "/_cat/nodes", cat_nodes)
    rc.register("GET", "/_cat/indices", cat_indices)
    rc.register("PUT", "/{index}", create_index)
    rc.register("DELETE", "/{index}", delete_index)
    rc.register("GET", "/{index}", get_index)
    rc.register("HEAD", "/{index}", index_exists)
    rc.register("GET", "/{index}/_mapping", get_mapping)
    rc.register("GET", "/_mapping", get_mapping)
    rc.register("POST", "/{index}/_refresh", refresh)
    rc.register("GET", "/{index}/_refresh", refresh)
    rc.register("POST", "/_refresh", refresh)
