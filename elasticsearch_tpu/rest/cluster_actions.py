"""REST handlers for a clustered node: HTTP → ClusterNode transport actions.

The production wiring the reference does in `node/Node.java:502` (REST →
NodeClient → TransportAction → TransportService): REST handlers run on the
HTTP worker pool, bridge onto the node's event loop, and wait on the
callback-style ClusterNode client methods. Any node serves any request —
writes reroute to the primary, admin updates reroute to the elected
master, searches scatter-gather over the shard copies.
"""

from __future__ import annotations

import concurrent.futures
import time
import uuid
from typing import Any, Callable, Optional, Tuple

from elasticsearch_tpu.common.errors import SearchEngineError
from elasticsearch_tpu.rest.controller import RestController, RestRequest
from elasticsearch_tpu.version import __version__


class ClusterRestAdapter:
    """Bridges HTTP worker threads onto the node's asyncio event loop and
    back: ClusterNode callbacks always fire on the loop thread."""

    def __init__(self, cluster_node, loop):
        self.node = cluster_node
        self.loop = loop

    def call(self, fn: Callable, *args, timeout: float = 30.0,
             has_failure_cb: bool = False, **kw) -> Any:
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def on_done(result):
            if not fut.done():
                fut.set_result(result)

        def on_failure(err):
            if not fut.done():
                fut.set_exception(err if isinstance(err, Exception)
                                  else SearchEngineError(str(err)))

        def invoke():
            try:
                if has_failure_cb:
                    fn(*args, on_done=on_done, on_failure=on_failure, **kw)
                else:
                    fn(*args, on_done=on_done, **kw)
            except Exception as e:
                on_failure(e)

        self.loop.call_soon_threadsafe(invoke)
        return fut.result(timeout=timeout)

    # -- cluster health -------------------------------------------------------
    def health(self) -> dict:
        state = self.node.cluster_state
        status = "green"
        unassigned = 0
        for r in state.routing:
            started = r.state == "STARTED"
            if not started:
                unassigned += 1
                if r.primary:
                    status = "red"
                elif status == "green":
                    status = "yellow"
        # an index created but with no routing yet is not green
        shards_expected = 0
        for name, meta in state.metadata.items():
            shards_expected += int(meta["settings"].get("index.number_of_shards", 1))
        primaries = sum(1 for r in state.routing if r.primary)
        if primaries < shards_expected:
            status = "red"
        if state.master_node_id is None:
            status = "red"
        return {
            "cluster_name": state.cluster_name,
            "status": status,
            "timed_out": False,
            "number_of_nodes": len(state.nodes),
            "number_of_data_nodes": len(state.nodes),
            "active_primary_shards": primaries,
            "active_shards": sum(1 for r in state.routing if r.state == "STARTED"),
            "unassigned_shards": unassigned,
            "master_node": state.master_node_id,
        }

    def wait_for_health(self, want: str, timeout_s: float) -> Tuple[dict, bool]:
        rank = {"red": 0, "yellow": 1, "green": 2}
        deadline = time.monotonic() + timeout_s
        while True:
            h = self.health()
            if rank[h["status"]] >= rank.get(want, 2):
                return h, False
            if time.monotonic() >= deadline:
                return h, True
            time.sleep(0.1)


def _parse_time_s(value) -> float:
    """ES time units → seconds ("30s", "1m", "500ms", bare number)."""
    s = str(value or "30s")
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("s"):
        return float(s[:-1])
    if s.endswith("m"):
        return float(s[:-1]) * 60.0
    return float(s)


def _doc_url_params(req: RestRequest) -> Tuple[str, Optional[str]]:
    return req.params["index"], req.params.get("id")


def register_cluster(rc: RestController, adapter: ClusterRestAdapter) -> None:
    node = adapter.node

    def root(req):
        return 200, {
            "name": node.node_id,
            "cluster_name": node.cluster_state.cluster_name,
            "version": {"number": __version__, "build_flavor": "tpu",
                        "distributed": True},
            "tagline": "You Know, for (TPU) Search",
        }

    def cluster_health(req):
        want = req.param("wait_for_status")
        if want:
            h, timed_out = adapter.wait_for_health(
                want, _parse_time_s(req.param("timeout", "30s")))
            h["timed_out"] = timed_out
            return 200, h
        return 200, adapter.health()

    def cluster_state_(req):
        return 200, node.cluster_state.to_dict()

    def cat_nodes(req):
        state = node.cluster_state
        lines = []
        for n in sorted(state.nodes.values(), key=lambda x: x.node_id):
            marker = "*" if n.node_id == state.master_node_id else "-"
            lines.append(f"{n.node_id} {marker} {n.address or '-'}")
        return 200, "\n".join(lines) + "\n"

    def create_index(req):
        body = req.json() or {}
        index = req.params["index"]
        adapter.call(node.client_create_index, index,
                     settings=body.get("settings"),
                     mappings=body.get("mappings"))
        return 200, {"acknowledged": True, "shards_acknowledged": True,
                     "index": index}

    def delete_index(req):
        adapter.call(node.client_delete_index, req.params["index"])
        return 200, {"acknowledged": True}

    def write_doc(req, op_type="index"):
        index, doc_id = _doc_url_params(req)
        if doc_id is None:
            doc_id = uuid.uuid4().hex[:20]
        op = {"type": "index", "id": doc_id, "source": req.json() or {},
              "op_type": op_type}
        routing = req.param("routing")
        if routing:
            op["routing"] = routing
        r = adapter.call(node.client_write, index, op, has_failure_cb=True)
        if "error" in r:
            return 400, r
        status = 201 if r.get("result") == "created" else 200
        return status, {"_index": index, "_id": doc_id,
                        "_version": r.get("_version", 1),
                        "_seq_no": r.get("_seq_no"),
                        "_primary_term": r.get("_primary_term"),
                        "result": r.get("result", "created"),
                        "_shards": {"total": 1, "successful": 1, "failed": 0}}

    def delete_doc(req):
        index, doc_id = _doc_url_params(req)
        op = {"type": "delete", "id": doc_id}
        r = adapter.call(node.client_write, index, op, has_failure_cb=True)
        return 200, {"_index": index, "_id": doc_id,
                     "result": r.get("result", "deleted")}

    def get_doc(req):
        index, doc_id = _doc_url_params(req)
        r = adapter.call(node.client_get, index, doc_id)
        status = 200 if r.get("found") else 404
        return status, {"_index": index, "_id": doc_id, **r}

    def refresh(req):
        index = req.params.get("index")
        r = adapter.call(node.client_refresh, index)
        return 200, r

    def search(req):
        index = req.params.get("index", "*")
        body = req.json() or {}
        if req.param("q"):
            body.setdefault("query", {"query_string": {"query": req.param("q")}})
        if req.param("size") is not None:
            body.setdefault("size", int(req.param("size")))
        r = adapter.call(node.client_search, index, body)
        if isinstance(r, dict) and r.get("status") == 404:
            return 404, r
        return 200, r

    def bulk(req):
        """NDJSON _bulk: sequential primary-routed writes."""
        lines = req.ndjson()
        items = []
        errors = False
        i = 0
        default_index = req.params.get("index")
        while i < len(lines):
            action_line = lines[i]
            ((action, meta),) = action_line.items()
            i += 1
            index = meta.get("_index", default_index)
            doc_id = meta.get("_id") or uuid.uuid4().hex[:20]
            if action in ("index", "create"):
                source = lines[i]
                i += 1
                op = {"type": "index", "id": doc_id, "source": source,
                      "op_type": "create" if action == "create" else "index"}
            elif action == "delete":
                op = {"type": "delete", "id": doc_id}
            else:  # update not supported on the cluster path yet
                items.append({action: {"_index": index, "_id": doc_id,
                                       "status": 400,
                                       "error": {"type": "illegal_argument_exception",
                                                 "reason": f"unsupported bulk action [{action}]"}}})
                errors = True
                continue
            try:
                r = adapter.call(node.client_write, index, op,
                                 has_failure_cb=True)
                items.append({action: {"_index": index, "_id": doc_id,
                                       "_version": r.get("_version", 1),
                                       "result": r.get("result"),
                                       "status": 201 if r.get("result") == "created" else 200}})
            except Exception as e:
                errors = True
                items.append({action: {"_index": index, "_id": doc_id,
                                       "status": 500,
                                       "error": {"type": type(e).__name__,
                                                 "reason": str(e)}}})
        return 200, {"took": 0, "errors": errors, "items": items}

    rc.register("GET", "/", root)
    rc.register("GET", "/_cluster/health", cluster_health)
    rc.register("GET", "/_cluster/state", cluster_state_)
    rc.register("GET", "/_cat/nodes", cat_nodes)
    rc.register("PUT", "/{index}", create_index)
    rc.register("DELETE", "/{index}", delete_index)
    rc.register("PUT", "/{index}/_doc/{id}", write_doc)
    rc.register("POST", "/{index}/_doc/{id}", write_doc)
    rc.register("POST", "/{index}/_doc", write_doc)
    rc.register("PUT", "/{index}/_create/{id}",
                lambda req: write_doc(req, op_type="create"))
    rc.register("POST", "/{index}/_create/{id}",
                lambda req: write_doc(req, op_type="create"))
    rc.register("DELETE", "/{index}/_doc/{id}", delete_doc)
    rc.register("GET", "/{index}/_doc/{id}", get_doc)
    rc.register("POST", "/{index}/_refresh", refresh)
    rc.register("GET", "/{index}/_refresh", refresh)
    rc.register("POST", "/_refresh", refresh)
    rc.register("GET", "/{index}/_search", search)
    rc.register("POST", "/{index}/_search", search)
    rc.register("POST", "/_bulk", bulk)
    rc.register("POST", "/{index}/_bulk", bulk)
