"""_cat table rendering (reference: `rest/action/cat/RestTable.java` +
`AbstractCatAction`): the text-format contract the reference's YAML suites
pin down —

- plain output has NO header row; `v=true` adds one; `help=true` prints the
  column catalog (name | aliases | description) and no data
- column widths are computed over cell values only, plus the header text
  when (and only when) `v=true` (RestTable.buildWidths verbose flag)
- numeric columns right-align, text left-aligns; one space separates
  columns and every cell pads to the column width
- `h=` selects/orders columns by name or alias; a column requested via an
  alias is titled with exactly what the caller typed
  (RestTable.buildDisplayHeaders)
- `s=` sorts rows by column (name or alias), `:desc` reverses
  (RestTable comparators), numeric-aware
- `format=json` renders the selected columns as a list of objects
- byte / millis / percent cells honor `bytes=` and render human units
  otherwise (ByteSizeValue / TimeValue rendering)
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple


class Col:
    def __init__(self, name: str, aliases: str = "", desc: str = "",
                 right: bool = False, default: bool = True):
        self.name = name
        self.aliases = [a for a in aliases.split(",") if a]
        self.desc = desc or name
        self.right = right
        self.default = default

    def matches(self, token: str) -> bool:
        t = token.lower()
        return t == self.name.lower() or t in (a.lower() for a in self.aliases)


class Bytes:
    """A byte-quantity cell: renders '12.1kb' style, or raw with bytes=b."""

    def __init__(self, n: Optional[int]):
        self.n = n

    _UNITS = {"b": 1, "k": 1024, "kb": 1024, "m": 1024 ** 2, "mb": 1024 ** 2,
              "g": 1024 ** 3, "gb": 1024 ** 3, "t": 1024 ** 4,
              "tb": 1024 ** 4, "p": 1024 ** 5, "pb": 1024 ** 5}

    def render(self, unit: Optional[str]) -> str:
        if self.n is None:
            return ""
        n = int(self.n)
        if unit in self._UNITS:
            # forced unit prints the integer quotient (ByteSizeValue.getGb)
            return str(n // self._UNITS[unit])
        for factor, suffix in ((1024 ** 5, "pb"), (1024 ** 4, "tb"),
                               (1024 ** 3, "gb"), (1024 ** 2, "mb"),
                               (1024, "kb")):
            if n >= factor:
                v = n / factor
                return f"{v:.1f}{suffix}".replace(".0" + suffix, suffix)
        return f"{n}b"

    def sort_key(self):
        return self.n if self.n is not None else -1


class Millis:
    """A duration cell: '123ms' under 1s else '1.2s' (TimeValue.toString)."""

    def __init__(self, ms: Optional[float]):
        self.ms = ms

    def render(self, unit: Optional[str]) -> str:
        if self.ms is None:
            return ""
        ms = float(self.ms)
        if ms < 1000:
            return f"{int(ms)}ms"
        if ms < 60_000:
            return f"{ms / 1000:.1f}s"
        return f"{ms / 60000:.1f}m"

    def sort_key(self):
        return self.ms if self.ms is not None else -1


def dir_size(path: str) -> int:
    """Recursive on-disk size of a directory tree (shared by the _cat
    store/disk columns)."""
    import os
    total = 0
    for dirpath, _, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, f))
            except OSError:
                pass
    return total


def fmt_iso_millis(ms: int) -> str:
    """epoch-millis -> 2020-01-01T00:00:00.000Z (strict_date_time)."""
    import datetime
    return datetime.datetime.fromtimestamp(
        ms / 1000, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.") \
        + f"{int(ms) % 1000:03d}Z"


def _cell_str(v: Any, bytes_unit: Optional[str]) -> str:
    if v is None:
        return ""
    if isinstance(v, (Bytes, Millis)):
        return v.render(bytes_unit)
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return f"{v:.1f}"
    return str(v)


def _resolve_h(cols: Sequence[Col], h_param: Optional[str]) -> List[Tuple[int, str]]:
    """-> [(col_index, display_title)]; default = declared default columns."""
    if not h_param:
        return [(i, c.name) for i, c in enumerate(cols) if c.default]
    out = []
    for token in h_param.split(","):
        token = token.strip()
        if not token:
            continue
        if "*" in token:
            import fnmatch
            out.extend((i, c.name) for i, c in enumerate(cols)
                       if fnmatch.fnmatchcase(c.name, token))
            continue
        for i, c in enumerate(cols):
            if c.matches(token):
                out.append((i, token))
                break
    return out


def _sort_rows(cols: Sequence[Col], rows: List[list], s_param: Optional[str]):
    if not s_param:
        return rows
    keys = []
    for token in s_param.split(","):
        token = token.strip()
        desc = False
        if token.endswith(":desc"):
            token, desc = token[:-5], True
        elif token.endswith(":asc"):
            token = token[:-4]
        for i, c in enumerate(cols):
            if c.matches(token):
                keys.append((i, desc))
                break
    if not keys:
        return rows

    # stable multi-key sort: apply keys right-to-left
    for i, desc in reversed(keys):
        def single(row, i=i):
            v = row[i]
            if isinstance(v, (Bytes, Millis)):
                v = v.sort_key()
            if isinstance(v, bool):
                v = str(v)
            if isinstance(v, (int, float)):
                return (0, float(v), "")
            return (1, 0.0, str(v))
        rows = sorted(rows, key=single, reverse=desc)
    return rows


def render(req, cols: Sequence[Col], rows: List[list]) -> Tuple[int, Any]:
    """Format a cat table per the request's h/s/v/help/format/bytes params."""
    if req.param("help") in ("true", "", True):
        width = max((len(c.name) for c in cols), default=0)
        lines = [f"{c.name.ljust(width)} | {','.join(c.aliases) or '-':15s} | "
                 f"{c.desc}" for c in cols]
        return 200, "\n".join(lines) + "\n"
    bytes_unit = req.param("bytes")
    rows = _sort_rows(cols, list(rows), req.param("s"))
    selected = _resolve_h(cols, req.param("h"))
    if req.param("format") == "json":
        return 200, [
            {title: _cell_str(r[i], bytes_unit) for i, title in selected}
            for r in rows]
    verbose = req.param("v") in ("true", "", True)
    # stringify the selected grid
    grid = [[_cell_str(r[i], bytes_unit) for i, _ in selected] for r in rows]
    titles = [title for _, title in selected]
    widths = []
    for ci in range(len(selected)):
        w = max((len(g[ci]) for g in grid), default=0)
        if verbose:
            w = max(w, len(titles[ci]))
        widths.append(w)
    # RestTable.pad: every cell pads to the column width EXCEPT the last
    # column when left-aligned (the suites pin both `value\n` on a final
    # text column and leading spaces on a final right-aligned one)
    last = len(selected) - 1
    lines = []
    if verbose:
        hdr = [t.ljust(w) if ci != last else t
               for ci, (t, w) in enumerate(zip(titles, widths))]
        lines.append(" ".join(hdr))
    for g in grid:
        cells = []
        for ci, (i, _) in enumerate(selected):
            if cols[i].right:
                cells.append(g[ci].rjust(widths[ci]))
            elif ci != last:
                cells.append(g[ci].ljust(widths[ci]))
            else:
                cells.append(g[ci])
        lines.append(" ".join(cells))
    return 200, "\n".join(lines) + "\n"
