"""REST handlers for the extended API surface: scroll, async-search, tasks,
ingest pipelines, templates, reindex family, field caps, validate, explain,
rank-eval, snapshots.

Registered alongside rest/actions.py's core table — together they cover the
bulk of the reference's 124-handler surface (SURVEY.md §2.7).
"""

from __future__ import annotations

from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.node_admin import (
    delete_by_query, explain_doc, field_caps, reindex, update_by_query,
    validate_query,
)
from elasticsearch_tpu.rest.controller import RestController
from elasticsearch_tpu.search.extras import rank_eval


def register_extra(rc: RestController, node: Node) -> None:
    # ------------------------------------------------------------------ scroll
    # (scroll START is a ?scroll= branch in the core _search handler,
    # rest/actions.py; only continuation/cleanup routes live here)
    def scroll_next(req):
        body = req.json() or {}
        # body wins; req.param covers both the path segment and query param
        scroll_id = body.get("scroll_id") or req.param("scroll_id")
        if not scroll_id:
            raise IllegalArgumentError("scroll_id is required")
        keep = body.get("scroll") or req.param("scroll")
        from elasticsearch_tpu.rest.actions import check_scroll_keep_alive
        check_scroll_keep_alive(node, keep)
        resp = node.search_scroll_next(scroll_id, keep)
        if req.bool_param("rest_total_hits_as_int", False):
            total = resp.get("hits", {}).get("total")
            if isinstance(total, dict):
                resp["hits"]["total"] = total.get("value")
        return 200, resp

    def scroll_delete(req):
        body = req.json() or {}
        ids = body.get("scroll_id", [])
        if isinstance(ids, str):
            ids = [ids]
        if not ids and req.params.get("scroll_id"):
            # DELETE /_search/scroll/{id}: body params override the path
            # segment (RestClearScrollAction)
            ids = req.params["scroll_id"].split(",")
        freed = 0
        if body.get("scroll_id") == "_all" or req.path.endswith("/_all") \
                or "_all" in ids:
            freed = node.clear_all_scrolls().get("num_freed", 0)
        else:
            for sid in ids:
                freed += int(node.clear_scroll(sid).get("num_freed", 0))
        if not freed and ids and "_all" not in ids:
            # nothing matched: the ids were unknown/expired (404 in the
            # reference's ClearScrollResponse when nothing freed)
            return 404, {"succeeded": True, "num_freed": 0}
        return 200, {"succeeded": True, "num_freed": freed}

    rc.register("POST", "/_search/scroll", scroll_next)
    rc.register("GET", "/_search/scroll", scroll_next)
    rc.register("GET", "/_search/scroll/{scroll_id}", scroll_next)
    rc.register("POST", "/_search/scroll/{scroll_id}", scroll_next)
    rc.register("DELETE", "/_search/scroll", scroll_delete)
    rc.register("DELETE", "/_search/scroll/{scroll_id}", scroll_delete)

    # ------------------------------------------------------------ async search
    def async_submit(req):
        body = req.json() or {}
        index = req.params.get("index")
        wait = req.param("wait_for_completion_timeout", "1s")
        from elasticsearch_tpu.common.settings import parse_time_value
        out = node.async_search.submit(lambda: node.search(index, body),
                                       wait_for_completion_s=parse_time_value(wait, "wait"))
        return 200, out

    def async_get(req):
        return 200, node.async_search.status(req.params["id"])

    def async_delete(req):
        ok = node.async_search.delete(req.params["id"])
        return (200 if ok else 404), {"acknowledged": ok}

    rc.register("POST", "/_async_search", async_submit)
    rc.register("POST", "/{index}/_async_search", async_submit)
    rc.register("GET", "/_async_search/{id}", async_get)
    rc.register("DELETE", "/_async_search/{id}", async_delete)

    # ------------------------------------------------------------------- tasks
    def list_tasks(req):
        import fnmatch
        import time as _time
        actions = req.param("actions")
        group_by = req.param("group_by") or "nodes"
        out = node.tasks_list_api(actions)
        # the list request itself runs as a task
        # (TransportListTasksAction registers itself) and carries the
        # caller's task headers (X-Opaque-Id)
        self_action = "cluster:monitor/tasks/lists"
        if actions is None or any(
                fnmatch.fnmatchcase(self_action, p.strip())
                for p in str(actions).split(",") if p.strip()):
            opaque = (req.headers or {}).get("x-opaque-id")
            self_task = {
                "node": node.node_id, "id": 0, "type": "transport",
                "action": self_action,
                "start_time_in_millis": int(_time.time() * 1000),
                "running_time_in_nanos": 1, "cancellable": False,
                "headers": ({"X-Opaque-Id": opaque} if opaque else {})}
            out["nodes"].setdefault(node.node_id, {}).setdefault(
                "tasks", {})[f"{node.node_id}:0"] = self_task
        if group_by == "none":
            tasks = [t for sec in out["nodes"].values()
                     for t in sec.get("tasks", {}).values()]
            return 200, {"tasks": tasks}
        if group_by == "parents":
            tasks = {tid: t for sec in out["nodes"].values()
                     for tid, t in sec.get("tasks", {}).items()}
            return 200, {"tasks": tasks}
        return 200, out

    def get_task(req):
        return 200, node.task_get_api(req.params["task_id"])

    def cancel_task(req):
        return 200, node.task_cancel_api(req.params["task_id"])

    rc.register("GET", "/_tasks", list_tasks)
    rc.register("GET", "/_tasks/{task_id}", get_task)
    rc.register("POST", "/_tasks/{task_id}/_cancel", cancel_task)

    # ------------------------------------------------------------------ ingest
    def put_pipeline(req):
        node.ingest.put_pipeline(req.params["id"], req.json() or {})
        return 200, {"acknowledged": True}

    def get_pipeline(req):
        pid = req.params.get("id")
        if pid:
            p = node.ingest.get_pipeline(pid)
            return 200, {pid: p.definition}
        return 200, {pid: p.definition for pid, p in node.ingest.pipelines.items()}

    def delete_pipeline(req):
        node.ingest.delete_pipeline(req.params["id"])
        return 200, {"acknowledged": True}

    def simulate_pipeline(req):
        body = req.json() or {}
        pid = req.params.get("id")
        pipeline = pid if pid else body.get("pipeline", {})
        docs = body.get("docs", [])
        return 200, {"docs": node.ingest.simulate(pipeline, docs)}

    rc.register("PUT", "/_ingest/pipeline/{id}", put_pipeline)
    rc.register("GET", "/_ingest/pipeline/{id}", get_pipeline)
    rc.register("GET", "/_ingest/pipeline", get_pipeline)
    rc.register("DELETE", "/_ingest/pipeline/{id}", delete_pipeline)
    rc.register("POST", "/_ingest/pipeline/_simulate", simulate_pipeline)
    rc.register("POST", "/_ingest/pipeline/{id}/_simulate", simulate_pipeline)

    # --------------------------------------------------------------- templates
    def put_template(req):
        name = req.params["name"]
        composable = "_index_template" in req.path
        if req.bool_param("create", False):
            store = (node.templates.index_templates if composable
                     else node.templates.templates)
            if name in store:
                raise IllegalArgumentError(
                    f"index_template [{name}] already exists")
        node.templates.put(name, req.json() or {}, composable=composable)
        return 200, {"acknowledged": True}

    def get_template(req):
        composable = "_index_template" in req.path
        name = req.params.get("name")
        flat = req.bool_param("flat_settings", False)

        def render(t):
            # legacy template rendering: order always present, settings
            # under the index. namespace with STRING values, nested by
            # default or flat with ?flat_settings
            aliases = {}
            for a, opts in (t.get("aliases") or {}).items():
                opts = dict(opts or {})
                routing = opts.pop("routing", None)
                if routing is not None:
                    opts.setdefault("index_routing", str(routing))
                    opts.setdefault("search_routing", str(routing))
                aliases[a] = opts
            out = {"order": t.get("order", 0),
                   "index_patterns": t.get("index_patterns", []),
                   "settings": {}, "mappings": t.get("mappings", {}),
                   "aliases": aliases}
            if "version" in t:
                out["version"] = t["version"]
            flat_settings = {}
            for k, v in (t.get("settings") or {}).items():
                key = k if k.startswith("index.") else f"index.{k}"
                flat_settings[key] = str(v)
            if flat:
                out["settings"] = flat_settings
            else:
                nested = {}
                for k, v in flat_settings.items():
                    nodep = nested
                    parts = k.split(".")
                    for p in parts[:-1]:
                        nodep = nodep.setdefault(p, {})
                    nodep[parts[-1]] = v
                out["settings"] = nested
            return out

        def render_composable(t):
            from elasticsearch_tpu.rest.actions_conf import (
                normalize_template_settings)
            t = dict(t)
            if "template" not in t:
                return t
            tpl = dict(t.get("template") or {})
            if "settings" in tpl:
                tpl["settings"] = normalize_template_settings(tpl["settings"])
            if "aliases" in tpl:
                aliases = {}
                for a, opts in (tpl["aliases"] or {}).items():
                    opts = dict(opts or {})
                    routing = opts.pop("routing", None)
                    if routing is not None:
                        opts.setdefault("index_routing", str(routing))
                        opts.setdefault("search_routing", str(routing))
                    aliases[a] = opts
                tpl["aliases"] = aliases
            t["template"] = tpl
            return t

        if composable:
            if name:
                return 200, {"index_templates": [
                    {"name": name,
                     "index_template": render_composable(
                         node.templates.get(name, True))}]}
            return 200, {"index_templates": [
                {"name": n, "index_template": render_composable(t)}
                for n, t in node.templates.index_templates.items()]}
        if name:
            import fnmatch as _fn
            if "*" in name:
                return 200, {n: render(t)
                             for n, t in node.templates.templates.items()
                             if _fn.fnmatch(n, name)}
            return 200, {name: render(node.templates.get(name))}
        return 200, {n: render(t)
                     for n, t in node.templates.templates.items()}

    def delete_template(req):
        node.templates.delete(req.params["name"],
                              composable="_index_template" in req.path)
        return 200, {"acknowledged": True}

    for base in ("/_template/{name}", "/_index_template/{name}"):
        rc.register("PUT", base, put_template)
        rc.register("POST", base, put_template)
        rc.register("GET", base, get_template)
        rc.register("DELETE", base, delete_template)
    rc.register("GET", "/_template", get_template)
    rc.register("GET", "/_index_template", get_template)

    # ----------------------------------------------------------------- reindex
    def do_reindex(req):
        return 200, reindex(node, req.json() or {})

    def do_update_by_query(req):
        return 200, update_by_query(node, req.params["index"], req.json())

    def do_delete_by_query(req):
        return 200, delete_by_query(node, req.params["index"], req.json() or {})

    rc.register("POST", "/_reindex", do_reindex)
    rc.register("POST", "/{index}/_update_by_query", do_update_by_query)
    rc.register("POST", "/{index}/_delete_by_query", do_delete_by_query)

    # ----------------------------------------------- field caps / validate / explain
    def do_field_caps(req):
        body = req.json() or {}
        fields = req.param("fields") or ",".join(body.get("fields", ["*"]))
        return 200, field_caps(
            node, req.params.get("index"), fields,
            include_unmapped=req.param("include_unmapped") in ("true", "", True))

    rc.register("GET", "/_field_caps", do_field_caps)
    rc.register("POST", "/_field_caps", do_field_caps)
    rc.register("GET", "/{index}/_field_caps", do_field_caps)
    rc.register("POST", "/{index}/_field_caps", do_field_caps)

    def do_validate(req):
        explain = str(req.param("explain", "false")) in ("true", "")
        body = req.json()
        if body is None and req.param("q") is not None:
            body = {"query": {"query_string": {"query": req.param("q")}}}
        return 200, validate_query(node, req.params.get("index"), body,
                                   explain=explain)

    rc.register("GET", "/{index}/_validate/query", do_validate)
    rc.register("POST", "/{index}/_validate/query", do_validate)

    def do_explain(req):
        from elasticsearch_tpu.rest.actions import apply_uri_query
        body = apply_uri_query(req, req.json() or {})
        src_param = req.param("_source")
        inc = req.param("_source_includes") or req.param("_source_include")
        exc = req.param("_source_excludes") or req.param("_source_exclude")
        source_spec = None
        if str(src_param) == "false":
            source_spec = None  # explicit opt-out beats include/exclude
        elif src_param is not None and str(src_param) != "true":
            source_spec = (str(src_param).split(","), [])
        elif str(src_param) == "true" or inc or exc:
            source_spec = (str(inc).split(",") if inc else [],
                           str(exc).split(",") if exc else [])
        return 200, explain_doc(node, req.params["index"], req.params["id"],
                                body, source_spec=source_spec)

    rc.register("GET", "/{index}/_explain/{id}", do_explain)
    rc.register("POST", "/{index}/_explain/{id}", do_explain)

    # --------------------------------------------------------------- rank eval
    def do_rank_eval(req):
        return 200, rank_eval(lambda idx, b: node.search(idx, b),
                              req.json() or {}, req.params.get("index"))

    rc.register("GET", "/_rank_eval", do_rank_eval)
    rc.register("POST", "/_rank_eval", do_rank_eval)
    rc.register("GET", "/{index}/_rank_eval", do_rank_eval)
    rc.register("POST", "/{index}/_rank_eval", do_rank_eval)

    # --------------------------------------------------------------- snapshots
    def put_repo(req):
        node.snapshots.put_repository(req.params["repo"], req.json() or {})
        return 200, {"acknowledged": True}

    def _redact_repo_settings(settings: dict) -> dict:
        # credentials never leave via the API (reference: Setting.Property
        # .Filtered hides secure-setting-adjacent values from GETs)
        secret_markers = ("access_key", "secret_key", "password", "token",
                          "credential", "sas_token", "client_secret")
        return {k: ("<redacted>" if any(m in k.lower() for m in secret_markers)
                    else v)
                for k, v in settings.items()}

    def get_repo(req):
        name = req.params.get("repo")
        if name:
            repo = node.snapshots.get_repository(name)
            return 200, {name: {"type": repo.type,
                                "settings": _redact_repo_settings(repo.settings)}}
        return 200, {name: {"type": r.type,
                            "settings": _redact_repo_settings(r.settings)}
                     for name, r in node.snapshots.repositories.items()}

    def delete_repo(req):
        node.snapshots.delete_repository(req.params["repo"])
        return 200, {"acknowledged": True}

    def create_snapshot(req):
        return 200, node.snapshots.create_snapshot(
            req.params["repo"], req.params["snapshot"], req.json())

    def get_snapshot(req):
        """GetSnapshotsAction, 8.0 response format: a `responses` array of
        per-repository results; missing snapshots surface as an error entry
        unless ignore_unavailable."""
        repo_name = req.params["repo"]
        expr = req.params.get("snapshot", "_all")
        verbose = str(req.param("verbose", "true")) != "false"
        ignore = str(req.param("ignore_unavailable", "false")) in ("true", "")
        listing = node.snapshots.get_snapshots(repo_name, expr)
        found = {s["snapshot"] for s in listing["snapshots"]}
        missing = [p for p in str(expr).split(",")
                   if p not in ("_all", "*") and "*" not in p
                   and p not in found]
        if missing and not ignore:
            err = {"type": "snapshot_missing_exception",
                   "reason": f"[{repo_name}:{missing[0]}] is missing"}
            return 200, {"responses": [{"repository": repo_name,
                                        "error": err}]}
        repo = node.snapshots.get_repository(repo_name)
        snaps = []
        for s in listing["snapshots"]:
            name = s["snapshot"]
            try:
                m = repo.get_manifest(name)
            except Exception:
                m = dict(s)
            if not verbose:
                snaps.append({"snapshot": name, "uuid": name,
                              "state": s.get("state", "SUCCESS"),
                              "indices": sorted(m.get("indices") or [])})
                continue
            entry = {"snapshot": name, "uuid": name,
                     "version": m.get("version", "8.0.0"),
                     "version_id": m.get("version_id", 8000099),
                     "indices": sorted(m.get("indices") or []),
                     "include_global_state": m.get("include_global_state",
                                                   True),
                     "state": s.get("state", "SUCCESS"),
                     "start_time_in_millis": m.get("start_time_in_millis"),
                     "end_time_in_millis": m.get("end_time_in_millis"),
                     "duration_in_millis": max(
                         (m.get("end_time_in_millis") or 0)
                         - (m.get("start_time_in_millis") or 0), 0),
                     "failures": [],
                     "shards": m.get("shards", {"total": 0, "failed": 0,
                                                "successful": 0})}
            if m.get("metadata"):
                entry["metadata"] = m["metadata"]
            snaps.append(entry)
        return 200, {"responses": [{"repository": repo_name,
                                    "snapshots": snaps}],
                     "snapshots": snaps}

    def delete_snapshot(req):
        node.snapshots.delete_snapshot(req.params["repo"], req.params["snapshot"])
        return 200, {"acknowledged": True}

    def restore_snapshot(req):
        return 200, node.snapshots.restore_snapshot(
            req.params["repo"], req.params["snapshot"], req.json())

    rc.register("PUT", "/_snapshot/{repo}", put_repo)
    rc.register("POST", "/_snapshot/{repo}", put_repo)
    rc.register("GET", "/_snapshot/{repo}", get_repo)
    rc.register("GET", "/_snapshot", get_repo)
    rc.register("DELETE", "/_snapshot/{repo}", delete_repo)
    rc.register("PUT", "/_snapshot/{repo}/{snapshot}", create_snapshot)
    rc.register("POST", "/_snapshot/{repo}/{snapshot}", create_snapshot)
    rc.register("GET", "/_snapshot/{repo}/{snapshot}", get_snapshot)
    rc.register("DELETE", "/_snapshot/{repo}/{snapshot}", delete_snapshot)
    rc.register("POST", "/_snapshot/{repo}/{snapshot}/_restore", restore_snapshot)

    def verify_repo(req):
        return 200, node.snapshots.verify_repository(req.params["repo"])

    rc.register("POST", "/_snapshot/{repo}/_verify", verify_repo)
