"""Minimal asyncio HTTP/1.1 server fronting the RestController.

Plays the role of `Netty4HttpServerTransport` (reference layer 4): accepts
keep-alive connections, parses request line + headers + Content-Length
bodies, dispatches to the controller on a worker thread pool (handlers do
blocking engine work), renders JSON (or text for _cat) responses. No
external dependencies — stdlib asyncio only.
"""

from __future__ import annotations

import asyncio
import json
import urllib.parse
from typing import Optional, Tuple

from elasticsearch_tpu.common import xcontent
from elasticsearch_tpu.rest.controller import RestController


def _negotiate_accept(accept: Optional[str]) -> Optional[str]:
    """Multi-valued Accept header → first supported x-content type, or None
    for the JSON default (reference: media-type negotiation in
    AbstractHttpServerTransport/RestController)."""
    if not accept:
        return None
    for part in accept.split(","):
        media = part.split(";")[0].strip()
        if media in ("*/*", "application/json"):
            return None
        try:
            return xcontent.XContentType.from_media_type(part.strip())
        except Exception:
            continue
    return None

MAX_BODY = 100 * 1024 * 1024  # reference http.max_content_length default 100mb


class HttpServer:
    def __init__(self, controller: RestController, host: str = "127.0.0.1",
                 port: int = 9200, max_workers: int = 8, thread_pool=None,
                 ssl_context=None):
        from elasticsearch_tpu.common.threadpool import ThreadPool
        self.controller = controller
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        # http.ssl.*: TLS terminates in-process (reference:
        # SecurityRestFilter + Netty4HttpServerTransport with
        # xpack.security.http.ssl); plaintext bytes on a TLS port fail
        # the handshake and never reach the REST layer
        self.ssl_context = ssl_context
        # per-workload named executors (ThreadPool.java): requests route to
        # the pool their workload class owns, so e.g. a bulk flood queues in
        # `write` while `search` keeps draining; full queues answer 429
        self.thread_pool = thread_pool or ThreadPool()
        self._owns_pool = thread_pool is None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, ssl=self.ssl_context)
        addr = self._server.sockets[0].getsockname()
        self.port = addr[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        if self._owns_pool:
            self.thread_pool.shutdown()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                request = await self._read_request(reader)
                if request is None:
                    break
                method, path, query, headers, body = request
                from elasticsearch_tpu.common.threadpool import (
                    EsRejectedExecutionError, pool_for_route,
                )
                try:
                    future = self.thread_pool.submit(
                        pool_for_route(method, path),
                        self.controller.dispatch, method, path, query,
                        body, headers.get("content-type"), headers)
                    status, payload = await asyncio.wrap_future(future)
                except EsRejectedExecutionError as e:
                    status, payload = 429, {"error": e.to_dict(),
                                            "status": 429}
                keep_alive = headers.get("connection", "keep-alive").lower() != "close"
                await self._write_response(writer, status, payload, keep_alive,
                                           accept=headers.get("accept"))
                if not keep_alive:
                    break
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _read_request(self, reader: asyncio.StreamReader):
        try:
            request_line = await reader.readline()
        except (asyncio.LimitOverrunError, ValueError):
            return None
        if not request_line:
            return None
        try:
            method, target, _version = request_line.decode("latin-1").split(" ", 2)
        except ValueError:
            return None
        parsed = urllib.parse.urlsplit(target)
        # keep the RAW path: the controller decodes per-SEGMENT, so an
        # encoded slash inside a segment (date-math index names) survives
        path = parsed.path
        query = {k: v[-1] for k, v in urllib.parse.parse_qs(
            parsed.query, keep_blank_values=True).items()}

        headers = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            name, _, value = line.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()

        body = b""
        length = int(headers.get("content-length", 0) or 0)
        if length > MAX_BODY:
            return None
        if length:
            body = await reader.readexactly(length)
        elif headers.get("transfer-encoding", "").lower() == "chunked":
            chunks = []
            while True:
                size_line = await reader.readline()
                size = int(size_line.strip() or b"0", 16)
                if size == 0:
                    await reader.readline()
                    break
                chunks.append(await reader.readexactly(size))
                await reader.readline()
            body = b"".join(chunks)
        return method.upper(), path, query, headers, body

    async def _write_response(self, writer: asyncio.StreamWriter, status: int,
                              payload, keep_alive: bool,
                              accept: str = None) -> None:
        reasons = {200: "OK", 201: "Created", 400: "Bad Request", 404: "Not Found",
                   405: "Method Not Allowed", 409: "Conflict", 429: "Too Many Requests",
                   500: "Internal Server Error", 503: "Service Unavailable"}
        if payload is None:
            data = b""
            ctype = "application/json"
        elif isinstance(payload, str):
            data = payload.encode("utf-8")
            ctype = "text/plain; charset=UTF-8"
        else:
            data = None
            out_type = _negotiate_accept(accept)
            if out_type and out_type != "application/json":
                try:
                    data = xcontent.dumps(payload, out_type)
                    ctype = out_type
                except Exception:
                    data = None  # unencodable in that format: JSON fallback
            if data is None:
                data = json.dumps(payload).encode("utf-8")
                ctype = "application/json"
        head = (f"HTTP/1.1 {status} {reasons.get(status, 'Unknown')}\r\n"
                f"content-type: {ctype}\r\n"
                f"content-length: {len(data)}\r\n"
                f"connection: {'keep-alive' if keep_alive else 'close'}\r\n"
                f"X-elastic-product: Elasticsearch\r\n\r\n")
        writer.write(head.encode("latin-1") + data)
        await writer.drain()
