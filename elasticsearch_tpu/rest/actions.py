"""REST handlers: the API surface table.

Covers the core of the reference's 124 handlers (`action/ActionModule.java`
initRestHandlers + `rest-api-spec/api/*.json` contract): document CRUD,
_bulk/_mget/_update, _search/_count/_msearch, index admin (create/delete/
mapping/settings/refresh/flush/forcemerge/aliases/stats/exists), _analyze,
cluster health/state/stats, _cat APIs, and the root banner.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional, Tuple

from elasticsearch_tpu import telemetry
from elasticsearch_tpu.common.errors import (
    DocumentMissingError, IllegalArgumentError, IndexNotFoundError,
    SearchEngineError,
)
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.controller import RestController, RestRequest
from elasticsearch_tpu.version import __version__


def _rest_telemetry(req, node, action: str, force_trace: bool = False,
                    description: str = "", parse_nanos: int = 0):
    """Per-request telemetry binding for an instrumented handler: live
    task (tasks API + cancellation token), trace when sampled or forced,
    X-Opaque-ID captured once from the header and threaded through
    both."""
    return telemetry.rest_request(
        node, action,
        opaque_id=(req.headers or {}).get("x-opaque-id"),
        force_trace=force_trace, description=description,
        parse_nanos=parse_nanos)


def _cat_table(req, headers, rows) -> Tuple[int, Any]:
    """Legacy shim over rest/cat.py's RestTable renderer."""
    from elasticsearch_tpu.rest.cat import Col, render
    return render(req, [Col(h) for h in headers], rows)


def apply_uri_query(req, body):
    """URI q= parameter -> query_string clause (RestSearchAction
    parseSearchRequest; shared by search/count/explain)."""
    q = req.param("q")
    if not q:
        return body
    if "query" in body:
        raise IllegalArgumentError(
            "cannot specify both [q] parameter and a request body query")
    qs = {"query": q}
    if req.param("df"):
        qs["default_field"] = req.param("df")
    if req.param("default_operator"):
        qs["default_operator"] = req.param("default_operator")
    if req.param("lenient") is not None:
        qs["lenient"] = req.bool_param("lenient", False)
    if req.param("analyzer"):
        qs["analyzer"] = req.param("analyzer")
    if req.param("analyze_wildcard") is not None:
        qs["analyze_wildcard"] = req.bool_param("analyze_wildcard", False)
    body["query"] = {"query_string": qs}
    return body


def register_all(rc: RestController, node: Node) -> None:
    from elasticsearch_tpu.rest.actions_extra import register_extra
    register_extra(rc, node)
    from elasticsearch_tpu.rest.actions_script import register_script
    register_script(rc, node)
    from elasticsearch_tpu.rest.actions_xpack import register_xpack
    register_xpack(rc, node)
    from elasticsearch_tpu.rest.actions_admin import register_admin
    register_admin(rc, node)
    from elasticsearch_tpu.rest.actions_conf import register_conf
    register_conf(rc, node)
    from elasticsearch_tpu.security.rest_filter import (
        make_security_filter, register_security,
    )
    register_security(rc, node)
    rc.add_filter(make_security_filter(node.security))
    # plugin-contributed REST handlers (reference:
    # ActionPlugin.getRestHandlers); on_node_start fires in Node.__init__
    node.plugins.register_rest(rc, node)
    # ------------------------------------------------------------------ root
    def root(req):
        return 200, {
            "name": node.node_name, "cluster_name": node.cluster_name,
            "cluster_uuid": node.node_id,
            "version": {"number": __version__,
                        "build_flavor": "tpu", "lucene_version": "none"},
            "tagline": "You Know, for (TPU) Search",
        }

    rc.register("GET", "/", root)

    # ------------------------------------------------------------- documents
    def put_doc(req):
        with _rest_telemetry(req, node, "indices:data/write/index",
                             force_trace=req.bool_param("trace"),
                             description=f"[{req.params['index']}]"):
            resp = node.index_doc(
                req.params["index"], req.params.get("id"), req.json() or {},
                op_type=req.param("op_type", "index"),
                refresh=req.param("refresh"),
                routing=req.param("routing"),
                if_seq_no=req.int_param("if_seq_no"),
                if_primary_term=req.int_param("if_primary_term"),
                version=req.int_param("version"),
                version_type=req.param("version_type", "internal"),
                pipeline=req.param("pipeline"))
            return (201 if resp["result"] == "created" else 200), resp

    def post_doc_auto_id(req):
        with _rest_telemetry(req, node, "indices:data/write/index",
                             force_trace=req.bool_param("trace"),
                             description=f"[{req.params['index']}]"):
            resp = node.index_doc(req.params["index"], None,
                                  req.json() or {},
                                  refresh=req.param("refresh"),
                                  routing=req.param("routing"))
            return 201, resp

    def create_doc(req):
        if req.param("version_type") in ("external", "external_gte"):
            from elasticsearch_tpu.common.errors import (
                ActionRequestValidationError)
            raise ActionRequestValidationError(
                "Validation Failed: 1: create operations only support "
                "internal versioning. use index instead;")
        with _rest_telemetry(req, node, "indices:data/write/index",
                             force_trace=req.bool_param("trace"),
                             description=f"[{req.params['index']}]"):
            resp = node.index_doc(req.params["index"], req.params["id"],
                                  req.json() or {}, op_type="create",
                                  refresh=req.param("refresh"),
                                  routing=req.param("routing"))
            return 201, resp

    def _get_source_filter(req):
        src = req.param("_source")
        inc, exc = req.param("_source_includes"), req.param("_source_excludes")
        source_filter = None
        if isinstance(src, str) and src.lower() == "false" or src is False:
            source_filter = False
        elif isinstance(src, str) and src.lower() == "true" or src is True:
            source_filter = True
        elif src:
            source_filter = src.split(",") if isinstance(src, str) else src
        if inc or exc:
            source_filter = {"includes": inc.split(",") if inc else [],
                             "excludes": exc.split(",") if exc else []}
        return source_filter

    def get_doc(req):
        from elasticsearch_tpu.common.errors import VersionConflictError
        if req.bool_param("refresh", False):
            # overridable: clustered nodes broadcast, local ones refresh
            # the service directly
            node._refresh_indices([req.params["index"]])
        resp = node.get_doc(req.params["index"], req.params["id"],
                            routing=req.param("routing"),
                            realtime=req.bool_param("realtime", True))
        v = req.int_param("version")
        if v is not None and resp.get("found") \
                and resp.get("_version") != v:
            raise VersionConflictError(
                f"[{req.params['id']}]: version conflict, current version "
                f"[{resp.get('_version')}] is different than the one "
                f"provided [{v}]")
        sf = req.param("stored_fields")
        node._apply_mget_projection(
            resp, {}, sf.split(",") if sf else None,
            req.params["index"], _get_source_filter(req))
        return (200 if resp.get("found") else 404), resp

    def get_source(req):
        if req.bool_param("refresh", False):
            node._refresh_indices([req.params["index"]])
        resp = node.get_doc(req.params["index"], req.params["id"],
                            routing=req.param("routing"),
                            realtime=req.bool_param("realtime", True))
        if not resp.get("found") or "_source" not in resp:
            # missing doc OR _source disabled in the mapping: both 404
            # (RestGetSourceAction)
            return 404, {"error": f"source [{req.params['id']}] not found"}
        node._apply_mget_projection(resp, {}, None, req.params["index"],
                                    _get_source_filter(req))
        return 200, resp.get("_source")

    def delete_doc(req):
        with _rest_telemetry(req, node, "indices:data/write/delete",
                             force_trace=req.bool_param("trace"),
                             description=f"[{req.params['index']}]"):
            try:
                resp = node.delete_doc(
                    req.params["index"], req.params["id"],
                    refresh=req.param("refresh"),
                    routing=req.param("routing"),
                    if_seq_no=req.int_param("if_seq_no"),
                    if_primary_term=req.int_param("if_primary_term"),
                    version=req.int_param("version"),
                    version_type=req.param("version_type", "internal"))
                return 200, resp
            except DocumentMissingError:
                return 404, {"_index": req.params["index"],
                             "_id": req.params["id"],
                             "result": "not_found"}

    def update_doc(req):
        with _rest_telemetry(req, node, "indices:data/write/update",
                             force_trace=req.bool_param("trace"),
                             description=f"[{req.params['index']}]"):
            return 200, node.update_doc(
                req.params["index"], req.params["id"], req.json() or {},
                refresh=req.param("refresh"),
                routing=req.param("routing"),
                if_seq_no=req.int_param("if_seq_no"),
                if_primary_term=req.int_param("if_primary_term"),
                source_filter=_get_source_filter(req))

    rc.register("PUT", "/{index}/_doc/{id}", put_doc)
    rc.register("POST", "/{index}/_doc/{id}", put_doc)
    rc.register("POST", "/{index}/_doc", post_doc_auto_id)
    rc.register("PUT", "/{index}/_create/{id}", create_doc)
    rc.register("POST", "/{index}/_create/{id}", create_doc)
    # no direct HEAD registration: RestController's HEAD fallback reuses GET
    # and strips the body (a HEAD body would desync keep-alive connections)
    rc.register("GET", "/{index}/_doc/{id}", get_doc)
    rc.register("GET", "/{index}/_source/{id}", get_source)
    rc.register("DELETE", "/{index}/_doc/{id}", delete_doc)
    rc.register("POST", "/{index}/_update/{id}", update_doc)

    def _total_hits_as_int(resp):
        """?rest_total_hits_as_int=true renders hits.total as the pre-7.0
        plain number (RestSearchAction.TOTAL_HITS_AS_INT_PARAM); with hit
        counting disabled the legacy rendering is -1."""
        hits = resp.get("hits") if isinstance(resp, dict) else None
        if hits is None:
            return
        total = hits.get("total")
        if isinstance(total, dict):
            hits["total"] = total.get("value")
        elif total is None:
            hits["total"] = -1
        for h in hits.get("hits", []):
            for ih in (h.get("inner_hits") or {}).values():
                _total_hits_as_int(ih)

    def _apply_typed_keys(resp, body):
        """?typed_keys=true prefixes agg names with their internal type
        (RestSearchAction TYPED_KEYS_PARAM; e.g. `avg#name`, `sterms#name`)
        so clients can re-parse responses type-safely."""
        _NUMERIC_TYPES = {"long", "integer", "short", "byte", "double",
                          "float", "half_float", "scaled_float", "date",
                          "boolean"}

        def type_prefix(kind, spec, result):
            if kind == "terms":
                # prefix comes from the FIELD type, not the matched buckets
                # (an empty result must keep the same typed key)
                field = spec.get("field") if isinstance(spec, dict) else None
                for svc in node.indices.indices.values():
                    mapper = svc.mapper_service.get(field) if field else None
                    if mapper is not None:
                        return ("lterms" if mapper.type_name in _NUMERIC_TYPES
                                else "sterms")
                buckets = result.get("buckets") or []
                numeric = buckets and all(
                    isinstance(b.get("key"), (int, float))
                    and not isinstance(b.get("key"), bool) for b in buckets)
                return "lterms" if numeric else "sterms"
            if kind == "percentiles":
                if isinstance(spec, dict) and spec.get("hdr") is not None:
                    return "hdr_percentiles"
                return "tdigest_percentiles"
            if kind == "significant_terms":
                field = spec.get("field") if isinstance(spec, dict) else None
                for svc in node.indices.indices.values():
                    mapper = svc.mapper_service.get(field) if field else None
                    if mapper is not None:
                        return ("siglterms"
                                if mapper.type_name in _NUMERIC_TYPES
                                else "sigsterms")
                return "sigsterms"
            if kind == "significant_text":
                return "sigsterms"
            if kind == "sampler":
                return "sampler"
            if kind == "percentile_ranks":
                return "tdigest_percentile_ranks"
            if kind == "max_bucket" or kind == "min_bucket":
                return "bucket_metric_value"
            return kind

        def walk(aggs_out, aggs_spec):
            if not isinstance(aggs_out, dict) or not aggs_spec:
                return
            for name, spec in list(aggs_spec.items()):
                if name not in aggs_out or not isinstance(spec, dict):
                    continue
                kinds = [k for k in spec
                         if k not in ("aggs", "aggregations", "meta")]
                if len(kinds) != 1:
                    continue
                result = aggs_out.pop(name)
                aggs_out[f"{type_prefix(kinds[0], spec[kinds[0]], result)}"
                         f"#{name}"] = result
                sub = spec.get("aggs") or spec.get("aggregations")
                if sub and isinstance(result, dict):
                    buckets = result.get("buckets")
                    if isinstance(buckets, dict):  # named filters buckets
                        buckets = buckets.values()
                    for bucket in buckets or []:
                        walk(bucket, sub)
                    walk(result, sub)

        if isinstance(resp.get("aggregations"), dict):
            walk(resp["aggregations"],
                 body.get("aggs") or body.get("aggregations") or {})
        # suggesters prefix too: suggest.{kind}#{name}
        if isinstance(resp.get("suggest"), dict):
            for name, sspec in (body.get("suggest") or {}).items():
                if name not in resp["suggest"] or not isinstance(sspec, dict):
                    continue
                kind = next((k for k in ("term", "phrase", "completion")
                             if k in sspec), None)
                if kind:
                    resp["suggest"][f"{kind}#{name}"] = \
                        resp["suggest"].pop(name)

    def bulk(req):
        t_parse = time.perf_counter_ns()
        ops = req.ndjson()
        parse_nanos = time.perf_counter_ns() - t_parse
        with _rest_telemetry(req, node, "indices:data/write/bulk",
                             force_trace=req.bool_param("trace"),
                             description=f"requests[{len(ops)}]",
                             parse_nanos=parse_nanos):
            t0 = time.perf_counter_ns()
            resp = node.bulk(ops,
                             default_index=req.params.get("index"),
                             refresh=req.param("refresh"),
                             source_filter=_get_source_filter(req))
            telemetry.record_span("bulk.execute",
                                  time.perf_counter_ns() - t0,
                                  ops=len(ops))
            return 200, resp

    rc.register("POST", "/_bulk", bulk)
    rc.register("PUT", "/_bulk", bulk)
    rc.register("POST", "/{index}/_bulk", bulk)

    def mget(req):
        sf = req.param("stored_fields")
        return 200, node.mget(
            req.json() or {}, req.params.get("index"),
            stored_fields=sf.split(",") if sf else None,
            realtime=req.param("realtime") not in ("false", False),
            refresh=req.param("refresh") in ("true", "", True),
            source_filter=_get_source_filter(req))

    rc.register("GET", "/_mget", mget)
    rc.register("POST", "/_mget", mget)
    rc.register("GET", "/{index}/_mget", mget)
    rc.register("POST", "/{index}/_mget", mget)

    # ---------------------------------------------------------------- search
    def search(req):
        t_parse = time.perf_counter_ns()
        body = req.json() or {}
        parse_nanos = time.perf_counter_ns() - t_parse
        # every search runs as a live task under telemetry: sampled by
        # telemetry.tracing.sample_rate, forced by ?trace=true or a
        # profile body; X-Opaque-ID rides the task, the trace, and any
        # slow-log breach
        with _rest_telemetry(
                req, node, "indices:data/read/search",
                force_trace=(req.bool_param("trace")
                             or bool(body.get("profile"))),
                description=f"indices[{req.params.get('index') or '_all'}]",
                parse_nanos=parse_nanos) as tr:
            status, resp = _search_inner(req, body)
            if tr is not None and isinstance(resp, dict) \
                    and body.get("profile"):
                from elasticsearch_tpu.search.profile import trace_profile
                resp.setdefault("profile", {})["trace"] = trace_profile(tr)
            return status, resp

    def _search_inner(req, body):
        # URI-search params (q=, size=, from=, sort=)
        body = apply_uri_query(req, body)
        for p, key in (("size", "size"), ("from", "from")):
            v = req.int_param(p)
            if v is not None:
                body[key] = v
        pfs = req.param("pre_filter_shard_size")
        if pfs is not None:
            if int(pfs) < 1:
                raise IllegalArgumentError("preFilterShardSize must be >= 1")
            body["__pre_filter_shard_size__"] = int(pfs)
        tth = req.param("track_total_hits")
        if tth is not None:
            body["track_total_hits"] = (
                True if tth in ("true", "") else
                False if tth == "false" else int(tth))
        sort = req.param("sort")
        if sort:
            body["sort"] = [
                {s.split(":")[0]: s.split(":")[1]} if ":" in s else s
                for s in sort.split(",")]
        # URL-level _source / docvalue_fields filtering (RestSearchAction
        # parses these into the SearchSourceBuilder)
        src_inc = req.param("_source_includes")
        src_exc = req.param("_source_excludes")
        if src_inc is not None or src_exc is not None:
            body["_source"] = {
                "includes": src_inc.split(",") if src_inc else [],
                "excludes": src_exc.split(",") if src_exc else []}
        elif req.param("_source") is not None:
            raw = req.param("_source")
            body["_source"] = ({"true": True, "false": False}.get(raw, None)
                               if raw in ("true", "false")
                               else raw.split(","))
        dvf = req.param("docvalue_fields")
        if dvf:
            body["docvalue_fields"] = dvf.split(",")
        if req.bool_param("seq_no_primary_term", False):
            body["seq_no_primary_term"] = True
        if req.bool_param("version", False):
            body["version"] = True
        st = req.param("search_type")
        if st in ("query_and_fetch", "dfs_query_and_fetch"):
            raise IllegalArgumentError(
                f"Unsupported search type [{st}]")
        brs = req.int_param("batched_reduce_size")
        if brs is None and body.get("batched_reduce_size") is not None:
            brs = int(body["batched_reduce_size"])
        if brs is not None:
            if brs < 2:
                raise IllegalArgumentError("batchedReduceSize must be >= 2")
            body["batched_reduce_size"] = brs
        pfss = req.int_param("pre_filter_shard_size")
        if pfss is not None and pfss < 1:
            raise IllegalArgumentError("preFilterShardSize must be >= 1")
        tt = body.get("track_total_hits")
        if isinstance(tt, int) and not isinstance(tt, bool) and tt < -1:
            raise IllegalArgumentError(
                f"[track_total_hits] parameter must be positive or "
                f"equals to -1, got {tt}")
        if req.bool_param("rest_total_hits_as_int", False):
            if isinstance(tt, int) and not isinstance(tt, bool) and tt != -1:
                raise IllegalArgumentError(
                    f"[rest_total_hits_as_int] cannot be used if the "
                    f"tracking of total hits is not accurate, got {tt}")
        scroll = req.param("scroll")
        if scroll:
            if body.get("size") == 0:
                raise IllegalArgumentError(
                    "[size] cannot be [0] in a scroll context")
            if req.param("request_cache") is not None:
                raise IllegalArgumentError(
                    "[request_cache] cannot be used in a scroll context")
            if body.get("track_total_hits") is False:
                raise IllegalArgumentError(
                    "disabling [track_total_hits] is not allowed in a "
                    "scroll context")
            check_scroll_keep_alive(node, scroll)
            resp = node.search_scroll_start(
                req.params.get("index"), body, keep_alive=scroll,
                ignore_throttled=req.bool_param("ignore_throttled", True))
        else:
            if req.param("request_cache") is not None:
                # the URI param form of the per-request cache opt-in/out
                # (RestSearchAction); the cache policy reads it from the
                # body (search/caches.RequestCache)
                body["request_cache"] = req.bool_param(
                    "request_cache", True)
            resp = node.search(req.params.get("index"), body,
                               ignore_throttled=req.bool_param(
                                   "ignore_throttled", True),
                               ignore_unavailable=req.bool_param(
                                   "ignore_unavailable", False),
                               allow_no_indices=req.bool_param(
                                   "allow_no_indices", True),
                               expand_wildcards=req.param(
                                   "expand_wildcards"))
        if req.bool_param("rest_total_hits_as_int", False):
            _total_hits_as_int(resp)
        if req.bool_param("typed_keys", False):
            _apply_typed_keys(resp, body)
        return 200, resp

    rc.register("GET", "/_search", search)
    rc.register("POST", "/_search", search)
    rc.register("GET", "/{index}/_search", search)
    rc.register("POST", "/{index}/_search", search)

    def count(req):
        body = apply_uri_query(req, req.json() or {})
        return 200, node.count(req.params.get("index"), body)

    rc.register("GET", "/_count", count)
    rc.register("POST", "/_count", count)
    rc.register("GET", "/{index}/_count", count)
    rc.register("POST", "/{index}/_count", count)

    def msearch(req):
        lines = req.ndjson()
        if req.bool_param("rest_total_hits_as_int", False):
            for i in range(1, len(lines), 2):
                tth = (lines[i] or {}).get("track_total_hits")
                if isinstance(tth, int) and not isinstance(tth, bool):
                    raise IllegalArgumentError(
                        "[rest_total_hits_as_int] cannot be used if the "
                        f"tracking of total hits is not accurate, got {tth}")
        resp = node.msearch(lines)
        bodies = [lines[i] for i in range(1, len(lines), 2)]
        for i, r in enumerate(resp.get("responses", [])):
            if req.bool_param("rest_total_hits_as_int", False):
                _total_hits_as_int(r)
            if req.bool_param("typed_keys", False) and i < len(bodies):
                _apply_typed_keys(r, bodies[i])
        return 200, resp

    rc.register("GET", "/_msearch", msearch)
    rc.register("POST", "/_msearch", msearch)
    rc.register("POST", "/{index}/_msearch", msearch)

    def analyze(req):
        return 200, node.analyze(req.json() or {},
                                 index=req.params.get("index"))

    rc.register("GET", "/_analyze", analyze)
    rc.register("POST", "/_analyze", analyze)
    rc.register("GET", "/{index}/_analyze", analyze)
    rc.register("POST", "/{index}/_analyze", analyze)

    # ----------------------------------------------------------- index admin
    def create_index(req):
        body = req.json() or {}
        svc = node.create_index_with_templates(
            req.params["index"], settings=body.get("settings"),
            mappings=body.get("mappings"), aliases=body.get("aliases"))
        return 200, {"acknowledged": True, "shards_acknowledged": True,
                     "index": svc.name}

    def delete_index(req):
        expr = req.params["index"]
        ignore_unavailable = req.bool_param("ignore_unavailable", False)
        allow_no = req.bool_param("allow_no_indices", True)
        to_delete = []
        for part in expr.split(","):
            part = part.strip()
            if not part:
                continue
            if "*" not in part and part != "_all":
                if part not in node.indices.indices:
                    if ignore_unavailable:
                        # lenient options skip alias and missing names alike
                        # (indices.delete/10_basic "ignore unavailable")
                        continue
                    # aliases may not be delete targets
                    if any(part in s.aliases
                           for s in node.indices.indices.values()):
                        raise IllegalArgumentError(
                            f"The provided expression [{part}] matches an "
                            f"alias, specify the corresponding concrete "
                            f"indices instead.")
                    raise IndexNotFoundError(part)
                to_delete.append(part)
            else:
                import fnmatch as _fn
                pat = "*" if part == "_all" else part
                matched = [n for n in node.indices.indices
                           if _fn.fnmatch(n, pat)]
                if not matched and not allow_no:
                    raise IndexNotFoundError(part)
                to_delete.extend(matched)
        for name in dict.fromkeys(to_delete):
            node.indices.delete_index(name)
        return 200, {"acknowledged": True}

    def _resolve_with_options(req, expr):
        """IndicesOptions resolution shared by the index-info APIs:
        ignore_unavailable drops missing concretes, allow_no_indices
        tolerates empty wildcards, expand_wildcards picks open/closed."""
        expand = req.param("expand_wildcards") or "open"
        if isinstance(expand, (list, tuple)):
            expand = ",".join(str(t) for t in expand)
        tokens = {t for t in expand.split(",") if t}
        want_open = bool(tokens & {"open", "all"}) or not tokens
        want_closed = bool(tokens & {"closed", "all"})
        ignore_unavailable = req.bool_param("ignore_unavailable", False)
        allow_no = req.bool_param("allow_no_indices", True)
        out = []
        for part in (expr or "_all").split(","):
            part = part.strip()
            if not part:
                continue
            if "*" in part or part == "_all":
                import fnmatch as _fn
                pat = "*" if part == "_all" else part
                for n, svc in node.indices.indices.items():
                    if not _fn.fnmatch(n, pat):
                        continue
                    if svc.closed and not want_closed:
                        continue
                    if not svc.closed and not want_open:
                        continue
                    if svc.hidden and not (tokens & {"all", "hidden"}) \
                            and not (pat.startswith(".")
                                     and n.startswith(".")):
                        continue
                    out.append(svc)
            else:
                try:
                    svc = node.indices.get(part)
                except SearchEngineError:
                    if ignore_unavailable:
                        continue
                    raise
                out.append(svc)
        if not out and not allow_no:
            raise IndexNotFoundError(expr)
        seen = set()
        return [s for s in out
                if s.name not in seen and not seen.add(s.name)]

    def get_index(req):
        from elasticsearch_tpu.indices.service import IndicesService
        for part in req.params["index"].split(","):
            part = part.strip()
            if part.startswith("_") and part not in ("_all",):
                # reserved names are a request error, not a missing index
                IndicesService.validate_index_name(part)
        human = req.bool_param("human", False)
        out = {}
        for svc in _resolve_with_options(req, req.params["index"]):
            idx_settings = {
                **{k.replace("index.", "", 1): v
                   for k, v in svc.settings.as_flat_dict().items()},
                "uuid": svc.uuid,
                "creation_date": str(svc.creation_date),
                "provided_name": svc.name,
            }
            if human:
                idx_settings["creation_date_string"] = _fmt_iso_millis(
                    svc.creation_date)
                idx_settings.setdefault("version", {})
                if isinstance(idx_settings["version"], dict):
                    idx_settings["version"]["created_string"] = __version__
                    idx_settings["version"].setdefault("created", "8000099")
            out[svc.name] = {
                "aliases": svc.aliases,
                "mappings": svc.mapper_service.to_dict(),
                "settings": {"index": idx_settings},
            }
        if not out and not req.bool_param("ignore_unavailable", False) \
                and "*" not in req.params["index"] \
                and req.bool_param("allow_no_indices", True) is False:
            raise IndexNotFoundError(req.params["index"])
        return 200, out

    def index_exists(req):
        return (200 if all(node.indices.exists(p) or "*" in p
                           for p in req.params["index"].split(","))
                else 404), None

    rc.register("PUT", "/{index}", create_index)
    rc.register("DELETE", "/{index}", delete_index)
    rc.register("GET", "/{index}", get_index)
    rc.register("HEAD", "/{index}", index_exists)

    def get_mapping(req):
        out = {}
        for svc in _resolve_with_options(req, req.params.get("index")):
            out[svc.name] = {"mappings": svc.mapper_service.to_dict()}
        return 200, out

    def put_mapping(req):
        # wildcard/_all expressions update every matching index
        # (MetaDataMappingService applies to all resolved concretes);
        # matching nothing is an error, not a silent ack
        body = req.json() or {}
        if "_doc" in body and isinstance(body["_doc"], dict) \
                and "properties" in body["_doc"]:
            raise IllegalArgumentError(
                "Types cannot be provided in put mapping requests")
        resolved = node.indices.resolve(req.params["index"])
        if not resolved:
            raise IndexNotFoundError(req.params["index"])
        for svc in resolved:
            node.indices.update_mapping(svc.name, body)
        return 200, {"acknowledged": True}

    def get_field_mapping(req):
        """GET [/{index}]/_mapping/field/{fields} (reference:
        RestGetFieldMappingAction / TransportGetFieldMappingsAction):
        per-index {mappings: {full_name: {full_name, mapping: {leaf: def}}}};
        unknown fields yield an empty mappings object."""
        import fnmatch
        fields = [f.strip() for f in req.params["fields"].split(",")]
        include_defaults = req.param("include_defaults") in ("true", "", True)
        out = {}
        for svc in node.indices.resolve(req.params.get("index")):
            ms = svc.mapper_service
            matched = {}
            for pat in fields:
                if "*" in pat:
                    names = [n for n in ms.field_names()
                             if fnmatch.fnmatchcase(n, pat)]
                else:
                    names = [pat] if ms.get_raw(pat) is not None else []
                for full in names:
                    mapper = ms.get_raw(full)
                    if mapper is None or mapper.type_name == "nested":
                        continue
                    d = mapper.to_def()
                    if include_defaults and d.get("type") == "text" \
                            and "analyzer" not in d:
                        d["analyzer"] = "default"
                    leaf = full.rsplit(".", 1)[-1]
                    matched[full] = {"full_name": full, "mapping": {leaf: d}}
            out[svc.name] = {"mappings": matched}
        return 200, out

    rc.register("GET", "/_mapping", get_mapping)
    rc.register("GET", "/{index}/_mapping", get_mapping)
    rc.register("GET", "/_mapping/field/{fields}", get_field_mapping)
    rc.register("GET", "/{index}/_mapping/field/{fields}", get_field_mapping)
    rc.register("PUT", "/{index}/_mapping", put_mapping)
    rc.register("POST", "/{index}/_mapping", put_mapping)

    def _settings_str(v):
        # the reference renders every setting value as a string
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, (list, tuple)):
            return [_settings_str(x) for x in v]
        return str(v)

    _SETTINGS_DEFAULTS = {
        "index.refresh_interval": "1s",
        "index.max_result_window": "10000",
        "index.max_inner_result_window": "100",
        "index.max_rescore_window": "10000",
        "index.flush_after_merge": "512mb",
        "index.translog.durability": "request",
        "index.translog.flush_threshold_size": "512mb",
        "index.write.wait_for_active_shards": "1",
        "index.highlight.max_analyzed_offset": "1000000",
    }

    def _nest(flat: dict) -> dict:
        nested: dict = {}
        for k, v in flat.items():
            parts = k.split(".")
            cur = nested
            for p in parts[:-1]:
                cur = cur.setdefault(p, {})
            cur[parts[-1]] = v
        return nested

    def get_settings(req):
        import fnmatch as _fn
        name_filter = req.params.get("name")
        patterns = ([p.strip() for p in name_filter.split(",")]
                    if name_filter and name_filter not in ("_all", "*")
                    else None)
        flat_mode = req.bool_param("flat_settings", False)
        include_defaults = req.bool_param("include_defaults", False)
        out = {}
        for svc in node.indices.resolve(req.params.get("index")):
            flat = {"index.uuid": svc.uuid,
                    "index.provided_name": svc.name,
                    "index.creation_date": str(svc.creation_date),
                    **svc.settings.as_flat_dict()}
            if patterns is not None:
                flat = {k: v for k, v in flat.items()
                        if any(_fn.fnmatch(k, p) for p in patterns)}
            flat = {k: _settings_str(v) for k, v in flat.items()
                    if v is not None}
            entry = {"settings": flat if flat_mode
                     else {"index": _nest({k.replace("index.", "", 1): v
                                           for k, v in flat.items()})}}
            if include_defaults:
                defaults = {k: v for k, v in _SETTINGS_DEFAULTS.items()
                            if k not in flat}
                entry["defaults"] = defaults if flat_mode else _nest(defaults)
            out[svc.name] = entry
        return 200, out

    rc.register("GET", "/_settings", get_settings)
    rc.register("GET", "/{index}/_settings", get_settings)
    rc.register("GET", "/_settings/{name}", get_settings)
    rc.register("GET", "/{index}/_settings/{name}", get_settings)

    def _shards_of(services) -> dict:
        n = sum(len(svc.shards) for svc in services)
        return {"_shards": {"total": n, "successful": n, "failed": 0}}

    def refresh(req):
        services = node.indices.resolve_open(req.params.get("index"))
        for svc in services:
            svc.refresh()
        return 200, _shards_of(services)

    def flush(req):
        force = req.param("force") in ("true", "", True)
        wait = req.param("wait_if_ongoing")
        if force and wait in ("false", False):
            from elasticsearch_tpu.common.errors import (
                ActionRequestValidationError)
            raise ActionRequestValidationError(
                "Validation Failed: 1: wait_if_ongoing must be true for a "
                "force flush;")
        services = node.indices.resolve_open(req.params.get("index"))
        for svc in services:
            svc.flush()
        return 200, _shards_of(services)

    def forcemerge(req):
        if req.param("only_expunge_deletes") in ("true", "", True) \
                and req.param("max_num_segments") is not None:
            from elasticsearch_tpu.common.errors import (
                ActionRequestValidationError)
            raise ActionRequestValidationError(
                "Validation Failed: 1: cannot set only_expunge_deletes and "
                "max_num_segments at the same time, those two parameters "
                "are mutually exclusive;")
        services = node.indices.resolve_open(req.params.get("index"))
        for svc in services:
            svc.force_merge()
        return 200, _shards_of(services)

    rc.register("POST", "/_refresh", refresh)
    rc.register("POST", "/{index}/_refresh", refresh)
    rc.register("GET", "/{index}/_refresh", refresh)
    rc.register("POST", "/_flush", flush)
    rc.register("POST", "/{index}/_flush", flush)
    rc.register("POST", "/_forcemerge", forcemerge)
    rc.register("POST", "/{index}/_forcemerge", forcemerge)

    def index_stats(req):
        metric = req.params.get("metric")
        metrics = [m.strip() for m in metric.split(",")] if metric else None
        expand = req.param("expand_wildcards") or ""
        if isinstance(expand, (list, tuple)):
            expand = ",".join(str(t) for t in expand)
        return 200, node.index_stats(
            req.params.get("index"), metrics,
            level=req.param("level") or "indices",
            fields=req.param("fields"),
            fielddata_fields=req.param("fielddata_fields"),
            completion_fields=req.param("completion_fields"),
            groups=req.param("groups"),
            include_segment_file_sizes=req.bool_param(
                "include_segment_file_sizes", False),
            include_unloaded_segments=req.bool_param(
                "include_unloaded_segments", False),
            forbid_closed_indices=req.bool_param(
                "forbid_closed_indices", True),
            expand_hidden=any(t in ("all", "hidden")
                              for t in expand.split(",") if t))

    rc.register("GET", "/_stats", index_stats)
    rc.register("GET", "/_stats/{metric}", index_stats)
    rc.register("GET", "/{index}/_stats", index_stats)
    rc.register("GET", "/{index}/_stats/{metric}", index_stats)

    def aliases_post(req):
        node.indices.update_aliases((req.json() or {}).get("actions", []))
        return 200, {"acknowledged": True}

    def _split_alias_patterns(patterns):
        """`-pat` subtracts when a wildcard include appeared earlier OR the
        exclusion itself is a wildcard pattern; otherwise `-name` is a
        literal name (IndexNameExpressionResolver wildcard resolution)."""
        includes, excludes = [], []
        seen_wildcard = False
        for p in patterns:
            if p.startswith("-") and (seen_wildcard or "*" in p):
                excludes.append(p[1:])
                if "*" in p:  # a wildcard EXCLUSION also arms later `-name`s
                    seen_wildcard = True
                continue
            includes.append(p)
            if "*" in p or p == "_all":
                seen_wildcard = True
        return includes, excludes

    def _alias_matches(alias: str, patterns) -> bool:
        import fnmatch as _fn
        includes, excludes = _split_alias_patterns(patterns)
        if not any(p in ("_all", "*") or _fn.fnmatch(alias, p)
                   for p in includes):
            return False
        return not any(p in ("_all", "*") or _fn.fnmatch(alias, p)
                       for p in excludes)

    def _missing_aliases(patterns, found) -> list:
        includes, _ = _split_alias_patterns(patterns)
        return [p for p in includes
                if "*" not in p and p != "_all" and p not in found]

    def _alias_missing_response(missing, extra=None):
        label = "alias" if len(missing) == 1 else "aliases"
        return 404, {"error": f"{label} [{','.join(sorted(missing))}] missing",
                     "status": 404, **(extra or {})}

    def get_aliases(req):
        """GET [/{index}]/_alias[/{name}] (TransportGetAliasesAction):
        name filters (csv, wildcards, _all, `-` exclusions); concrete
        names matching nothing anywhere are a 404 `alias(es) [x] missing`."""
        name = req.params.get("alias")
        patterns = [p.strip() for p in name.split(",")] if name else None
        out = {}
        tokens = {t.strip() for t in
                  str(req.param("expand_wildcards") or "all").split(",") if t}
        want_open = bool(tokens & {"open", "all"})
        want_closed = bool(tokens & {"closed", "all"})
        resolved = node.indices.resolve(req.params.get("index"),
                                        expand_closed=want_closed)
        resolved = [s for s in resolved
                    if (want_open and not s.closed)
                    or (want_closed and s.closed)]

        def render(spec):
            # alias "routing" renders split into index_/search_routing
            # (AliasMetadata#toXContent)
            spec = dict(spec or {})
            routing = spec.pop("routing", None)
            if routing is not None:
                spec.setdefault("index_routing", routing)
                spec.setdefault("search_routing", routing)
            return spec

        for svc in resolved:
            if patterns is None:
                out[svc.name] = {"aliases": {a: render(s)
                                             for a, s in svc.aliases.items()}}
                continue
            matched = {a: render(spec) for a, spec in svc.aliases.items()
                       if _alias_matches(a, patterns)}
            if matched:
                out[svc.name] = {"aliases": matched}
        if patterns:
            # missing is judged WITHIN the requested index scope
            # (RestGetAliasesAction checks the response, not the cluster)
            scope_aliases = {a for svc in resolved for a in svc.aliases}
            missing = _missing_aliases(patterns, scope_aliases)
            if missing:
                return _alias_missing_response(missing, out)
        return 200, out

    def alias_exists(req):
        status, _body = get_aliases(req)
        return (200 if status == 200 else 404), None

    def put_alias(req):
        alias = req.params.get("alias")
        if alias:
            bad = set('#\\/*?"<>| ,:')
            if any(c in bad for c in alias) \
                    or alias.startswith(("-", "_", "+")):
                raise IllegalArgumentError(
                    f"Invalid alias name [{alias}]: must be lowercase and "
                    "must not contain spaces, commas, or special characters")
            if alias in node.indices.indices:
                raise IllegalArgumentError(
                    f"Invalid alias name [{alias}]: an index or data stream "
                    "exists with the same name as the alias")
        body = req.json() or {}
        spec = {k: v for k, v in body.items()
                if k in ("filter", "routing", "index_routing",
                         "search_routing", "is_write_index", "is_hidden")}
        targets = node.indices.resolve(req.params["index"])
        if not targets:
            raise IndexNotFoundError(req.params["index"])
        for svc in targets:
            node.indices.update_aliases([{"add": {
                "index": svc.name, "alias": req.params["alias"], **spec}}])
        return 200, {"acknowledged": True}

    def delete_alias(req):
        """DELETE /{index}/_alias/{name}: names/indices take csv +
        wildcards. Validation-first and ATOMIC: a missing concrete name
        404s with NOTHING removed (the reference validates all alias
        actions before mutating)."""
        patterns = [p.strip() for p in req.params["alias"].split(",")]
        targets = node.indices.resolve(req.params["index"])
        if not targets:
            raise IndexNotFoundError(req.params["index"])
        removals = [(svc.name, a) for svc in targets
                    for a in list(svc.aliases)
                    if _alias_matches(a, patterns)]
        scope_aliases = {a for _, a in removals}
        missing = _missing_aliases(patterns, scope_aliases)
        if missing:
            return _alias_missing_response(missing)
        for index_name, alias in removals:
            node.indices.update_aliases([{"remove": {
                "index": index_name, "alias": alias}}])
        return 200, {"acknowledged": True}

    rc.register("POST", "/_aliases", aliases_post)
    for path in ("/_alias", "/{index}/_alias", "/_alias/{alias}",
                 "/{index}/_alias/{alias}"):
        rc.register("GET", path, get_aliases)
        rc.register("HEAD", path, alias_exists)
    for path in ("/{index}/_alias/{alias}", "/{index}/_aliases/{alias}"):
        rc.register("PUT", path, put_alias)
        rc.register("POST", path, put_alias)
        rc.register("DELETE", path, delete_alias)

    # ---------------------------------------------------------------- cluster
    def cluster_health(req):
        # wait_for_* resolves immediately: single-node state is
        # deterministic, so a target is either already met or never will
        # be within the request (reference waits on a state observer)
        expand = req.param("expand_wildcards") or "all"
        if isinstance(expand, (list, tuple)):
            expand = ",".join(str(t) for t in expand)
        out = node.cluster_health(req.params.get("index"),
                                  level=req.param("level", "cluster"),
                                  expand_wildcards=expand)
        timed_out = bool(out.get("timed_out"))
        want = req.param("wait_for_status")
        order = {"green": 0, "yellow": 1, "red": 2}
        if want and order.get(out["status"], 2) > order.get(want, 0):
            timed_out = True
        wn = req.param("wait_for_nodes")
        if wn:
            import re as _re
            m = _re.fullmatch(r"(>=|<=|>|<|==|eq\()?\s*(\d+)\)?", str(wn))
            if m:
                op = m.group(1) or ">="
                n = int(m.group(2))
                have = out["number_of_nodes"]
                ok = {">=": have >= n, "<=": have <= n, ">": have > n,
                      "<": have < n, "==": have == n,
                      "eq(": have == n}[op]
                if not ok:
                    timed_out = True
        was = req.param("wait_for_active_shards")
        if was and was != "all" and int(was) > out["active_shards"]:
            timed_out = True
        if timed_out:
            out["timed_out"] = True
            return 408, out
        return 200, out

    def cluster_stats(req):
        import resource as _res
        import shutil as _sh
        total_docs = sum(s.doc_count() for s in node.indices.indices.values())
        segs = sum(len(sh.engine.segments)
                   for s in node.indices.indices.values()
                   for sh in s.shards)
        # field type census incl. synthesized object parents, with
        # per-index attribution (MappingStats)
        from elasticsearch_tpu.node_admin import _index_field_caps
        field_types: dict = {}
        for s in node.indices.indices.values():
            per_index_types: dict = {}
            for _path, (t, _se, _ag, _m) in _index_field_caps(
                    s.mapper_service).items():
                per_index_types[t] = per_index_types.get(t, 0) + 1
            for t, c in per_index_types.items():
                e = field_types.setdefault(t, {"count": 0, "indices": 0})
                e["count"] += c
                e["indices"] += 1
        du = _sh.disk_usage(node.data_path)
        mem_total = 8 * 1024 ** 3
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        mem_total = int(line.split()[1]) * 1024
                        break
        except OSError:
            pass
        mem_used = mem_total // 2
        health = node.cluster_health()
        return 200, {
            "cluster_name": node.cluster_name,
            "cluster_uuid": node.node_id,
            "timestamp": int(time.time() * 1000),
            "status": health["status"],
            "indices": {
                "count": len(node.indices.indices),
                "shards": {"total": sum(
                    s.num_shards for s in node.indices.indices.values())},
                "docs": {"count": total_docs, "deleted": 0},
                "store": {"size_in_bytes": 0, "reserved_in_bytes": 0},
                "fielddata": {"memory_size_in_bytes": 0, "evictions": 0},
                "query_cache": {"memory_size_in_bytes": 0, "hit_count": 0,
                                "miss_count": 0, "evictions": 0},
                "completion": {"size_in_bytes": 0},
                "segments": {"count": segs, "memory_in_bytes": 0},
                "mappings": {"field_types": [
                    {"name": t, "count": e["count"],
                     "index_count": e["indices"]}
                    for t, e in sorted(field_types.items())]},
                "analysis": {"analyzer_types": [], "char_filter_types": [],
                             "filter_types": [], "tokenizer_types": []},
            },
            "nodes": {
                "count": {"total": 1, "data": 1, "master": 1, "ingest": 1,
                          "coordinating_only": 0,
                          "voting_only": 0, "ml": 1,
                          "remote_cluster_client": 1, "transform": 1},
                "versions": [__version__],
                "os": {"available_processors": _os_cpus(),
                       "allocated_processors": _os_cpus(),
                       "names": [{"name": "Linux", "count": 1}],
                       "mem": {"total_in_bytes": mem_total,
                               "free_in_bytes": mem_total - mem_used,
                               "used_in_bytes": mem_used,
                               "free_percent": 50, "used_percent": 50}},
                "process": {"cpu": {"percent": 1},
                            "open_file_descriptors": {"min": 64, "max": 512,
                                                      "avg": 128}},
                "jvm": {"versions": [], "mem": {
                    "heap_used_in_bytes": 256 * 1024 * 1024,
                    "heap_max_in_bytes": 4 * 1024 ** 3},
                    "threads": 16, "max_uptime_in_millis": 1},
                "fs": {"total_in_bytes": du.total, "free_in_bytes": du.free,
                       "available_in_bytes": du.free},
                "plugins": [{"name": p, "version": __version__}
                            for p in ("sql", "eql", "ilm")],
                "network_types": {"transport_types": {"tcp": 1},
                                  "http_types": {"asyncio": 1}},
                "discovery_types": {"zen": 1},
                "packaging_types": [{"flavor": "tpu", "type": "source",
                                     "count": 1}],
            },
        }

    def _os_cpus():
        import os as _os
        return _os.cpu_count() or 1

    def cluster_state(req):
        """GET /_cluster/state[/{metric}[/{index}]] — metric filtering
        (ClusterStateRequest: version, master_node, nodes, metadata,
        routing_table, routing_nodes, blocks; cluster_name + cluster_uuid
        always present)."""
        from elasticsearch_tpu.common.settings import setting_bool
        _VALID_METRICS = {"_all", "version", "master_node", "nodes",
                          "metadata", "routing_table", "routing_nodes",
                          "blocks"}
        metric = req.params.get("metric")
        metrics = ({m.strip() for m in metric.split(",")} if metric else None)
        if metrics is not None:
            unknown = metrics - _VALID_METRICS
            if unknown:
                raise IllegalArgumentError(
                    f"request [/_cluster/state/{metric}] contains "
                    f"unrecognized metric: [{sorted(unknown)[0]}]")
            if "_all" in metrics:
                metrics = None  # _all anywhere in the list = everything
        index_filter = req.params.get("index")
        tokens = {t.strip() for t in
                  str(req.param("expand_wildcards") or "open,closed")
                  .split(",") if t.strip()}
        want_open = bool(tokens & {"open", "all"})
        want_closed = bool(tokens & {"closed", "all"})
        ignore_unavailable = req.bool_param("ignore_unavailable", False)
        allow_no = req.bool_param("allow_no_indices", True)
        if index_filter:
            if ignore_unavailable:
                svcs = []
                for part in index_filter.split(","):
                    try:
                        svcs.extend(node.indices.resolve(
                            part.strip(), expand_closed=True))
                    except SearchEngineError:
                        continue
            else:
                svcs = node.indices.resolve(index_filter,
                                            expand_closed=True)
            if not svcs and not allow_no:
                raise IndexNotFoundError(index_filter)
        else:
            svcs = list(node.indices.indices.values())
        svcs = [s for s in svcs
                if (want_open and not s.closed)
                or (want_closed and s.closed)]
        meta = {}
        routing = {}
        index_blocks = {}
        for svc in svcs:
            meta[svc.name] = {"settings": svc.settings.as_flat_dict(),
                              "mappings": svc.mapper_service.to_dict(),
                              "aliases": list(svc.aliases),
                              "state": "close" if svc.closed else "open"}
            routing[svc.name] = {"shards": {
                str(s.shard_id): [{"state": "STARTED", "primary": True,
                                   "node": node.node_id,
                                   "shard": s.shard_id, "index": svc.name}]
                for s in svc.shards}}
            b = {}
            if setting_bool(svc.settings.get("index.blocks.read_only")):
                b["5"] = {"description": "index read-only (api)",
                          "retryable": False,
                          "levels": ["write", "metadata_write"]}
            if setting_bool(svc.settings.get("index.blocks.write")):
                b["8"] = {"description": "index write (api)",
                          "retryable": False, "levels": ["write"]}
            if b:
                index_blocks[svc.name] = b
        sections = {
            "version": 1,
            "master_node": node.node_id,
            "blocks": {"indices": index_blocks} if index_blocks else {},
            "nodes": {node.node_id: {"name": node.node_name}},
            "metadata": {"indices": meta,
                         "cluster_uuid": node.node_id},
            "routing_table": {"indices": routing},
            "routing_nodes": {"unassigned": [],
                              "nodes": {node.node_id: [
                                  e for r in routing.values()
                                  for shards in r["shards"].values()
                                  for e in shards]}},
        }
        out = {"cluster_name": node.cluster_name,
               "cluster_uuid": node.node_id,
               "state_uuid": node.node_id}
        for key, value in sections.items():
            if metrics is None or key in metrics:
                out[key] = value
        return 200, out

    _NODES_INFO_METRICS = {"settings", "os", "process", "jvm",
                           "thread_pool", "transport", "http", "plugins",
                           "ingest", "aggregations", "indices", "_all"}
    _INFO_BASE_KEYS = {"name", "roles", "transport_address", "host", "ip",
                       "version", "build_flavor", "build_type",
                       "build_hash", "attributes"}

    def _filter_info(info, metrics):
        if not metrics or "_all" in metrics:
            return info
        keep = set(metrics)
        info = dict(info)
        info["nodes"] = {
            nid: {k: v for k, v in sec.items()
                  if k in keep or k in _INFO_BASE_KEYS}
            for nid, sec in info["nodes"].items()}
        return info

    def nodes_info(req):
        # /_nodes[/{selector-or-metrics}[/{metrics}]] — a lone segment is
        # METRICS when every comma part is a known metric name, else a
        # node selector (RestNodesInfoAction's exact disambiguation).
        # Single-node build: every selector (_all/_local/_master/
        # data:true/names) resolves to this node.
        # the trie keeps the FIRST param name registered at a level, so
        # this segment may arrive as either {seg} or {node_id}
        seg = req.params.get("seg", req.params.get("node_id"))
        metrics_seg = req.params.get("metrics")
        metrics = []
        if metrics_seg is not None:
            metrics = [m for m in str(metrics_seg).split(",") if m]
            if metrics == ["stats"]:
                # /_nodes/{selector}/stats is the node-scoped STATS path
                return nodes_stats(req)
            for m in metrics:
                if m not in _NODES_INFO_METRICS:
                    raise IllegalArgumentError(
                        f"request [/_nodes/{seg}/{metrics_seg}] contains "
                        f"unrecognized metric: [{m}]")
        elif seg is not None:
            parts = [p for p in str(seg).split(",") if p]
            if parts and all(p in _NODES_INFO_METRICS for p in parts):
                metrics = parts
        info = _filter_info(node.nodes_info_api(), metrics)
        if req.bool_param("flat_settings", False):
            # ?flat_settings=true renders settings as dotted keys with
            # string values (Settings#toXContent flat mode)
            def _flatten(obj, prefix=""):
                out = {}
                for k, v in obj.items():
                    if isinstance(v, dict):
                        out.update(_flatten(v, f"{prefix}{k}."))
                    else:
                        out[f"{prefix}{k}"] = v if isinstance(v, str) \
                            else ("true" if v is True else
                                  "false" if v is False else str(v))
                return out
            for sec in info["nodes"].values():
                if isinstance(sec.get("settings"), dict):
                    sec["settings"] = _flatten(sec["settings"])
        return 200, info

    def nodes_stats(req):
        from elasticsearch_tpu.common.settings import setting_bool
        return 200, node.nodes_stats_api(
            level=req.param("level"),
            include_segment_file_sizes=setting_bool(
                req.param("include_segment_file_sizes")))

    rc.register("GET", "/_cluster/health", cluster_health)
    rc.register("GET", "/_cluster/health/{index}", cluster_health)
    rc.register("GET", "/_cluster/stats", cluster_stats)
    rc.register("GET", "/_cluster/state", cluster_state)
    rc.register("GET", "/_cluster/state/{metric}", cluster_state)
    rc.register("GET", "/_cluster/state/{metric}/{index}", cluster_state)
    rc.register("GET", "/_nodes", nodes_info)
    rc.register("GET", "/_nodes/{seg}", nodes_info)
    rc.register("GET", "/_nodes/{seg}/{metrics}", nodes_info)
    rc.register("GET", "/_nodes/stats", nodes_stats)

    # -------------------------------------------------------------------- cat
    # (reference: rest/action/cat/Rest*Action column catalogs + RestTable)
    from elasticsearch_tpu.rest.cat import (
        Bytes, Col, Millis, dir_size, render as cat_render,
    )

    def _index_health(svc) -> str:
        # single-node semantics: replicas can never assign, so any
        # replicated index reports yellow (ClusterHealthStatus)
        if svc.num_replicas > 0 and len(getattr(node, "cluster_nodes", [])) <= 1:
            return "yellow"
        return "green"

    def _store_bytes(svc) -> int:
        import os as _os
        tlog = sum(dir_size(_os.path.join(s.engine.path, "translog"))
                   for s in svc.shards)
        return max(sum(dir_size(s.engine.path) for s in svc.shards) - tlog, 0)

    _INDICES_COLS = [
        Col("health", "h", "current health status"),
        Col("status", "s", "open/close status"),
        Col("index", "i,idx", "index name"),
        Col("uuid", "id,uuid", "index uuid"),
        Col("pri", "p,shards.primary,shardsPrimary", "number of primary shards", right=True),
        Col("rep", "r,shards.replica,shardsReplica", "number of replica shards", right=True),
        Col("docs.count", "dc,docsCount", "available docs", right=True),
        Col("docs.deleted", "dd,docsDeleted", "deleted docs", right=True),
        Col("creation.date", "cd", "index creation date (millis)", right=True, default=False),
        Col("creation.date.string", "cds", "index creation date (ISO)", default=False),
        Col("store.size", "ss,storeSize", "store size of primaries and replicas", right=True),
        Col("pri.store.size", "", "store size of primaries", right=True),
    ]

    def cat_indices(req):
        expand = req.param("expand_wildcards") or ""
        if isinstance(expand, (list, tuple)):
            expand = ",".join(str(t) for t in expand)
        expand_hidden = any(t in ("all", "hidden")
                            for t in expand.split(",") if t)
        health_filter = req.param("health")
        rows = []
        for svc in node.indices.resolve(req.params.get("index"),
                                        expand_hidden=expand_hidden):
            health = _index_health(svc)
            if health_filter and health != health_filter:
                continue
            sb = _store_bytes(svc)
            rows.append([health, "close" if svc.closed else "open",
                         svc.name, svc.uuid, svc.num_shards,
                         svc.num_replicas, svc.doc_count(), 0,
                         svc.creation_date,
                         _fmt_iso_millis(svc.creation_date),
                         Bytes(sb), Bytes(sb)])
        # closed indices drop out of wildcard resolve(); list them too
        # when explicitly requested or matching the expression
        import fnmatch as _fn
        expr = req.params.get("index")
        emitted = {r[2] for r in rows}
        for name, svc in node.indices.indices.items():
            if not svc.closed or name in emitted:
                continue
            if expr in (None, "", "_all", "*") or any(
                    _fn.fnmatch(name, p.strip())
                    for p in (expr or "*").split(",")):
                health = _index_health(svc)
                if health_filter and health != health_filter:
                    continue
                rows.append([health, "close", name, svc.uuid,
                             svc.num_shards, svc.num_replicas,
                             None, None, svc.creation_date,
                             _fmt_iso_millis(svc.creation_date), None, None])
        rows.sort(key=lambda r: r[2])
        return cat_render(req, _INDICES_COLS, rows)

    _HEALTH_COLS = [
        Col("epoch", "t,time", "seconds since 1970-01-01 00:00:00", right=True),
        Col("timestamp", "ts,hms,hhmmss", "time in HH:MM:SS"),
        Col("cluster", "cl", "cluster name"),
        Col("status", "st", "health status"),
        Col("node.total", "nt,nodeTotal", "total number of nodes", right=True),
        Col("node.data", "nd,nodeData", "number of nodes that can store data", right=True),
        Col("shards", "t,sh,shards.total,shardsTotal", "total number of shards", right=True),
        Col("pri", "p,shards.primary,shardsPrimary", "number of primary shards", right=True),
        Col("relo", "r,shards.relocating,shardsRelocating", "number of relocating nodes", right=True),
        Col("init", "i,shards.initializing,shardsInitializing", "number of initializing nodes", right=True),
        Col("unassign", "u,shards.unassigned,shardsUnassigned", "number of unassigned shards", right=True),
        Col("pending_tasks", "pt,pendingTasks", "number of pending tasks", right=True),
        Col("max_task_wait_time", "mtwt,maxTaskWaitTime", "wait time of longest task pending"),
        Col("active_shards_percent", "asp,activeShardsPercent", "active number of shards in percent", right=True),
    ]

    def cat_health(req):
        h = node.cluster_health()
        cols = _HEALTH_COLS
        if req.param("ts") in ("false", False):
            cols = _HEALTH_COLS[2:]
        row = [h["cluster_name"], h["status"],
               h["number_of_nodes"], h["number_of_data_nodes"],
               h["active_shards"], h["active_primary_shards"],
               h["relocating_shards"], h["initializing_shards"],
               h["unassigned_shards"],
               h.get("number_of_pending_tasks", 0),
               "-",
               f"{h.get('active_shards_percent_as_number', 100.0):.1f}%"]
        if cols is _HEALTH_COLS:
            row = [int(time.time()),
                   time.strftime("%H:%M:%S", time.gmtime())] + row
        return cat_render(req, cols, [row])

    _SHARDS_COLS = [
        Col("index", "i,idx", "index name"),
        Col("shard", "s,sh", "shard name", right=True),
        Col("prirep", "p,pr,primaryOrReplica", "primary or replica"),
        Col("state", "st", "shard state"),
        Col("docs", "d,dc", "number of docs in shard", right=True),
        Col("store", "sto", "store size of shard", right=True),
        Col("ip", "", "ip of node where it lives"),
        Col("id", "", "unique id of node where it lives", default=False),
        Col("node", "n", "name of node where it lives"),
    ] + [Col(n, a, d, right=r, default=False) for (n, a, d, r) in [
        ("sync_id", "", "sync id", False),
        ("unassigned.reason", "ur", "reason shard became unassigned", False),
        ("unassigned.at", "ua", "time shard became unassigned", False),
        ("unassigned.for", "uf", "time has been unassigned", True),
        ("unassigned.details", "ud", "additional details as to why the shard became unassigned", False),
        ("recoverysource.type", "rs", "recovery source type", False),
        ("completion.size", "cs,completionSize", "size of completion", True),
        ("fielddata.memory_size", "fm,fielddataMemory", "used fielddata cache", True),
        ("fielddata.evictions", "fe,fielddataEvictions", "fielddata evictions", True),
        ("query_cache.memory_size", "qcm,queryCacheMemory", "used query cache", True),
        ("query_cache.evictions", "qce,queryCacheEvictions", "query cache evictions", True),
        ("flush.total", "ft,flushTotal", "number of flushes", True),
        ("flush.total_time", "ftt,flushTotalTime", "time spent in flush", True),
        ("get.current", "gc,getCurrent", "number of current get ops", True),
        ("get.time", "gti,getTime", "time spent in get", True),
        ("get.total", "gto,getTotal", "number of get ops", True),
        ("get.exists_time", "geti,getExistsTime", "time spent in successful gets", True),
        ("get.exists_total", "geto,getExistsTotal", "number of successful gets", True),
        ("get.missing_time", "gmti,getMissingTime", "time spent in failed gets", True),
        ("get.missing_total", "gmto,getMissingTotal", "number of failed gets", True),
        ("indexing.delete_current", "idc,indexingDeleteCurrent", "number of current deletions", True),
        ("indexing.delete_time", "idti,indexingDeleteTime", "time spent in deletions", True),
        ("indexing.delete_total", "idto,indexingDeleteTotal", "number of delete ops", True),
        ("indexing.index_current", "iic,indexingIndexCurrent", "number of current indexing ops", True),
        ("indexing.index_time", "iiti,indexingIndexTime", "time spent in indexing", True),
        ("indexing.index_total", "iito,indexingIndexTotal", "number of indexing ops", True),
        ("indexing.index_failed", "iif,indexingIndexFailed", "number of failed indexing ops", True),
        ("merges.current", "mc,mergesCurrent", "number of current merges", True),
        ("merges.current_docs", "mcd,mergesCurrentDocs", "number of current merging docs", True),
        ("merges.current_size", "mcs,mergesCurrentSize", "size of current merges", True),
        ("merges.total", "mt,mergesTotal", "number of completed merge ops", True),
        ("merges.total_docs", "mtd,mergesTotalDocs", "docs merged", True),
        ("merges.total_size", "mts,mergesTotalSize", "size merged", True),
        ("merges.total_time", "mtt,mergesTotalTime", "time spent in merges", True),
        ("refresh.total", "rto,refreshTotal", "total refreshes", True),
        ("refresh.time", "rti,refreshTime", "time spent in refreshes", True),
        ("refresh.external_total", "rto,refreshTotal", "total external refreshes", True),
        ("refresh.external_time", "rti,refreshTime", "time spent in external refreshes", True),
        ("refresh.listeners", "rli,refreshListeners", "number of pending refresh listeners", True),
        ("search.fetch_current", "sfc,searchFetchCurrent", "current fetch phase ops", True),
        ("search.fetch_time", "sfti,searchFetchTime", "time spent in fetch phase", True),
        ("search.fetch_total", "sfto,searchFetchTotal", "total fetch ops", True),
        ("search.open_contexts", "so,searchOpenContexts", "open search contexts", True),
        ("search.query_current", "sqc,searchQueryCurrent", "current query phase ops", True),
        ("search.query_time", "sqti,searchQueryTime", "time spent in query phase", True),
        ("search.query_total", "sqto,searchQueryTotal", "total query phase ops", True),
        ("search.scroll_current", "scc,searchScrollCurrent", "open scroll contexts", True),
        ("search.scroll_time", "scti,searchScrollTime", "time scroll contexts held open", True),
        ("search.scroll_total", "scto,searchScrollTotal", "completed scroll contexts", True),
        ("segments.count", "sc,segmentsCount", "number of segments", True),
        ("segments.memory", "sm,segmentsMemory", "memory used by segments", True),
        ("segments.index_writer_memory", "siwm,segmentsIndexWriterMemory", "memory used by index writer", True),
        ("segments.version_map_memory", "svmm,segmentsVersionMapMemory", "memory used by version map", True),
        ("segments.fixed_bitset_memory", "sfbm,fixedBitsetMemory", "memory used by fixed bit sets", True),
        ("seq_no.max", "sqm,maxSeqNo", "max sequence number", True),
        ("seq_no.local_checkpoint", "sql,localCheckpoint", "local checkpoint", True),
        ("seq_no.global_checkpoint", "sqg,globalCheckpoint", "global checkpoint", True),
        ("warmer.current", "wc,warmerCurrent", "current warmer ops", True),
        ("warmer.total", "wto,warmerTotal", "total warmer ops", True),
        ("warmer.total_time", "wtt,warmerTotalTime", "time spent in warmers", True),
        ("path.data", "pd,dataPath", "shard data path", False),
        ("path.state", "ps,statsPath", "shard state path", False),
    ]]

    def cat_shards(req):
        rows = []
        for svc in node.indices.resolve(req.params.get("index"),
                                        expand_hidden=True):
            for shard in svc.shards:
                ckpt = shard.engine.local_checkpoint
                by_name = {
                    "recoverysource.type": "EXISTING_STORE",
                    "completion.size": Bytes(0),
                    "fielddata.memory_size": Bytes(0),
                    "query_cache.memory_size": Bytes(0),
                    "merges.current_size": Bytes(0),
                    "merges.total_size": Bytes(0),
                    "segments.count": len(shard.engine.segments),
                    "segments.memory": Bytes(0),
                    "segments.index_writer_memory": Bytes(0),
                    "segments.version_map_memory": Bytes(0),
                    "segments.fixed_bitset_memory": Bytes(0),
                    "indexing.index_total": ckpt + 1,
                    "seq_no.max": ckpt,
                    "seq_no.local_checkpoint": ckpt,
                    "seq_no.global_checkpoint": ckpt,
                    "path.data": shard.engine.path,
                    "path.state": shard.engine.path,
                    "sync_id": None,
                    "unassigned.reason": None, "unassigned.at": None,
                    "unassigned.for": None, "unassigned.details": None,
                }
                extras = []
                for c in _SHARDS_COLS[9:]:
                    if c.name in by_name:
                        extras.append(by_name[c.name])
                    elif c.name.endswith(("_time", ".time", "total_time")):
                        extras.append(Millis(0))
                    else:
                        extras.append(0)
                rows.append([svc.name, shard.shard_id, "p", "STARTED",
                             shard.engine.doc_count(),
                             Bytes(dir_size(shard.engine.path)),
                             "127.0.0.1", node.node_id, node.node_name]
                            + extras)
                for _ in range(svc.num_replicas):
                    rows.append([svc.name, shard.shard_id, "r", "UNASSIGNED"]
                                + [None] * (len(_SHARDS_COLS) - 4))
        return cat_render(req, _SHARDS_COLS, rows)

    _NODES_COLS = [
        Col("id", "id,nodeId", "unique node id", default=False),
        Col("pid", "p", "process id", right=True, default=False),
        Col("ip", "i", "ip address"),
        Col("port", "po", "bound transport port", right=True, default=False),
        Col("http_address", "http", "bound http address", default=False),
        Col("version", "v", "es version", default=False),
        Col("heap.current", "hc,heapCurrent", "used heap", right=True, default=False),
        Col("heap.percent", "hp,heapPercent", "used heap ratio", right=True),
        Col("heap.max", "hm,heapMax", "max configured heap", right=True, default=False),
        Col("ram.percent", "rp,ramPercent", "used machine memory ratio", right=True),
        Col("cpu", "", "recent cpu usage", right=True),
        Col("load_1m", "l", "1m load avg", right=True),
        Col("load_5m", "", "5m load avg", right=True),
        Col("load_15m", "", "15m load avg", right=True),
        Col("file_desc.current", "fdc,fileDescriptorCurrent", "used file descriptors", right=True, default=False),
        Col("file_desc.percent", "fdp,fileDescriptorPercent", "used file descriptor ratio", right=True, default=False),
        Col("file_desc.max", "fdm,fileDescriptorMax", "max file descriptors", right=True, default=False),
        Col("disk.total", "dt,diskTotal", "total disk space", right=True, default=False),
        Col("disk.used", "du,diskUsed", "used disk space", right=True, default=False),
        Col("disk.avail", "d,da,disk,diskAvail", "available disk space", right=True, default=False),
        Col("disk.used_percent", "dup,diskUsedPercent", "used disk space percentage", right=True, default=False),
        Col("node.role", "r,role,nodeRole", "m:master eligible node, d:data node, i:ingest node, -:coordinating node only"),
        Col("master", "m", "*:current master"),
        Col("name", "n", "node name"),
    ]

    def cat_nodes(req):
        import shutil as _sh
        du = _sh.disk_usage(node.data_path)
        import resource as _res
        heap_pct = 42
        try:
            la1, la5, la15 = __import__("os").getloadavg()
        except OSError:
            la1 = la5 = la15 = 0.0
        soft, _hard = _res.getrlimit(_res.RLIMIT_NOFILE)
        full_id = req.param("full_id") in ("true", "", True)
        nid = node.node_id if full_id else node.node_id[:4]
        row = [nid, __import__("os").getpid(), "127.0.0.1", 9300,
               "127.0.0.1:9200", __version__,
               Bytes(256 * 1024 * 1024), heap_pct,
               Bytes(4 * 1024 ** 3), 50, 1,
               f"{la1:.2f}", f"{la5:.2f}", f"{la15:.2f}",
               64, 1, soft,
               Bytes(du.total), Bytes(du.used), Bytes(du.free),
               f"{du.used / du.total * 100:.2f}",
               "dim", "*", node.node_name]
        return cat_render(req, _NODES_COLS, [row])

    _COUNT_COLS = [
        Col("epoch", "t,time", "seconds since 1970-01-01 00:00:00", right=True),
        Col("timestamp", "ts,hms,hhmmss", "time in HH:MM:SS"),
        Col("count", "dc,docs.count,docsCount", "the document count", right=True),
    ]

    def cat_count(req):
        total = sum(s.doc_count()
                    for s in node.indices.resolve(req.params.get("index"),
                                                  expand_hidden=True))
        return cat_render(req, _COUNT_COLS,
                          [[int(time.time()),
                            time.strftime("%H:%M:%S", time.gmtime()), total]])

    _ALIASES_COLS = [
        Col("alias", "a", "alias name"),
        Col("index", "i,idx", "index alias points to"),
        Col("filter", "f,fi", "filter"),
        Col("routing.index", "ri,routingIndex", "index routing"),
        Col("routing.search", "rs,routingSearch", "search routing"),
        Col("is_write_index", "w,isWriteIndex", "write index"),
    ]

    def cat_aliases(req):
        import fnmatch as _fn
        name_filter = req.params.get("name")
        expand = req.param("expand_wildcards") or ""
        if isinstance(expand, (list, tuple)):
            expand = ",".join(str(t) for t in expand)
        # default is lenient (hidden shown); an explicit expand_wildcards
        # without all/hidden drops hidden indices and hidden aliases
        strict = expand and not any(
            t in ("all", "hidden") for t in expand.split(","))
        rows = []
        for name, svc in sorted(node.indices.indices.items()):
            for alias, opts in svc.aliases.items():
                if strict and (svc.hidden or (opts or {}).get("is_hidden")):
                    continue
                if name_filter and not any(
                        _fn.fnmatch(alias, p.strip())
                        for p in name_filter.split(",")):
                    continue
                opts = opts or {}
                routing = opts.get("routing")
                rows.append([
                    alias, name,
                    "*" if opts.get("filter") else "-",
                    opts.get("index_routing") or routing or "-",
                    opts.get("search_routing") or routing or "-",
                    str(opts["is_write_index"]).lower()
                    if opts.get("is_write_index") is not None else "-",
                ])
        return cat_render(req, _ALIASES_COLS, rows)

    # -------------------------------------------------------- open / close
    def close_index_h(req):
        names = [s.name for s in node.indices.resolve(req.params["index"])]
        if not names and "*" not in req.params["index"]:
            raise IndexNotFoundError(req.params["index"])
        for name in names:
            node.indices.close_index_state(name)
        return 200, {"acknowledged": True, "shards_acknowledged": True,
                     "indices": {n: {"closed": True} for n in names}}

    def open_index_h(req):
        # match closed indices too: resolve() skips them for wildcards;
        # each comma part resolves independently (mixed lists work)
        import fnmatch as _fn
        names = []
        for part in req.params["index"].split(","):
            part = part.strip()
            if not part:
                continue
            if "*" in part or part == "_all":
                pat = "*" if part == "_all" else part
                names.extend(n for n in node.indices.indices
                             if _fn.fnmatch(n, pat))
            elif part in node.indices.indices:
                names.append(part)
            else:
                raise IndexNotFoundError(part)
        if not names:
            raise IndexNotFoundError(req.params["index"])
        for name in dict.fromkeys(names):
            node.indices.open_index_state(name)
        return 200, {"acknowledged": True, "shards_acknowledged": True}

    rc.register("POST", "/{index}/_close", close_index_h)
    rc.register("POST", "/{index}/_open", open_index_h)

    rc.register("GET", "/_cat/indices", cat_indices)
    rc.register("GET", "/_cat/indices/{index}", cat_indices)
    rc.register("GET", "/_cat/health", cat_health)
    rc.register("GET", "/_cat/shards", cat_shards)
    rc.register("GET", "/_cat/shards/{index}", cat_shards)
    rc.register("GET", "/_cat/nodes", cat_nodes)
    rc.register("GET", "/_cat/count", cat_count)
    rc.register("GET", "/_cat/count/{index}", cat_count)
    rc.register("GET", "/_cat/aliases", cat_aliases)
    rc.register("GET", "/_cat/aliases/{name}", cat_aliases)


from elasticsearch_tpu.rest.cat import fmt_iso_millis as _fmt_iso_millis


def check_scroll_keep_alive(node, value) -> None:
    """search.max_keep_alive gate for scroll keepalives (SearchService
    validateKeepAlives)."""
    mka = node._cluster_setting("search.max_keep_alive") \
        if hasattr(node, "_cluster_setting") else None
    if not value or mka is None:
        return
    from elasticsearch_tpu.common.settings import parse_time_value
    if parse_time_value(str(value), "scroll") > \
            parse_time_value(str(mka), "max_keep_alive"):
        raise IllegalArgumentError(
            f"Keep alive for scroll ({value}) is too large. It must be "
            f"less than ({mka}). This limit can be set by changing the "
            f"[search.max_keep_alive] cluster level setting.")


def _query_string_to_dsl(q: str) -> dict:
    return {"query_string": {"query": q}}
