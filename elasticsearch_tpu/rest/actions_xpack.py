"""REST handlers for x-pack features: SQL, EQL (more arrive per feature).

Reference: each x-pack plugin registers its own Rest*Action handlers
(`x-pack/plugin/sql/.../RestSqlQueryAction.java`, eql's RestEqlSearchAction).
"""

from __future__ import annotations

from elasticsearch_tpu.common.errors import SearchEngineError
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.controller import RestController


def register_xpack(rc: RestController, node: Node) -> None:
    from elasticsearch_tpu.xpack.eql import EqlEngine
    from elasticsearch_tpu.xpack.sql import SqlEngine, to_text_table

    sql_engine = SqlEngine(node)
    eql_engine = EqlEngine(node)

    # ------------------------------------------------------------------ SQL
    def sql_query(req):
        body = req.json() or {}
        result = sql_engine.execute(body)
        if req.param("format") == "txt":
            return 200, to_text_table(result)
        return 200, result

    def sql_translate(req):
        return 200, sql_engine.translate(req.json() or {})

    def sql_close(req):
        return 200, sql_engine.close_cursor(req.json() or {})

    rc.register("POST", "/_sql", sql_query)
    rc.register("GET", "/_sql", sql_query)
    rc.register("POST", "/_sql/translate", sql_translate)
    rc.register("GET", "/_sql/translate", sql_translate)
    rc.register("POST", "/_sql/close", sql_close)

    # ------------------------------------------------------------------ EQL
    def eql_search(req):
        return 200, eql_engine.search(req.params["index"], req.json() or {})

    rc.register("POST", "/{index}/_eql/search", eql_search)
    rc.register("GET", "/{index}/_eql/search", eql_search)

    # ------------------------------------------------------------------ ILM
    from elasticsearch_tpu.xpack.ilm import resize_index, rollover

    def ilm_put_policy(req):
        node.ilm.put_policy(req.params["name"], req.json() or {})
        return 200, {"acknowledged": True}

    def ilm_get_policy(req):
        return 200, node.ilm.get_policy(req.params.get("name"))

    def ilm_delete_policy(req):
        node.ilm.delete_policy(req.params["name"])
        return 200, {"acknowledged": True}

    def ilm_explain(req):
        return 200, node.ilm.explain(req.params["index"])

    def ilm_status(req):
        return 200, {"operation_mode":
                     "RUNNING" if node.ilm.running else "STOPPED"}

    def ilm_start(req):
        node.ilm.running = True
        return 200, {"acknowledged": True}

    def ilm_stop(req):
        node.ilm.running = False
        return 200, {"acknowledged": True}

    def ilm_run(req):
        # explicit tick (tests/ops; the reference triggers via SchedulerEngine)
        return 200, {"actions": node.ilm.run_once()}

    rc.register("PUT", "/_ilm/policy/{name}", ilm_put_policy)
    rc.register("GET", "/_ilm/policy/{name}", ilm_get_policy)
    rc.register("GET", "/_ilm/policy", ilm_get_policy)
    rc.register("DELETE", "/_ilm/policy/{name}", ilm_delete_policy)
    rc.register("GET", "/{index}/_ilm/explain", ilm_explain)
    rc.register("GET", "/_ilm/status", ilm_status)
    rc.register("POST", "/_ilm/start", ilm_start)
    rc.register("POST", "/_ilm/stop", ilm_stop)
    rc.register("POST", "/_ilm/_run", ilm_run)

    # ------------------------------------------------- rollover + resize
    def do_rollover(req):
        # the path param slot is named by whichever route registered the
        # first {param} at this trie position — accept either
        alias = req.params.get("alias") or req.params.get("index")
        body = req.json() or {}
        if req.params.get("new_index"):
            body = {**body, "new_index": req.params["new_index"]}
        return 200, rollover(node, alias, body,
                             dry_run=req.bool_param("dry_run"))

    def do_resize(kind):
        def handler(req):
            return 200, resize_index(node, req.params["index"],
                                     req.params["target"], kind,
                                     req.json() or {})
        return handler

    rc.register("POST", "/{alias}/_rollover", do_rollover)
    rc.register("POST", "/{alias}/_rollover/{new_index}", do_rollover)
    rc.register("POST", "/{index}/_shrink/{target}", do_resize("shrink"))
    rc.register("PUT", "/{index}/_shrink/{target}", do_resize("shrink"))
    rc.register("POST", "/{index}/_split/{target}", do_resize("split"))
    rc.register("PUT", "/{index}/_split/{target}", do_resize("split"))
    rc.register("POST", "/{index}/_clone/{target}", do_resize("clone"))
    rc.register("PUT", "/{index}/_clone/{target}", do_resize("clone"))

    # ------------------------------------------------------------------ SLM
    def slm_put(req):
        node.slm.put_policy(req.params["id"], req.json() or {})
        return 200, {"acknowledged": True}

    def slm_get(req):
        return 200, node.slm.get_policy(req.params.get("id"))

    def slm_delete(req):
        node.slm.delete_policy(req.params["id"])
        return 200, {"acknowledged": True}

    def slm_execute(req):
        return 200, node.slm.execute(req.params["id"])

    rc.register("PUT", "/_slm/policy/{id}", slm_put)
    rc.register("GET", "/_slm/policy/{id}", slm_get)
    rc.register("GET", "/_slm/policy", slm_get)
    rc.register("DELETE", "/_slm/policy/{id}", slm_delete)
    rc.register("POST", "/_slm/policy/{id}/_execute", slm_execute)

    # -------------------------------------------------------------- watcher
    def watch_put(req):
        active = req.bool_param("active", True)
        return 200, node.watcher.put_watch(req.params["id"], req.json() or {},
                                           active=active)

    def watch_get(req):
        return 200, node.watcher.get_watch(req.params["id"])

    def watch_delete(req):
        node.watcher.delete_watch(req.params["id"])
        return 200, {"found": True, "_id": req.params["id"]}

    def watch_execute(req):
        body = req.json() or {}
        record = node.watcher.execute(
            req.params["id"],
            trigger_data=body.get("trigger_data"),
            record_execution=body.get("record_execution", False),
            alternative_input=body.get("alternative_input"))
        return 200, {"_id": req.params["id"], "watch_record": record}

    rc.register("PUT", "/_watcher/watch/{id}", watch_put)
    rc.register("POST", "/_watcher/watch/{id}", watch_put)
    rc.register("GET", "/_watcher/watch/{id}", watch_get)
    rc.register("DELETE", "/_watcher/watch/{id}", watch_delete)
    rc.register("POST", "/_watcher/watch/{id}/_execute", watch_execute)
    rc.register("PUT", "/_watcher/watch/{id}/_execute", watch_execute)

    def watch_ack_handler(req):
        action_id = req.params.get("action_id")
        node.watcher.ack(req.params["id"], [action_id] if action_id else None)
        return 200, {"status": {"state": {"active": True}}}

    rc.register("POST", "/_watcher/watch/{id}/_ack", watch_ack_handler)
    rc.register("PUT", "/_watcher/watch/{id}/_ack", watch_ack_handler)
    rc.register("POST", "/_watcher/watch/{id}/_ack/{action_id}", watch_ack_handler)

    def watch_activate(req):
        node.watcher.set_active(req.params["id"], True)
        return 200, {"status": {"state": {"active": True}}}

    def watch_deactivate(req):
        node.watcher.set_active(req.params["id"], False)
        return 200, {"status": {"state": {"active": False}}}

    rc.register("POST", "/_watcher/watch/{id}/_activate", watch_activate)
    rc.register("PUT", "/_watcher/watch/{id}/_activate", watch_activate)
    rc.register("POST", "/_watcher/watch/{id}/_deactivate", watch_deactivate)
    rc.register("PUT", "/_watcher/watch/{id}/_deactivate", watch_deactivate)

    def watcher_stats(req):
        return 200, node.watcher.stats()

    def watcher_start(req):
        node.watcher.running = True
        return 200, {"acknowledged": True}

    def watcher_stop(req):
        node.watcher.running = False
        return 200, {"acknowledged": True}

    def watcher_tick(req):
        return 200, {"records": node.watcher.run_once()}

    rc.register("GET", "/_watcher/stats", watcher_stats)
    rc.register("POST", "/_watcher/_start", watcher_start)
    rc.register("POST", "/_watcher/_stop", watcher_stop)
    rc.register("POST", "/_watcher/_tick", watcher_tick)

    # ------------------------------------------------------------ transform
    def transform_put(req):
        node.transform.put(req.params["id"], req.json() or {})
        return 200, {"acknowledged": True}

    def transform_get(req):
        return 200, node.transform.get(req.params.get("id"))

    def transform_delete(req):
        node.transform.delete(req.params["id"])
        return 200, {"acknowledged": True}

    def transform_start(req):
        node.transform.start(req.params["id"])
        return 200, {"acknowledged": True}

    def transform_stop(req):
        node.transform.stop(req.params["id"])
        return 200, {"acknowledged": True}

    def transform_stats(req):
        return 200, node.transform.stats(req.params["id"])

    def transform_preview(req):
        return 200, node.transform.preview(req.json() or {})

    rc.register("PUT", "/_transform/{id}", transform_put)
    rc.register("GET", "/_transform/{id}", transform_get)
    rc.register("GET", "/_transform", transform_get)
    rc.register("DELETE", "/_transform/{id}", transform_delete)
    rc.register("POST", "/_transform/{id}/_start", transform_start)
    rc.register("POST", "/_transform/{id}/_stop", transform_stop)
    rc.register("GET", "/_transform/{id}/_stats", transform_stats)
    rc.register("POST", "/_transform/_preview", transform_preview)

    # --------------------------------------------------------------- rollup
    def rollup_put(req):
        node.rollup.put_job(req.params["id"], req.json() or {})
        return 200, {"acknowledged": True}

    def rollup_get(req):
        return 200, node.rollup.get_job(req.params.get("id"))

    def rollup_delete(req):
        node.rollup.delete_job(req.params["id"])
        return 200, {"acknowledged": True}

    def rollup_start(req):
        return 200, node.rollup.start_job(req.params["id"])

    def rollup_stop(req):
        return 200, node.rollup.stop_job(req.params["id"])

    def rollup_caps(req):
        return 200, node.rollup.caps(req.params.get("index", "_all"))

    rc.register("PUT", "/_rollup/job/{id}", rollup_put)
    rc.register("GET", "/_rollup/job/{id}", rollup_get)
    rc.register("GET", "/_rollup/job", rollup_get)
    rc.register("DELETE", "/_rollup/job/{id}", rollup_delete)
    rc.register("POST", "/_rollup/job/{id}/_start", rollup_start)
    rc.register("POST", "/_rollup/job/{id}/_stop", rollup_stop)
    rc.register("GET", "/_rollup/data/{index}", rollup_caps)
    rc.register("GET", "/_rollup/data", rollup_caps)

    # ----------------------------------------------------------- CCR / CCS
    def remote_info(req):
        return 200, node.remotes.info()

    rc.register("GET", "/_remote/info", remote_info)

    def ccr_follow(req):
        return 200, node.ccr.follow(req.params["index"], req.json() or {})

    def ccr_pause(req):
        node.ccr.pause(req.params["index"])
        return 200, {"acknowledged": True}

    def ccr_resume(req):
        node.ccr.resume(req.params["index"])
        return 200, {"acknowledged": True}

    def ccr_unfollow(req):
        node.ccr.unfollow(req.params["index"])
        return 200, {"acknowledged": True}

    def ccr_stats(req):
        return 200, node.ccr.stats()

    def ccr_follow_info(req):
        return 200, node.ccr.follow_info(req.params.get("index", "_all"))

    rc.register("PUT", "/{index}/_ccr/follow", ccr_follow)
    rc.register("POST", "/{index}/_ccr/pause_follow", ccr_pause)
    rc.register("POST", "/{index}/_ccr/resume_follow", ccr_resume)
    rc.register("POST", "/{index}/_ccr/unfollow", ccr_unfollow)
    def ccr_tick(req):
        # explicit replication tick (the ShardFollowNodeTask scheduler
        # analog, same convention as /_watcher/_tick)
        return 200, {"operations": node.ccr.run_once()}

    rc.register("GET", "/{index}/_ccr/info", ccr_follow_info)
    rc.register("GET", "/_ccr/stats", ccr_stats)
    rc.register("POST", "/_ccr/_tick", ccr_tick)

    def auto_follow_put(req):
        node.ccr.put_auto_follow(req.params["name"], req.json() or {})
        return 200, {"acknowledged": True}

    def auto_follow_get(req):
        return 200, node.ccr.get_auto_follow(req.params.get("name"))

    def auto_follow_delete(req):
        node.ccr.delete_auto_follow(req.params["name"])
        return 200, {"acknowledged": True}

    rc.register("PUT", "/_ccr/auto_follow/{name}", auto_follow_put)
    rc.register("GET", "/_ccr/auto_follow/{name}", auto_follow_get)
    rc.register("GET", "/_ccr/auto_follow", auto_follow_get)
    rc.register("DELETE", "/_ccr/auto_follow/{name}", auto_follow_delete)

    # ------------------------------------------ dynamic index settings
    def put_settings(req):
        body = req.json() or {}
        flat = _flatten_settings(body.get("settings", body))
        # bare keys normalize under index. (PUT bodies mix forms freely)
        flat = {k if k.startswith("index.") else f"index.{k}": v
                for k, v in flat.items()}
        preserve = req.bool_param("preserve_existing", False)
        ignore_unavailable = req.bool_param("ignore_unavailable", False)
        expr = req.params.get("index")
        targets = []
        for part in (expr or "_all").split(","):
            part = part.strip()
            if "*" in part or part in ("_all", ""):
                targets.extend(node.indices.resolve(part or "_all"))
            else:
                try:
                    targets.append(node.indices.get(part))
                except SearchEngineError:
                    if not ignore_unavailable:
                        raise
        for svc in targets:
            updates = dict(flat)
            if preserve:
                existing = svc.settings.as_flat_dict()
                updates = {k: v for k, v in updates.items()
                           if k not in existing}
            node.indices.update_settings(svc, updates)
        return 200, {"acknowledged": True}

    rc.register("PUT", "/{index}/_settings", put_settings)
    rc.register("PUT", "/_settings", put_settings)

    _register_ml(rc, node)
    register_license(rc, node)

    # --------------------------------------------------------------- enrich
    def enrich_put(req):
        node.enrich.put_policy(req.params["name"], req.json() or {})
        return 200, {"acknowledged": True}

    def enrich_get(req):
        return 200, node.enrich.get_policy(req.params.get("name"))

    def enrich_delete(req):
        node.enrich.delete_policy(req.params["name"])
        return 200, {"acknowledged": True}

    def enrich_execute(req):
        return 200, node.enrich.execute_policy(req.params["name"])

    def enrich_stats(req):
        return 200, {"executing_policies": [],
                     "coordinator_stats": [],
                     "executed_count": node.enrich.stats["executed"]}

    rc.register("PUT", "/_enrich/policy/{name}", enrich_put)
    rc.register("GET", "/_enrich/policy/{name}", enrich_get)
    rc.register("GET", "/_enrich/policy", enrich_get)
    rc.register("DELETE", "/_enrich/policy/{name}", enrich_delete)
    rc.register("POST", "/_enrich/policy/{name}/_execute", enrich_execute)
    rc.register("GET", "/_enrich/_stats", enrich_stats)

    # ---------------------------------------------------------------- graph
    def graph_explore(req):
        return 200, node.graph.explore(req.params["index"], req.json() or {})

    rc.register("POST", "/{index}/_graph/explore", graph_explore)
    rc.register("GET", "/{index}/_graph/explore", graph_explore)

    # ------------------------------------------------------- frozen indices
    def freeze(req):
        # reference: x-pack/plugin/frozen-indices TransportFreezeIndexAction
        for svc in node.indices.resolve(req.params["index"]):
            node.indices.update_settings(svc, {
                "index.frozen": True, "index.search.throttled": True})
        return 200, {"acknowledged": True}

    def unfreeze(req):
        for svc in node.indices.resolve(req.params["index"]):
            node.indices.update_settings(svc, {
                "index.frozen": False, "index.search.throttled": False})
        return 200, {"acknowledged": True}

    rc.register("POST", "/{index}/_freeze", freeze)
    rc.register("POST", "/{index}/_unfreeze", unfreeze)

    # ------------------------------------------------------------ monitoring
    def monitoring_bulk(req):
        return 200, node.monitoring.bulk(req.param("system_id"),
                                         req.ndjson())

    def monitoring_collect(req):
        # explicit collection tick (the scheduler analog; see
        # xpack/monitoring.py)
        return 200, node.monitoring.collect()

    rc.register("POST", "/_monitoring/bulk", monitoring_bulk)
    rc.register("PUT", "/_monitoring/bulk", monitoring_bulk)
    rc.register("POST", "/_monitoring/_collect", monitoring_collect)


def register_license(rc: RestController, node: Node) -> None:
    """GET/PUT/DELETE /_license + trial/basic upgrades
    (RestGetLicenseAction and friends)."""
    def get_license(req):
        return 200, {"license": node.license.license}

    def put_license(req):
        return 200, node.license.put_license(req.json() or {})

    def delete_license(req):
        return 200, node.license.delete_license()

    def start_trial(req):
        return 200, node.license.start_trial(
            req.param("acknowledge") in ("true", "", True))

    def start_basic(req):
        return 200, node.license.start_basic(
            req.param("acknowledge") in ("true", "", True))

    rc.register("GET", "/_license", get_license)
    rc.register("PUT", "/_license", put_license)
    rc.register("POST", "/_license", put_license)
    rc.register("DELETE", "/_license", delete_license)
    rc.register("POST", "/_license/start_trial", start_trial)
    rc.register("POST", "/_license/start_basic", start_basic)


def _register_ml(rc: RestController, node: Node) -> None:
    """REST surface of `x-pack/plugin/ml/.../rest/` (job/, datafeeds/,
    results/ subpackages)."""

    # ----------------------------------------------------- anomaly detectors
    def _gate(req):
        # machine learning is a platinum feature (XPackLicenseState)
        node.license.gate("ml")

    def ml_put_job(req):
        _gate(req)
        return 200, node.ml.put_job(req.params["job_id"], req.json() or {})

    def ml_get_jobs(req):
        return 200, node.ml.get_jobs(req.params.get("job_id"))

    def ml_delete_job(req):
        node.ml.delete_job(req.params["job_id"],
                           force=req.bool_param("force"))
        return 200, {"acknowledged": True}

    def ml_open(req):
        return 200, node.ml.open_job(req.params["job_id"])

    def ml_close(req):
        return 200, node.ml.close_job(req.params["job_id"],
                                      force=req.bool_param("force"))

    def ml_post_data(req):
        try:
            body = req.json()
        except Exception:
            body = None
        records = body if isinstance(body, list) else req.ndjson()
        return 202, node.ml.post_data(req.params["job_id"], records)

    def ml_flush(req):
        return 200, node.ml.flush_job(
            req.params["job_id"], calc_interim=req.bool_param("calc_interim"))

    def ml_job_stats(req):
        return 200, node.ml.job_stats(req.params.get("job_id"))

    def ml_buckets(req):
        return 200, node.ml.get_buckets(req.params["job_id"], req.json() or {})

    def ml_records(req):
        return 200, node.ml.get_records(req.params["job_id"], req.json() or {})

    def ml_overall(req):
        return 200, node.ml.get_overall_buckets(req.params["job_id"],
                                                req.json() or {})

    base = "/_ml/anomaly_detectors"
    rc.register("PUT", base + "/{job_id}", ml_put_job)
    rc.register("GET", base, ml_get_jobs)
    rc.register("GET", base + "/{job_id}", ml_get_jobs)
    rc.register("DELETE", base + "/{job_id}", ml_delete_job)
    rc.register("POST", base + "/{job_id}/_open", ml_open)
    rc.register("POST", base + "/{job_id}/_close", ml_close)
    rc.register("POST", base + "/{job_id}/_data", ml_post_data)
    rc.register("POST", base + "/{job_id}/_flush", ml_flush)
    rc.register("GET", base + "/_stats", ml_job_stats)
    rc.register("GET", base + "/{job_id}/_stats", ml_job_stats)
    for method in ("GET", "POST"):
        rc.register(method, base + "/{job_id}/results/buckets", ml_buckets)
        rc.register(method, base + "/{job_id}/results/records", ml_records)
        rc.register(method, base + "/{job_id}/results/overall_buckets",
                    ml_overall)

    # -------------------------------------------------------------- datafeeds
    def df_put(req):
        return 200, node.datafeeds.put(req.params["datafeed_id"],
                                       req.json() or {})

    def df_get(req):
        return 200, node.datafeeds.get(req.params.get("datafeed_id"))

    def df_delete(req):
        node.datafeeds.delete(req.params["datafeed_id"])
        return 200, {"acknowledged": True}

    def df_start(req):
        body = req.json() or {}
        return 200, node.datafeeds.start(
            req.params["datafeed_id"],
            start=body.get("start", req.param("start")),
            end=body.get("end", req.param("end")))

    def df_stop(req):
        return 200, node.datafeeds.stop(req.params["datafeed_id"])

    def df_stats(req):
        return 200, node.datafeeds.stats(req.params.get("datafeed_id"))

    def df_preview(req):
        return 200, node.datafeeds.preview(req.params["datafeed_id"])

    base = "/_ml/datafeeds"
    rc.register("PUT", base + "/{datafeed_id}", df_put)
    rc.register("GET", base, df_get)
    rc.register("GET", base + "/{datafeed_id}", df_get)
    rc.register("DELETE", base + "/{datafeed_id}", df_delete)
    rc.register("POST", base + "/{datafeed_id}/_start", df_start)
    rc.register("POST", base + "/{datafeed_id}/_stop", df_stop)
    rc.register("GET", base + "/_stats", df_stats)
    rc.register("GET", base + "/{datafeed_id}/_stats", df_stats)
    rc.register("GET", base + "/{datafeed_id}/_preview", df_preview)
    rc.register("POST", base + "/{datafeed_id}/_preview", df_preview)


def _flatten_settings(obj: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in obj.items():
        key = prefix + k
        if isinstance(v, dict):
            out.update(_flatten_settings(v, key + "."))
        else:
            out[key] = v
    # accept both "index.x" and bare "x" forms like the reference
    return {k if k.startswith("index.") else "index." + k: v
            for k, v in out.items()}
