"""REST handlers for x-pack features: SQL, EQL (more arrive per feature).

Reference: each x-pack plugin registers its own Rest*Action handlers
(`x-pack/plugin/sql/.../RestSqlQueryAction.java`, eql's RestEqlSearchAction).
"""

from __future__ import annotations

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.controller import RestController


def register_xpack(rc: RestController, node: Node) -> None:
    from elasticsearch_tpu.xpack.eql import EqlEngine
    from elasticsearch_tpu.xpack.sql import SqlEngine, to_text_table

    sql_engine = SqlEngine(node)
    eql_engine = EqlEngine(node)

    # ------------------------------------------------------------------ SQL
    def sql_query(req):
        body = req.json() or {}
        result = sql_engine.execute(body)
        if req.param("format") == "txt":
            return 200, to_text_table(result)
        return 200, result

    def sql_translate(req):
        return 200, sql_engine.translate(req.json() or {})

    def sql_close(req):
        return 200, sql_engine.close_cursor(req.json() or {})

    rc.register("POST", "/_sql", sql_query)
    rc.register("GET", "/_sql", sql_query)
    rc.register("POST", "/_sql/translate", sql_translate)
    rc.register("GET", "/_sql/translate", sql_translate)
    rc.register("POST", "/_sql/close", sql_close)

    # ------------------------------------------------------------------ EQL
    def eql_search(req):
        return 200, eql_engine.search(req.params["index"], req.json() or {})

    rc.register("POST", "/{index}/_eql/search", eql_search)
    rc.register("GET", "/{index}/_eql/search", eql_search)

    # ------------------------------------------------------------------ ILM
    from elasticsearch_tpu.xpack.ilm import resize_index, rollover

    def ilm_put_policy(req):
        node.ilm.put_policy(req.params["name"], req.json() or {})
        return 200, {"acknowledged": True}

    def ilm_get_policy(req):
        return 200, node.ilm.get_policy(req.params.get("name"))

    def ilm_delete_policy(req):
        node.ilm.delete_policy(req.params["name"])
        return 200, {"acknowledged": True}

    def ilm_explain(req):
        return 200, node.ilm.explain(req.params["index"])

    def ilm_status(req):
        return 200, {"operation_mode":
                     "RUNNING" if node.ilm.running else "STOPPED"}

    def ilm_start(req):
        node.ilm.running = True
        return 200, {"acknowledged": True}

    def ilm_stop(req):
        node.ilm.running = False
        return 200, {"acknowledged": True}

    def ilm_run(req):
        # explicit tick (tests/ops; the reference triggers via SchedulerEngine)
        return 200, {"actions": node.ilm.run_once()}

    rc.register("PUT", "/_ilm/policy/{name}", ilm_put_policy)
    rc.register("GET", "/_ilm/policy/{name}", ilm_get_policy)
    rc.register("GET", "/_ilm/policy", ilm_get_policy)
    rc.register("DELETE", "/_ilm/policy/{name}", ilm_delete_policy)
    rc.register("GET", "/{index}/_ilm/explain", ilm_explain)
    rc.register("GET", "/_ilm/status", ilm_status)
    rc.register("POST", "/_ilm/start", ilm_start)
    rc.register("POST", "/_ilm/stop", ilm_stop)
    rc.register("POST", "/_ilm/_run", ilm_run)

    # ------------------------------------------------- rollover + resize
    def do_rollover(req):
        # the path param slot is named by whichever route registered the
        # first {param} at this trie position — accept either
        alias = req.params.get("alias") or req.params.get("index")
        return 200, rollover(node, alias, req.json() or {},
                             dry_run=req.bool_param("dry_run"))

    def do_resize(kind):
        def handler(req):
            return 200, resize_index(node, req.params["index"],
                                     req.params["target"], kind,
                                     req.json() or {})
        return handler

    rc.register("POST", "/{alias}/_rollover", do_rollover)
    rc.register("POST", "/{index}/_shrink/{target}", do_resize("shrink"))
    rc.register("PUT", "/{index}/_shrink/{target}", do_resize("shrink"))
    rc.register("POST", "/{index}/_split/{target}", do_resize("split"))
    rc.register("PUT", "/{index}/_split/{target}", do_resize("split"))
    rc.register("POST", "/{index}/_clone/{target}", do_resize("clone"))
    rc.register("PUT", "/{index}/_clone/{target}", do_resize("clone"))

    # ------------------------------------------------------------------ SLM
    def slm_put(req):
        node.slm.put_policy(req.params["id"], req.json() or {})
        return 200, {"acknowledged": True}

    def slm_get(req):
        return 200, node.slm.get_policy(req.params.get("id"))

    def slm_delete(req):
        node.slm.delete_policy(req.params["id"])
        return 200, {"acknowledged": True}

    def slm_execute(req):
        return 200, node.slm.execute(req.params["id"])

    rc.register("PUT", "/_slm/policy/{id}", slm_put)
    rc.register("GET", "/_slm/policy/{id}", slm_get)
    rc.register("GET", "/_slm/policy", slm_get)
    rc.register("DELETE", "/_slm/policy/{id}", slm_delete)
    rc.register("POST", "/_slm/policy/{id}/_execute", slm_execute)

    # -------------------------------------------------------------- watcher
    def watch_put(req):
        active = req.bool_param("active", True)
        return 200, node.watcher.put_watch(req.params["id"], req.json() or {},
                                           active=active)

    def watch_get(req):
        return 200, node.watcher.get_watch(req.params["id"])

    def watch_delete(req):
        node.watcher.delete_watch(req.params["id"])
        return 200, {"found": True, "_id": req.params["id"]}

    def watch_execute(req):
        body = req.json() or {}
        record = node.watcher.execute(
            req.params["id"],
            trigger_data=body.get("trigger_data"),
            record_execution=body.get("record_execution", False),
            alternative_input=body.get("alternative_input"))
        return 200, {"_id": req.params["id"], "watch_record": record}

    rc.register("PUT", "/_watcher/watch/{id}", watch_put)
    rc.register("POST", "/_watcher/watch/{id}", watch_put)
    rc.register("GET", "/_watcher/watch/{id}", watch_get)
    rc.register("DELETE", "/_watcher/watch/{id}", watch_delete)
    rc.register("POST", "/_watcher/watch/{id}/_execute", watch_execute)
    rc.register("PUT", "/_watcher/watch/{id}/_execute", watch_execute)

    def watch_ack_handler(req):
        action_id = req.params.get("action_id")
        node.watcher.ack(req.params["id"], [action_id] if action_id else None)
        return 200, {"status": {"state": {"active": True}}}

    rc.register("POST", "/_watcher/watch/{id}/_ack", watch_ack_handler)
    rc.register("PUT", "/_watcher/watch/{id}/_ack", watch_ack_handler)
    rc.register("POST", "/_watcher/watch/{id}/_ack/{action_id}", watch_ack_handler)

    def watch_activate(req):
        node.watcher.set_active(req.params["id"], True)
        return 200, {"status": {"state": {"active": True}}}

    def watch_deactivate(req):
        node.watcher.set_active(req.params["id"], False)
        return 200, {"status": {"state": {"active": False}}}

    rc.register("POST", "/_watcher/watch/{id}/_activate", watch_activate)
    rc.register("PUT", "/_watcher/watch/{id}/_activate", watch_activate)
    rc.register("POST", "/_watcher/watch/{id}/_deactivate", watch_deactivate)
    rc.register("PUT", "/_watcher/watch/{id}/_deactivate", watch_deactivate)

    def watcher_stats(req):
        return 200, node.watcher.stats()

    def watcher_start(req):
        node.watcher.running = True
        return 200, {"acknowledged": True}

    def watcher_stop(req):
        node.watcher.running = False
        return 200, {"acknowledged": True}

    def watcher_tick(req):
        return 200, {"records": node.watcher.run_once()}

    rc.register("GET", "/_watcher/stats", watcher_stats)
    rc.register("POST", "/_watcher/_start", watcher_start)
    rc.register("POST", "/_watcher/_stop", watcher_stop)
    rc.register("POST", "/_watcher/_tick", watcher_tick)

    # ------------------------------------------------------------ transform
    def transform_put(req):
        node.transform.put(req.params["id"], req.json() or {})
        return 200, {"acknowledged": True}

    def transform_get(req):
        return 200, node.transform.get(req.params.get("id"))

    def transform_delete(req):
        node.transform.delete(req.params["id"])
        return 200, {"acknowledged": True}

    def transform_start(req):
        node.transform.start(req.params["id"])
        return 200, {"acknowledged": True}

    def transform_stop(req):
        node.transform.stop(req.params["id"])
        return 200, {"acknowledged": True}

    def transform_stats(req):
        return 200, node.transform.stats(req.params["id"])

    def transform_preview(req):
        return 200, node.transform.preview(req.json() or {})

    rc.register("PUT", "/_transform/{id}", transform_put)
    rc.register("GET", "/_transform/{id}", transform_get)
    rc.register("GET", "/_transform", transform_get)
    rc.register("DELETE", "/_transform/{id}", transform_delete)
    rc.register("POST", "/_transform/{id}/_start", transform_start)
    rc.register("POST", "/_transform/{id}/_stop", transform_stop)
    rc.register("GET", "/_transform/{id}/_stats", transform_stats)
    rc.register("POST", "/_transform/_preview", transform_preview)

    # --------------------------------------------------------------- rollup
    def rollup_put(req):
        node.rollup.put_job(req.params["id"], req.json() or {})
        return 200, {"acknowledged": True}

    def rollup_get(req):
        return 200, node.rollup.get_job(req.params.get("id"))

    def rollup_delete(req):
        node.rollup.delete_job(req.params["id"])
        return 200, {"acknowledged": True}

    def rollup_start(req):
        return 200, node.rollup.start_job(req.params["id"])

    def rollup_stop(req):
        return 200, node.rollup.stop_job(req.params["id"])

    def rollup_caps(req):
        return 200, node.rollup.caps(req.params.get("index", "_all"))

    rc.register("PUT", "/_rollup/job/{id}", rollup_put)
    rc.register("GET", "/_rollup/job/{id}", rollup_get)
    rc.register("GET", "/_rollup/job", rollup_get)
    rc.register("DELETE", "/_rollup/job/{id}", rollup_delete)
    rc.register("POST", "/_rollup/job/{id}/_start", rollup_start)
    rc.register("POST", "/_rollup/job/{id}/_stop", rollup_stop)
    rc.register("GET", "/_rollup/data/{index}", rollup_caps)
    rc.register("GET", "/_rollup/data", rollup_caps)

    # ----------------------------------------------------------- CCR / CCS
    def remote_info(req):
        return 200, node.remotes.info()

    rc.register("GET", "/_remote/info", remote_info)

    def ccr_follow(req):
        return 200, node.ccr.follow(req.params["index"], req.json() or {})

    def ccr_pause(req):
        node.ccr.pause(req.params["index"])
        return 200, {"acknowledged": True}

    def ccr_resume(req):
        node.ccr.resume(req.params["index"])
        return 200, {"acknowledged": True}

    def ccr_unfollow(req):
        node.ccr.unfollow(req.params["index"])
        return 200, {"acknowledged": True}

    def ccr_stats(req):
        return 200, node.ccr.stats()

    def ccr_follow_info(req):
        return 200, node.ccr.follow_info(req.params.get("index", "_all"))

    rc.register("PUT", "/{index}/_ccr/follow", ccr_follow)
    rc.register("POST", "/{index}/_ccr/pause_follow", ccr_pause)
    rc.register("POST", "/{index}/_ccr/resume_follow", ccr_resume)
    rc.register("POST", "/{index}/_ccr/unfollow", ccr_unfollow)
    rc.register("GET", "/{index}/_ccr/info", ccr_follow_info)
    rc.register("GET", "/_ccr/stats", ccr_stats)

    def auto_follow_put(req):
        node.ccr.put_auto_follow(req.params["name"], req.json() or {})
        return 200, {"acknowledged": True}

    def auto_follow_get(req):
        return 200, node.ccr.get_auto_follow(req.params.get("name"))

    def auto_follow_delete(req):
        node.ccr.delete_auto_follow(req.params["name"])
        return 200, {"acknowledged": True}

    rc.register("PUT", "/_ccr/auto_follow/{name}", auto_follow_put)
    rc.register("GET", "/_ccr/auto_follow/{name}", auto_follow_get)
    rc.register("GET", "/_ccr/auto_follow", auto_follow_get)
    rc.register("DELETE", "/_ccr/auto_follow/{name}", auto_follow_delete)

    # ------------------------------------------ dynamic index settings
    def put_settings(req):
        body = req.json() or {}
        flat = _flatten_settings(body.get("settings", body))
        for svc in node.indices.resolve(req.params.get("index")):
            svc.settings_update(flat)
        return 200, {"acknowledged": True}

    rc.register("PUT", "/{index}/_settings", put_settings)
    rc.register("PUT", "/_settings", put_settings)


def _flatten_settings(obj: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in obj.items():
        key = prefix + k
        if isinstance(v, dict):
            out.update(_flatten_settings(v, key + "."))
        else:
            out[key] = v
    # accept both "index.x" and bare "x" forms like the reference
    return {k if k.startswith("index.") else "index." + k: v
            for k, v in out.items()}
