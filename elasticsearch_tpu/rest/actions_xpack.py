"""REST handlers for x-pack features: SQL, EQL (more arrive per feature).

Reference: each x-pack plugin registers its own Rest*Action handlers
(`x-pack/plugin/sql/.../RestSqlQueryAction.java`, eql's RestEqlSearchAction).
"""

from __future__ import annotations

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.controller import RestController


def register_xpack(rc: RestController, node: Node) -> None:
    from elasticsearch_tpu.xpack.eql import EqlEngine
    from elasticsearch_tpu.xpack.sql import SqlEngine, to_text_table

    sql_engine = SqlEngine(node)
    eql_engine = EqlEngine(node)

    # ------------------------------------------------------------------ SQL
    def sql_query(req):
        body = req.json() or {}
        result = sql_engine.execute(body)
        if req.param("format") == "txt":
            return 200, to_text_table(result)
        return 200, result

    def sql_translate(req):
        return 200, sql_engine.translate(req.json() or {})

    def sql_close(req):
        return 200, sql_engine.close_cursor(req.json() or {})

    rc.register("POST", "/_sql", sql_query)
    rc.register("GET", "/_sql", sql_query)
    rc.register("POST", "/_sql/translate", sql_translate)
    rc.register("GET", "/_sql/translate", sql_translate)
    rc.register("POST", "/_sql/close", sql_close)

    # ------------------------------------------------------------------ EQL
    def eql_search(req):
        return 200, eql_engine.search(req.params["index"], req.json() or {})

    rc.register("POST", "/{index}/_eql/search", eql_search)
    rc.register("GET", "/{index}/_eql/search", eql_search)
