"""REST routing and dispatch.

Re-design of `rest/RestController.java:62,146,168,271`: a path trie with
{param} segments routes (method, path) to handlers; errors render as the
reference's structured error body {"error": {...}, "status": N}. Handlers
receive a RestRequest (params, query args, decoded body) and return
(status, body) — transport-agnostic so the same table serves HTTP and tests.
"""

from __future__ import annotations

import re
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.common import xcontent
from elasticsearch_tpu.common.errors import (
    IllegalArgumentError, ParsingError, SearchEngineError,
)


class RestRequest:
    def __init__(self, method: str, path: str, params: Dict[str, str],
                 query: Dict[str, str], body: bytes,
                 content_type: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None):
        self.method = method
        self.path = path
        self.params = params          # path template params
        self.query = query            # query-string args
        self.raw_body = body
        self.content_type = content_type
        self.headers = headers or {}  # lower-cased header names
        self.context: Dict[str, Any] = {}  # filter-populated (e.g. auth)

    def json(self) -> Any:
        if not self.raw_body:
            return None
        ct = xcontent.XContentType.from_media_type(self.content_type)
        return xcontent.loads(self.raw_body, ct)

    def ndjson(self) -> List[Any]:
        """Newline-delimited JSON bodies (_bulk, _msearch)."""
        out = []
        for line in self.raw_body.split(b"\n"):
            line = line.strip()
            if line:
                out.append(xcontent.loads(line, xcontent.XContentType.JSON))
        return out

    def param(self, name: str, default: Any = None) -> Any:
        if name in self.params:
            return self.params[name]
        return self.query.get(name, default)

    def bool_param(self, name: str, default: bool = False) -> bool:
        v = self.param(name)
        if v is None:
            return default
        return v in ("", "true", "1", True)

    def int_param(self, name: str, default: Optional[int] = None) -> Optional[int]:
        v = self.param(name)
        if v is None or v == "":
            return default
        try:
            return int(v)
        except ValueError:
            raise IllegalArgumentError(f"Failed to parse int parameter [{name}] with value [{v}]")


Handler = Callable[[RestRequest], Tuple[int, Any]]


class _TrieNode:
    __slots__ = ("children", "param_child", "param_name", "handlers")

    def __init__(self):
        self.children: Dict[str, _TrieNode] = {}
        self.param_child: Optional[_TrieNode] = None
        self.param_name: Optional[str] = None
        self.handlers: Dict[str, Handler] = {}


class RestController:
    def __init__(self):
        self._root = _TrieNode()
        self._filters: List[Any] = []

    def register(self, method: str, template: str, handler: Handler) -> None:
        node = self._root
        for seg in [s for s in template.split("/") if s]:
            if seg.startswith("{") and seg.endswith("}"):
                if node.param_child is None:
                    node.param_child = _TrieNode()
                    node.param_name = seg[1:-1]
                node = node.param_child
            else:
                node = node.children.setdefault(seg, _TrieNode())
        node.handlers[method.upper()] = handler

    def _resolve(self, path: str) -> Tuple[Optional[_TrieNode], Dict[str, str]]:
        from urllib.parse import unquote
        segments = [unquote(s) for s in path.split("/") if s]

        def walk(node: _TrieNode, i: int, params: Dict[str, str]):
            if i == len(segments):
                return node if node.handlers else None, params
            seg = segments[i]
            child = node.children.get(seg)
            if child is not None:
                found, p = walk(child, i + 1, params)
                if found:
                    return found, p
            if node.param_child is not None:
                p2 = dict(params)
                p2[node.param_name] = seg
                found, p = walk(node.param_child, i + 1, p2)
                if found:
                    return found, p
            return None, params

        return walk(self._root, 0, {})

    def add_filter(self, f) -> None:
        """Install a pre-handler filter (reference: SecurityRestFilter wraps
        every handler via RestController). A filter receives the RestRequest
        and either returns None (continue) or a (status, body) short-circuit
        response; it may mutate the request (e.g. rewrite the body for
        document-level security)."""
        self._filters.append(f)

    def dispatch(self, method: str, path: str, query: Dict[str, str],
                 body: bytes, content_type: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None) -> Tuple[int, Any]:
        try:
            node, params = self._resolve(path)
            if node is None:
                return 400, _error_body(
                    "invalid_index_name_exception" if False else "illegal_argument_exception",
                    f"no handler found for uri [{path}] and method [{method}]", 400)
            handler = node.handlers.get(method.upper())
            if handler is None:
                if method.upper() == "HEAD" and "GET" in node.handlers:
                    req = RestRequest("HEAD", path, params, query, body,
                                      content_type, headers)
                    for f in self._filters:
                        short = f(req)
                        if short is not None:
                            return short[0], None
                    status, _ = node.handlers["GET"](req)
                    return status, None
                allowed = ", ".join(sorted(node.handlers))
                return 405, _error_body(
                    "method_not_allowed_exception",
                    f"Incorrect HTTP method for uri [{path}], allowed: [{allowed}]", 405)
            req = RestRequest(method.upper(), path, params, query, body,
                              content_type, headers)
            for f in self._filters:
                short = f(req)
                if short is not None:
                    return short
            status, resp = handler(req)
        except SearchEngineError as e:
            status, resp = e.status, {"error": e.to_wrapped_dict(),
                                      "status": e.status}
        except Exception as e:  # unexpected: 500 with reason, never a raw traceback
            tb = traceback.format_exc(limit=5)
            status, resp = 500, _error_body(
                "internal_server_error",
                f"{type(e).__name__}: {e}", 500, stack_trace=tb)
        # filter_path applies to error bodies too (FilterPath at the
        # xcontent layer, below the error renderer)
        fp = query.get("filter_path")
        if fp and isinstance(resp, (dict, list)):
            resp = filter_path_apply(resp, str(fp))
        return status, resp


def filter_path_apply(resp, spec: str):
    """Response filtering (reference: common/xcontent/support/filtering/
    FilterPath): comma-separated dotted patterns with * and ** wildcards;
    leading '-' patterns exclude instead."""
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    includes = [p for p in parts if not p.startswith("-")]
    excludes = [p[1:] for p in parts if p.startswith("-")]

    def match_steps(steps, obj, build):
        # returns filtered copy of obj containing only matching paths
        if not steps:
            return obj
        step, rest = steps[0], steps[1:]
        if isinstance(obj, list):
            out = []
            for item in obj:
                r = match_steps(steps, item, build)
                if r is not _SKIP:
                    out.append(r)
            return out if out else _SKIP
        if not isinstance(obj, dict):
            return _SKIP
        out = {}
        for k, v in obj.items():
            import fnmatch
            if step == "**":
                # '**' matches any number of segments: try consuming it
                # here or matching the rest at this level
                r = match_steps(rest, {k: v}, build)
                if isinstance(r, dict):
                    out.update(r)
                    continue
                r = match_steps(steps, v, build)
                if r is not _SKIP:
                    out[k] = r
            elif fnmatch.fnmatchcase(str(k), step):
                r = match_steps(rest, v, build) if rest else v
                if r is not _SKIP:
                    out[k] = r
            # non-matching keys drop
        return out if out else _SKIP

    def exclude_steps(steps, obj):
        """Filtered copy of obj with paths matching steps removed; _SKIP
        when obj itself is fully excluded. '**' spans any number of
        segments (FilterPath double-wildcard)."""
        if not steps:
            return _SKIP
        if isinstance(obj, list):
            return [r for r in (exclude_steps(steps, item) for item in obj)
                    if r is not _SKIP]
        if not isinstance(obj, dict):
            return obj
        step, rest = steps[0], steps[1:]
        import fnmatch
        out = {}
        for k, v in obj.items():
            if step == "**":
                keep = v
                # '**' already satisfied: the rest matches starting at k
                if rest and fnmatch.fnmatchcase(str(k), rest[0]):
                    if len(rest) == 1:
                        continue  # excluded leaf
                    keep = exclude_steps(rest[1:], keep)
                    if keep is _SKIP:
                        continue
                # '**' still spanning: keep consuming segments below
                keep = exclude_steps(steps, keep)
                if keep is _SKIP:
                    continue
                out[k] = keep
            elif fnmatch.fnmatchcase(str(k), step):
                if not rest:
                    continue  # excluded leaf
                keep = exclude_steps(rest, v)
                if keep is _SKIP:
                    continue
                out[k] = keep
            else:
                out[k] = v
        return out

    out = resp
    if includes:
        merged = _SKIP
        for p in includes:
            r = match_steps(p.split("."), resp, None)
            if r is _SKIP:
                continue
            merged = r if merged is _SKIP else _deep_merge(merged, r)
        out = merged if merged is not _SKIP else ({} if isinstance(resp, dict) else [])
    for p in excludes:
        out = exclude_steps(p.split("."), out)
    return out


_SKIP = object()


def _deep_merge(a, b):
    if isinstance(a, dict) and isinstance(b, dict):
        out = dict(a)
        for k, v in b.items():
            out[k] = _deep_merge(out[k], v) if k in out else v
        return out
    if isinstance(a, list) and isinstance(b, list):
        # element-wise merge keeps hit objects aligned across patterns
        out = []
        for i in range(max(len(a), len(b))):
            if i < len(a) and i < len(b):
                out.append(_deep_merge(a[i], b[i]))
            else:
                out.append(a[i] if i < len(a) else b[i])
        return out
    return a


def _error_body(err_type: str, reason: str, status: int, **extra) -> dict:
    err = {"type": err_type, "reason": reason, **extra}
    return {"error": {**err, "root_cause": [err]}, "status": status}
