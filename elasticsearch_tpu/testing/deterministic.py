"""Deterministic simulation: virtual clock, seeded scheduling, disruptable
in-memory transport.

Re-design of the reference's crown-jewel test harness (SURVEY.md §4.3):
`DeterministicTaskQueue` + `DisruptableMockTransport`
(`test/framework/.../cluster/coordination/`). Whole clusters run on one
thread with a virtual clock; message delivery order is shuffled by a seeded
RNG; partitions/drops/delays are injected; every run is reproducible from
its seed. The coordination layer is validated against safety invariants
under these schedules (the LinearizabilityChecker analog lives in the tests:
single-leader-per-term + committed-state durability).
"""

from __future__ import annotations

import heapq
import random
from typing import Any, Callable, Dict, List, Optional, Set, Tuple


class DeterministicTaskQueue:
    """Virtual-time scheduler. Tasks run one at a time; `run_random_task`
    picks among currently-runnable tasks with the seeded RNG, matching the
    reference's randomized interleavings."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.now_ms = 0
        self._runnable: List[Tuple[int, Callable[[], None], str]] = []
        self._deferred: List[Tuple[int, int, Callable[[], None], str]] = []  # (time, tiebreak, fn)
        self._counter = 0

    def schedule(self, fn: Callable[[], None], label: str = "") -> None:
        self._counter += 1
        self._runnable.append((self._counter, fn, label))

    def schedule_at(self, time_ms: int, fn: Callable[[], None], label: str = "") -> None:
        self._counter += 1
        heapq.heappush(self._deferred, (max(time_ms, self.now_ms), self._counter, fn, label))

    def schedule_in(self, delay_ms: int, fn: Callable[[], None], label: str = "") -> None:
        self.schedule_at(self.now_ms + delay_ms, fn, label)

    @property
    def has_runnable(self) -> bool:
        return bool(self._runnable)

    @property
    def has_deferred(self) -> bool:
        return bool(self._deferred)

    def _promote_due(self) -> None:
        while self._deferred and self._deferred[0][0] <= self.now_ms:
            _, counter, fn, label = heapq.heappop(self._deferred)
            self._runnable.append((counter, fn, label))

    def run_random_task(self) -> bool:
        """Run one runnable task chosen at random; advance clock if none."""
        self._promote_due()
        if not self._runnable:
            if not self._deferred:
                return False
            self.now_ms = self._deferred[0][0]
            self._promote_due()
        idx = self.rng.randrange(len(self._runnable))
        _, fn, _label = self._runnable.pop(idx)
        fn()
        return True

    def run_all_runnable(self) -> None:
        while self._runnable:
            self.run_random_task()

    def run_for(self, duration_ms: int) -> None:
        """Run everything scheduled within the next duration_ms of virtual time."""
        deadline = self.now_ms + duration_ms
        while True:
            self._promote_due()
            if self._runnable:
                self.run_random_task()
                continue
            if self._deferred and self._deferred[0][0] <= deadline:
                self.now_ms = self._deferred[0][0]
                continue
            break
        self.now_ms = deadline


class DisruptableTransport:
    """In-memory message bus between named nodes with fault injection.

    The analog of `DisruptableMockTransport`: every message is a scheduled
    task; blackholed or partitioned links silently drop (like a network
    timeout); delays are randomized within [min,max] from the seeded RNG.
    """

    def __init__(self, queue: DeterministicTaskQueue,
                 min_delay_ms: int = 1, max_delay_ms: int = 50):
        self.queue = queue
        self.min_delay_ms = min_delay_ms
        self.max_delay_ms = max_delay_ms
        self._handlers: Dict[str, Dict[str, Callable]] = {}   # node -> action -> fn
        self._blackholed: Set[str] = set()                    # nodes dropping everything
        self._partitions: Set[frozenset] = set()              # {a,b} pairs cut
        self._disconnected: Set[Tuple[str, str]] = set()      # one-way cuts

    # -- wiring ---------------------------------------------------------------
    def register(self, node_id: str, action: str,
                 handler: Callable[[str, Any, Callable[[Any], None]], None]) -> None:
        """handler(sender, request, respond) — respond sends the reply back."""
        self._handlers.setdefault(node_id, {})[action] = handler

    # -- faults ---------------------------------------------------------------
    def blackhole(self, node_id: str) -> None:
        self._blackholed.add(node_id)

    def heal_node(self, node_id: str) -> None:
        self._blackholed.discard(node_id)

    def partition(self, side_a: Set[str], side_b: Set[str]) -> None:
        for a in side_a:
            for b in side_b:
                self._partitions.add(frozenset((a, b)))

    def heal_all(self) -> None:
        self._partitions.clear()
        self._blackholed.clear()
        self._disconnected.clear()

    def _delivery_ok(self, sender: str, target: str) -> bool:
        if sender in self._blackholed or target in self._blackholed:
            return False
        if frozenset((sender, target)) in self._partitions:
            return False
        if (sender, target) in self._disconnected:
            return False
        return True

    # -- sending --------------------------------------------------------------
    def send(self, sender: str, target: str, action: str, request: Any,
             on_response: Optional[Callable[[Any], None]] = None,
             on_failure: Optional[Callable[[Exception], None]] = None) -> None:
        delay = self.queue.rng.randint(self.min_delay_ms, self.max_delay_ms)

        def deliver():
            if not self._delivery_ok(sender, target):
                return  # dropped silently, like a network timeout
            handler = self._handlers.get(target, {}).get(action)
            if handler is None:
                if on_failure:
                    self.queue.schedule(lambda: on_failure(
                        RuntimeError(f"no handler for [{action}] on [{target}]")))
                return

            def respond(response: Any) -> None:
                rdelay = self.queue.rng.randint(self.min_delay_ms, self.max_delay_ms)

                def deliver_response():
                    if not self._delivery_ok(target, sender):
                        return
                    if on_response is not None:
                        on_response(response)

                self.queue.schedule_in(rdelay, deliver_response,
                                       f"response:{action}:{target}->{sender}")

            def fail(error: Exception) -> None:
                rdelay = self.queue.rng.randint(self.min_delay_ms, self.max_delay_ms)

                def deliver_failure():
                    if not self._delivery_ok(target, sender):
                        return
                    if on_failure is not None:
                        on_failure(error)

                self.queue.schedule_in(rdelay, deliver_failure,
                                       f"failure:{action}:{target}->{sender}")

            try:
                handler(sender, request, respond)
            except Exception as e:
                fail(e)

        self.queue.schedule_in(delay, deliver, f"request:{action}:{sender}->{target}")
