"""Reusable transport fault injection: drop / delay / kill, as a wrapper.

`DisruptableTransport` (testing/deterministic.py) already drops messages
for blackholed nodes and cut links, but its faults are baked into the sim
transport — the TCP transport has none, and neither lets a test say "delay
only QUERY-phase requests to n2 by 500 ms" or "fail the next 3 sends".
This module wraps ANY transport exposing the shared `register`/`send`
surface with an injectable rule set, so the same fault scenarios drive the
deterministic simulator, the asyncio TCP stack, and the bench harness
(bench config `10_fanout_node_kill`).

Rules match on (sender, target, action) and apply in order; the first
matching rule's behavior wins:

* ``drop``      — the send vanishes (neither response nor failure: the
                  silent network-partition shape that exposes unbounded
                  coordinator waits)
* ``delay_ms``  — delivery is deferred on the scheduler; at delivery time
                  only the KILLED set is re-checked (a node killed while
                  the message was in flight still swallows it) — other
                  rules are NOT re-applied to in-flight messages. A
                  delayed request arriving after its propagated deadline
                  is exactly the slow-node shed-at-remote scenario
* ``error``     — on_failure fires with the given exception (a connection
                  reset: the fast-failure shape)

`kill_node(n)` installs drop rules for everything to AND from `n` — the
process-death fault the graceful-degradation bench gates on. `revive(n)`
heals it.

The wrapper counts every injected fault per (rule, node) so tests and
bench rows can assert the fault actually fired.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional


class FaultRule:
    """One match+behavior entry. All match fields are optional; a None
    field matches anything. `action_prefix` matches on the reference-style
    action-name prefix (e.g. "indices:data/read")."""

    _ids = itertools.count()

    def __init__(self, *, sender: Optional[str] = None,
                 target: Optional[str] = None,
                 action: Optional[str] = None,
                 action_prefix: Optional[str] = None,
                 drop: bool = False,
                 delay_ms: int = 0,
                 error: Optional[Exception] = None,
                 times: Optional[int] = None):
        if drop and (delay_ms or error):
            raise ValueError("drop is exclusive of delay/error")
        self.sender = sender
        self.target = target
        self.action = action
        self.action_prefix = action_prefix
        self.drop = drop
        self.delay_ms = int(delay_ms)
        self.error = error
        self.times = times      # None = unlimited; else fires this many
        self.fired = 0
        self.rule_id = next(self._ids)

    def matches(self, sender: str, target: str, action: str) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        if self.sender is not None and sender != self.sender:
            return False
        if self.target is not None and target != self.target:
            return False
        if self.action is not None and action != self.action:
            return False
        if self.action_prefix is not None \
                and not action.startswith(self.action_prefix):
            return False
        return True

    def describe(self) -> str:
        what = ("drop" if self.drop else
                f"delay {self.delay_ms}ms" if self.delay_ms else
                f"error {type(self.error).__name__}" if self.error else
                "noop")
        return (f"{what} [{self.sender or '*'} -> {self.target or '*'} "
                f"{self.action or self.action_prefix or '*'}]")


class FaultInjectingTransport:
    """Wrap a transport with the injectable rule set. API-compatible with
    DisruptableTransport / TcpTransportService: `register` passes through;
    `send` consults the rules first."""

    def __init__(self, inner, scheduler=None):
        self.inner = inner
        # scheduler is required only for delay rules; the sim queue and the
        # AsyncioScheduler both expose schedule_in
        self.scheduler = scheduler
        self.rules: List[FaultRule] = []
        self._killed: set = set()
        self.stats = {"dropped": 0, "delayed": 0, "errored": 0,
                      "by_node": {}}

    # ------------------------------------------------------------ rule admin
    def inject(self, rule: FaultRule) -> FaultRule:
        if rule.delay_ms and self.scheduler is None:
            raise ValueError("delay rules need a scheduler")
        self.rules.append(rule)
        return rule

    def remove(self, rule: FaultRule) -> None:
        self.rules = [r for r in self.rules if r is not rule]

    def kill_node(self, node_id: str) -> None:
        """Process death: everything to and from the node vanishes."""
        self._killed.add(node_id)

    def revive(self, node_id: str) -> None:
        self._killed.discard(node_id)

    def slow_node(self, node_id: str, delay_ms: int,
                  action_prefix: Optional[str] = None) -> FaultRule:
        return self.inject(FaultRule(target=node_id, delay_ms=delay_ms,
                                     action_prefix=action_prefix))

    def clear(self) -> None:
        self.rules = []
        self._killed.clear()

    # ------------------------------------------------------------- passthru
    def register(self, node_id: str, action: str,
                 handler: Callable) -> None:
        self.inner.register(node_id, action, handler)

    def __getattr__(self, name: str):
        # everything else (add_peer_address, blackhole, loop, ...) belongs
        # to the wrapped transport
        return getattr(self.inner, name)

    # -------------------------------------------------------------- sending
    def _count(self, kind: str, node_id: str) -> None:
        self.stats[kind] += 1
        per = self.stats["by_node"].setdefault(
            node_id, {"dropped": 0, "delayed": 0, "errored": 0})
        per[kind] += 1

    def send(self, sender: str, target: str, action: str, request: Any,
             on_response: Optional[Callable] = None,
             on_failure: Optional[Callable] = None, **kwargs) -> None:
        if sender in self._killed or target in self._killed:
            self._count("dropped", target if target in self._killed
                        else sender)
            return  # silent: a dead process neither responds nor errors
        for rule in self.rules:
            if not rule.matches(sender, target, action):
                continue
            rule.fired += 1
            if rule.drop:
                self._count("dropped", target)
                return
            if rule.error is not None:
                self._count("errored", target)
                if on_failure is not None:
                    err = rule.error
                    self.scheduler.schedule(
                        lambda: on_failure(err),
                        f"fault_error:{action}") if self.scheduler \
                        else on_failure(err)
                return
            if rule.delay_ms:
                self._count("delayed", target)
                delay = rule.delay_ms

                def deliver() -> None:
                    # at delivery only the killed set is re-checked (a
                    # node killed mid-flight swallows the message);
                    # re-entering send() would re-match this same delay
                    # rule and defer forever
                    if sender in self._killed or target in self._killed:
                        self._count("dropped", target)
                        return
                    self.inner.send(sender, target, action, request,
                                    on_response=on_response,
                                    on_failure=on_failure, **kwargs)

                self.scheduler.schedule_in(
                    delay, deliver, f"fault_delay:{action}:{target}")
                return
            break  # a matching no-behavior rule: passthrough
        self.inner.send(sender, target, action, request,
                        on_response=on_response, on_failure=on_failure,
                        **kwargs)
