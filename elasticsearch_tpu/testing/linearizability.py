"""History-based linearizability checking (Wing & Gong graph search).

Redesign of the reference's crown-jewel harness
(`test/framework/src/main/java/org/elasticsearch/cluster/coordination/
LinearizabilityChecker.java:63`), following the same sources: Gavin Lowe,
"Testing for linearizability" (CCPE 29(4), 2017) and Horn & Kroening,
"Faster linearizability checking via P-compositionality" (FORTE 2015).

A `History` records client-visible INVOCATION/RESPONSE event pairs from a
concurrent run; `is_linearizable` searches for a total order of the
operations that (a) respects real-time precedence (an op that responded
before another was invoked must linearize first) and (b) steps a
`SequentialSpec` through valid transitions. Unlike invariant checks over
internal state, this catches client-observable anomalies — e.g. a stale
read served during a partition — which is exactly what S1/S2-style
assertions cannot see.

The linearized prefix travels as an int bitmask and the memoization cache
is a set of (state, mask) pairs — the P-compositionality partitioning
(KeyedSpec) keeps each sub-history's search space small.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

INVOCATION = "invocation"
RESPONSE = "response"


class TimedOut:
    """Sentinel response for operations that never responded (the history
    completion marker; specs decide what a timed-out op may have done)."""

    _instance: Optional["TimedOut"] = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<timed-out>"


TIMED_OUT = TimedOut()


class SequentialSpec:
    """Sequential datatype specification. States must be hashable."""

    def initial_state(self) -> Any:
        raise NotImplementedError

    def next_state(self, state: Any, inp: Any, out: Any) -> Optional[Any]:
        """The successor state if (state, inp, out) is a valid transition,
        else None."""
        raise NotImplementedError

    def partition(self, events: List[tuple]) -> List[List[tuple]]:
        return [events]


class KeyedSpec(SequentialSpec):
    """Spec with keyed access: the history partitions per key
    (P-compositionality), and `next_state` sees the key-less value."""

    def get_key(self, inp: Any) -> Any:
        raise NotImplementedError

    def get_value(self, inp: Any) -> Any:
        raise NotImplementedError

    def partition(self, events: List[tuple]) -> List[List[tuple]]:
        keyed: Dict[Any, List[tuple]] = {}
        matches: Dict[int, Any] = {}
        for etype, value, eid in events:
            if etype == INVOCATION:
                key = self.get_key(value)
                keyed.setdefault(key, []).append(
                    (etype, self.get_value(value), eid))
                matches[eid] = key
            else:
                keyed[matches[eid]].append((etype, value, eid))
        return list(keyed.values())


class History:
    """Recorded sequence of invocation/response events."""

    def __init__(self, events: Optional[List[tuple]] = None):
        self.events: List[tuple] = list(events or [])
        self._next_id = max((e[2] for e in self.events), default=-1) + 1

    def invoke(self, inp: Any) -> int:
        eid = self._next_id
        self._next_id += 1
        self.events.append((INVOCATION, inp, eid))
        return eid

    def respond(self, eid: int, out: Any) -> None:
        self.events.append((RESPONSE, out, eid))

    def remove(self, eid: int) -> None:
        """Drop an operation that provably never reached the system."""
        self.events = [e for e in self.events if e[2] != eid]

    def complete(self, generator: Callable[[Any], Any]) -> None:
        """Append responses for every uncompleted invocation (at the END of
        the history: a timed-out op may linearize at any point up to it)."""
        open_invocations: Dict[int, Any] = {}
        for etype, value, eid in self.events:
            if etype == INVOCATION:
                open_invocations[eid] = value
            else:
                if eid not in open_invocations:
                    raise ValueError(f"response without invocation: {eid}")
                del open_invocations[eid]
        for eid, inp in open_invocations.items():
            self.events.append((RESPONSE, generator(inp), eid))

    def clone(self) -> "History":
        return History(self.events)

    def __len__(self):
        return len(self.events)

    def __repr__(self):
        return f"History({self.events!r})"


class _Entry:
    __slots__ = ("value", "match", "bit", "prev", "next")

    def __init__(self, value, match, bit):
        self.value = value
        self.match = match  # the response entry (None for responses)
        self.bit = bit      # contiguous internal id for the bitmask
        self.prev: Optional[_Entry] = None
        self.next: Optional[_Entry] = None

    def lift(self) -> None:
        """Unlink this invocation AND its response from the list."""
        self.prev.next = self.next
        if self.next is not None:
            self.next.prev = self.prev
        m = self.match
        m.prev.next = m.next
        if m.next is not None:
            m.next.prev = m.prev

    def unlift(self) -> None:
        m = self.match
        m.prev.next = m
        if m.next is not None:
            m.next.prev = m
        self.prev.next = self
        if self.next is not None:
            self.next.prev = self


def _linked_entries(events: List[tuple]) -> _Entry:
    """history order -> doubly linked entries with a head sentinel;
    invocations carry a pointer to their response and a contiguous bit."""
    if len(events) % 2 != 0:
        raise ValueError("mismatched invocations and responses")
    matches: Dict[int, _Entry] = {}
    entries: List[_Entry] = [None] * len(events)  # type: ignore[list-item]
    next_bit = len(events) // 2 - 1
    for i in range(len(events) - 1, -1, -1):
        etype, value, eid = events[i]
        if etype == RESPONSE:
            if eid in matches:
                raise ValueError(f"duplicate response id {eid}")
            entries[i] = matches[eid] = _Entry(value, None, next_bit)
            next_bit -= 1
        else:
            resp = matches.get(eid)
            if resp is None:
                raise ValueError(f"no response for invocation {eid}")
            entries[i] = _Entry(value, resp, resp.bit)
    head = _Entry(None, None, -1)
    last = head
    for e in entries:
        last.next = e
        e.prev = last
        last = e
    return head


def _partition_linearizable(spec: SequentialSpec,
                            events: List[tuple]) -> bool:
    state = spec.initial_state()
    linearized = 0                       # bitmask of linearized ops
    cache = {(state, 0)}                 # explored (state, prefix) pairs
    stack: List[Tuple[_Entry, Any]] = []
    head = _linked_entries(events)
    entry = head.next
    while head.next is not None:
        if entry.match is not None:
            # an invocation whose response is still pending: try to
            # linearize it here
            next_state = spec.next_state(state, entry.value,
                                         entry.match.value)
            explore = False
            if next_state is not None:
                key = (next_state, linearized | (1 << entry.bit))
                if key not in cache:
                    cache.add(key)
                    explore = True
            if explore:
                stack.append((entry, state))
                state = next_state
                linearized |= 1 << entry.bit
                entry.lift()
                entry = head.next
            else:
                entry = entry.next
        else:
            # hit a response barrier: every pending op before it failed to
            # linearize — backtrack
            if not stack:
                return False
            entry, state = stack.pop()
            linearized &= ~(1 << entry.bit)
            entry.unlift()
            entry = entry.next
    return True


def is_linearizable(spec: SequentialSpec, history: History,
                    missing_response_generator: Callable[[Any], Any]
                    = lambda inp: TIMED_OUT) -> bool:
    """True iff `history` is linearizable w.r.t. `spec`."""
    h = history.clone()
    h.complete(missing_response_generator)
    return all(_partition_linearizable(spec, part)
               for part in spec.partition(h.events))


def visualize(history: History) -> str:
    """Concurrency diagram of a (complete) history for failure messages."""
    pos = {(etype, eid): i
           for i, (etype, _v, eid) in enumerate(history.events)}
    lines = []
    for etype, value, eid in history.events:
        if etype != INVOCATION:
            continue
        begin = pos[(INVOCATION, eid)]
        end = pos.get((RESPONSE, eid), len(history.events))
        resp = next((v for t, v, i in history.events
                     if t == RESPONSE and i == eid), TIMED_OUT)
        lines.append(" " * begin + "X" * max(end - begin, 1)
                     + f"  {value!r} -> {resp!r}  ({eid})")
    return "\n".join(lines)
