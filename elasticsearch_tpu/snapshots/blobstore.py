"""Blob store backends for snapshot repositories.

Re-design of `common/blobstore/` + the repository plugins
(`repositories/blobstore/BlobStoreRepository.java`, `modules/repository-url`,
`plugins/repository-{s3,gcs,azure}` — SURVEY.md §2.10): a small byte-keyed
store interface with six backends:

- fs      — directory tree (the always-available default)
- memory  — process-global named stores (test fixture + CI parity)
- url     — read-only http(s)/file base URL (reference: repository-url)
- s3      — S3-compatible REST dialect (GET/PUT/DELETE/HEAD on
            /{bucket}/{key}, ?prefix= listing) with AWS SigV4 signing when
            credentials are configured — the shape MinIO and the
            reference's s3-fixture speak
- gcs     — Google Cloud Storage JSON/media API dialect with bearer-token
            auth (fake-gcs-server / the real service)
- azure   — Azure Block Blob dialect with SharedKey request signing
            (Azurite / the real service)
"""

from __future__ import annotations

import os
import re
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentError, SearchEngineError


class BlobStoreError(SearchEngineError):
    status = 500


class BlobStoreUnavailableError(BlobStoreError):
    """The backing service is unreachable (distinct from a missing blob)."""


class BlobStore:
    """Byte-keyed blob container; keys use '/' separators."""

    read_only = False

    def write_blob(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def write_blob_from_file(self, key: str, path: str) -> None:
        """Streaming upload; default buffers (remote dialects need the
        whole body), FsBlobStore overrides with a chunked copy."""
        with open(path, "rb") as f:
            self.write_blob(key, f.read())

    def read_blob(self, key: str) -> bytes:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def delete_blob(self, key: str) -> None:
        raise NotImplementedError

    def list_blobs(self, prefix: str = "") -> List[str]:
        raise NotImplementedError


class FsBlobStore(BlobStore):
    def __init__(self, location: str):
        self.root = location
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        root = os.path.normpath(self.root)
        path = os.path.normpath(os.path.join(root, key))
        # trailing-separator check: a bare prefix match would let
        # "../repo-evil" escape into siblings sharing the root's prefix
        if path != root and not path.startswith(root + os.sep):
            raise IllegalArgumentError(f"invalid blob key [{key}]")
        return path

    def write_blob(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".tmp", "wb") as f:
            f.write(data)
        os.replace(path + ".tmp", path)

    def write_blob_from_file(self, key: str, src_path: str) -> None:
        import shutil
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        shutil.copyfile(src_path, path + ".tmp")  # chunked, not in-memory
        os.replace(path + ".tmp", path)

    def read_blob(self, key: str) -> bytes:
        path = self._path(key)
        if not os.path.exists(path):
            raise BlobStoreError(f"missing blob [{key}]")
        with open(path, "rb") as f:
            return f.read()

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def delete_blob(self, key: str) -> None:
        path = self._path(key)
        if os.path.exists(path):
            os.remove(path)

    def list_blobs(self, prefix: str = "") -> List[str]:
        # scope the walk to the prefix's directory so listing a handful of
        # manifests doesn't traverse every content-addressed blob
        if prefix and "/" in prefix:
            walk_root = self._path(prefix.rsplit("/", 1)[0])
        else:
            walk_root = os.path.normpath(self.root)
        if not os.path.isdir(walk_root):
            return []
        out = []
        for dirpath, _dirs, files in os.walk(walk_root):
            for fn in files:
                if fn.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                key = rel.replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)


_MEMORY_STORES: Dict[str, Dict[str, bytes]] = {}
_MEMORY_STORES_LOCK = threading.Lock()


class MemoryBlobStore(BlobStore):
    """Named in-process stores — shared by name so two repositories
    pointing at the same location see the same blobs."""

    def __init__(self, location: str):
        # two repositories registering the same location concurrently
        # must end up sharing ONE store dict (tpulint TPU008)
        with _MEMORY_STORES_LOCK:
            self.blobs = _MEMORY_STORES.setdefault(location, {})

    def write_blob(self, key: str, data: bytes) -> None:
        self.blobs[key] = bytes(data)

    def read_blob(self, key: str) -> bytes:
        if key not in self.blobs:
            raise BlobStoreError(f"missing blob [{key}]")
        return self.blobs[key]

    def exists(self, key: str) -> bool:
        return key in self.blobs

    def delete_blob(self, key: str) -> None:
        self.blobs.pop(key, None)

    def list_blobs(self, prefix: str = "") -> List[str]:
        return sorted(k for k in self.blobs if k.startswith(prefix))


class UrlBlobStore(BlobStore):
    """Read-only store over a base URL (reference: modules/repository-url —
    for serving snapshots from a static file server)."""

    read_only = True

    def __init__(self, url: str):
        if not url.endswith("/"):
            url += "/"
        scheme = urllib.parse.urlsplit(url).scheme
        if scheme not in ("http", "https", "file"):
            raise IllegalArgumentError(
                f"unsupported url repository scheme [{scheme}]")
        self.base = url

    def _url(self, key: str) -> str:
        if ".." in key.split("/"):
            raise IllegalArgumentError(f"invalid blob key [{key}]")
        return self.base + urllib.parse.quote(key)

    def write_blob(self, key: str, data: bytes) -> None:
        raise IllegalArgumentError("url repository is read-only")

    def delete_blob(self, key: str) -> None:
        raise IllegalArgumentError("url repository is read-only")

    def read_blob(self, key: str) -> bytes:
        try:
            with urllib.request.urlopen(self._url(key), timeout=30) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise BlobStoreError(f"missing blob [{key}]") from None
            raise BlobStoreError(
                f"url repository error for [{key}]: HTTP {e.code}") from None
        except urllib.error.URLError as e:
            # file:// wraps FileNotFoundError in URLError — that's a missing
            # blob; anything else (refused connection, DNS) means the
            # endpoint is unreachable and verification must fail loudly
            if isinstance(getattr(e, "reason", None),
                          (FileNotFoundError, IsADirectoryError,
                           NotADirectoryError, PermissionError)):
                raise BlobStoreError(f"missing blob [{key}]") from None
            raise BlobStoreUnavailableError(
                f"url repository unreachable: {e}") from None

    def exists(self, key: str) -> bool:
        try:
            self.read_blob(key)
            return True
        except BlobStoreError:
            return False

    def list_blobs(self, prefix: str = "") -> List[str]:
        # static file servers have no listing; repositories fall back to a
        # manifest index blob (index.json) when present. A missing index is
        # an empty repo; an unreachable endpoint propagates.
        try:
            import json
            names = json.loads(self.read_blob("index.json"))
            return sorted(k for k in names if k.startswith(prefix))
        except BlobStoreUnavailableError:
            raise
        except BlobStoreError:
            return []


class S3BlobStore(BlobStore):
    """S3-compatible dialect: path-style object API over HTTP with AWS
    Signature Version 4 request signing when credentials are configured
    (reference: repository-s3 signs via the AWS SDK; MinIO and real S3
    reject anything but SigV4).

    Error taxonomy mirrors UrlBlobStore: only HTTP 404 means "missing
    blob" — connection refusals, DNS failures, and non-404 statuses raise
    BlobStoreUnavailableError so a transient endpoint outage during
    restore surfaces as unavailability, never as missing data."""

    def __init__(self, endpoint: str, bucket: str, base_path: str = "",
                 access_key: str = "", secret_key: str = "",
                 region: str = "us-east-1"):
        if not endpoint:
            raise IllegalArgumentError(
                "[endpoint] is required for s3 repositories in this build "
                "(an S3-compatible service such as MinIO or a fixture)")
        if not bucket:
            raise IllegalArgumentError("[bucket] is required for s3 "
                                       "repositories")
        if not endpoint.startswith(("http://", "https://")):
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.base_path = base_path.strip("/")
        self.region = region
        self.access_key = access_key
        self.secret_key = secret_key

    def _key(self, key: str) -> str:
        return f"{self.base_path}/{key}" if self.base_path else key

    def _url(self, key: str) -> str:
        return (f"{self.endpoint}/{self.bucket}/"
                f"{urllib.parse.quote(self._key(key))}")

    # -- SigV4 ----------------------------------------------------------------
    def _sign(self, req: "urllib.request.Request",
              payload: Optional[bytes]) -> None:
        """AWS Signature Version 4 (service "s3", single-chunk payload)."""
        import datetime
        import hashlib
        import hmac as hmac_mod

        parsed = urllib.parse.urlsplit(req.full_url)
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        payload_hash = hashlib.sha256(payload or b"").hexdigest()
        host = parsed.netloc

        canonical_query = "&".join(
            f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
            for k, v in sorted(urllib.parse.parse_qsl(
                parsed.query, keep_blank_values=True)))
        headers = {"host": host, "x-amz-content-sha256": payload_hash,
                   "x-amz-date": amz_date}
        signed_headers = ";".join(sorted(headers))
        canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
        canonical_request = "\n".join([
            req.get_method(), parsed.path or "/", canonical_query,
            canonical_headers, signed_headers, payload_hash])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        string_to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical_request.encode()).hexdigest()])

        def hm(key: bytes, msg: str) -> bytes:
            return hmac_mod.new(key, msg.encode(), hashlib.sha256).digest()

        k_date = hm(("AWS4" + self.secret_key).encode(), datestamp)
        k_region = hm(k_date, self.region)
        k_service = hm(k_region, "s3")
        k_signing = hm(k_service, "aws4_request")
        signature = hmac_mod.new(k_signing, string_to_sign.encode(),
                                 hashlib.sha256).hexdigest()
        req.add_header("x-amz-date", amz_date)
        req.add_header("x-amz-content-sha256", payload_hash)
        req.add_header(
            "Authorization",
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}")

    def _request(self, method: str, url: str, data: Optional[bytes] = None):
        req = urllib.request.Request(url, data=data, method=method)
        if self.access_key:
            self._sign(req, data)
        return urllib.request.urlopen(req, timeout=30)

    @staticmethod
    def _unavailable(op: str, key: str, e: Exception) -> BlobStoreError:
        return BlobStoreUnavailableError(
            f"s3 endpoint unavailable during {op} of [{key}]: {e}")

    def write_blob(self, key: str, data: bytes) -> None:
        try:
            with self._request("PUT", self._url(key), data):
                pass
        except urllib.error.HTTPError as e:
            raise self._unavailable("put", key, e) from None
        except urllib.error.URLError as e:
            raise self._unavailable("put", key, e) from None

    def read_blob(self, key: str) -> bytes:
        try:
            with self._request("GET", self._url(key)) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise BlobStoreError(f"missing blob [{key}]") from None
            raise self._unavailable("get", key, e) from None
        except urllib.error.URLError as e:
            raise self._unavailable("get", key, e) from None

    def exists(self, key: str) -> bool:
        try:
            with self._request("HEAD", self._url(key)):
                return True
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return False
            raise self._unavailable("head", key, e) from None
        except urllib.error.URLError as e:
            raise self._unavailable("head", key, e) from None

    def delete_blob(self, key: str) -> None:
        try:
            with self._request("DELETE", self._url(key)):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:  # deleting a missing blob is fine; outages are not
                raise self._unavailable("delete", key, e) from None
        except urllib.error.URLError as e:
            raise self._unavailable("delete", key, e) from None

    def list_blobs(self, prefix: str = "") -> List[str]:
        full_prefix = self._key(prefix)
        keys: List[str] = []
        token: Optional[str] = None
        while True:  # follow ListObjectsV2 pagination
            url = (f"{self.endpoint}/{self.bucket}/?list-type=2&prefix="
                   f"{urllib.parse.quote(full_prefix)}")
            if token:
                url += f"&continuation-token={urllib.parse.quote(token)}"
            try:
                with self._request("GET", url) as resp:
                    xml = resp.read().decode("utf-8")
            except urllib.error.URLError as e:
                raise BlobStoreError(f"s3 list failed: {e}") from None
            keys.extend(re.findall(r"<Key>([^<]+)</Key>", xml))
            m = re.search(r"<NextContinuationToken>([^<]+)"
                          r"</NextContinuationToken>", xml)
            truncated = re.search(r"<IsTruncated>true</IsTruncated>", xml)
            if m and truncated:
                token = m.group(1)
            elif truncated and not m:
                raise BlobStoreError(
                    "s3 listing truncated without a continuation token")
            else:
                break
        strip = len(self.base_path) + 1 if self.base_path else 0
        return sorted(k[strip:] for k in keys)


class GcsBlobStore(BlobStore):
    """Google Cloud Storage dialect (reference: `plugins/repository-gcs`):
    the JSON/media API — media upload via
    `POST /upload/storage/v1/b/{bucket}/o?uploadType=media&name=`, download
    via `GET /storage/v1/b/{bucket}/o/{object}?alt=media`, paged listing
    via `GET /storage/v1/b/{bucket}/o?prefix=` — against a configurable
    `endpoint` (fake-gcs-server / an in-process fixture; the real service
    with a bearer `token`). Same error taxonomy as S3BlobStore: only 404
    means missing; everything else is unavailability, never data loss."""

    def __init__(self, endpoint: str, bucket: str, base_path: str = "",
                 token: str = ""):
        if not endpoint:
            raise IllegalArgumentError(
                "[endpoint] is required for gcs repositories in this build "
                "(a GCS-compatible service such as fake-gcs-server)")
        if not bucket:
            raise IllegalArgumentError(
                "[bucket] is required for gcs repositories")
        if not endpoint.startswith(("http://", "https://")):
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.base_path = base_path.strip("/")
        self.token = token

    def _key(self, key: str) -> str:
        return f"{self.base_path}/{key}" if self.base_path else key

    def _object_url(self, key: str) -> str:
        return (f"{self.endpoint}/storage/v1/b/{self.bucket}/o/"
                f"{urllib.parse.quote(self._key(key), safe='')}")

    def _request(self, method: str, url: str, data: Optional[bytes] = None):
        req = urllib.request.Request(url, data=data, method=method)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(req, timeout=30)

    @staticmethod
    def _unavailable(op: str, key: str, e: Exception) -> BlobStoreError:
        return BlobStoreUnavailableError(
            f"gcs endpoint unavailable during {op} of [{key}]: {e}")

    def write_blob(self, key: str, data: bytes) -> None:
        url = (f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o"
               f"?uploadType=media&name="
               f"{urllib.parse.quote(self._key(key), safe='')}")
        try:
            with self._request("POST", url, data):
                pass
        except (urllib.error.HTTPError, urllib.error.URLError) as e:
            raise self._unavailable("upload", key, e) from None

    def read_blob(self, key: str) -> bytes:
        try:
            with self._request("GET",
                               self._object_url(key) + "?alt=media") as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise BlobStoreError(f"missing blob [{key}]") from None
            raise self._unavailable("get", key, e) from None
        except urllib.error.URLError as e:
            raise self._unavailable("get", key, e) from None

    def exists(self, key: str) -> bool:
        try:
            with self._request("GET", self._object_url(key)):
                return True
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return False
            raise self._unavailable("stat", key, e) from None
        except urllib.error.URLError as e:
            raise self._unavailable("stat", key, e) from None

    def delete_blob(self, key: str) -> None:
        try:
            with self._request("DELETE", self._object_url(key)):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise self._unavailable("delete", key, e) from None
        except urllib.error.URLError as e:
            raise self._unavailable("delete", key, e) from None

    def list_blobs(self, prefix: str = "") -> List[str]:
        import json as _json
        full_prefix = self._key(prefix)
        keys: List[str] = []
        token: Optional[str] = None
        while True:  # follow nextPageToken pagination
            url = (f"{self.endpoint}/storage/v1/b/{self.bucket}/o?prefix="
                   f"{urllib.parse.quote(full_prefix, safe='')}")
            if token:
                url += f"&pageToken={urllib.parse.quote(token)}"
            try:
                with self._request("GET", url) as resp:
                    page = _json.loads(resp.read())
            except (urllib.error.HTTPError, urllib.error.URLError) as e:
                raise BlobStoreError(f"gcs list failed: {e}") from None
            keys.extend(item["name"] for item in page.get("items", []))
            token = page.get("nextPageToken")
            if not token:
                break
        strip = len(self.base_path) + 1 if self.base_path else 0
        return sorted(k[strip:] for k in keys)


class AzureBlobStore(BlobStore):
    """Azure Blob Storage dialect (reference: `plugins/repository-azure`):
    Block Blob PUT/GET/DELETE on `{endpoint}/{container}/{blob}` with
    SharedKey request signing when an `account`/`key` pair is configured
    (Azurite and the real service reject unsigned requests; an unsigned
    mode remains for bare fixtures), and container listing via
    `?restype=container&comp=list&prefix=` XML with marker pagination."""

    API_VERSION = "2019-12-12"

    def __init__(self, endpoint: str, container: str, base_path: str = "",
                 account: str = "", key: str = ""):
        if not endpoint:
            raise IllegalArgumentError(
                "[endpoint] is required for azure repositories in this "
                "build (Azurite or an in-process fixture)")
        if not container:
            raise IllegalArgumentError(
                "[container] is required for azure repositories")
        if not endpoint.startswith(("http://", "https://")):
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.container = container
        self.base_path = base_path.strip("/")
        self.account = account
        self.key = key

    def _key(self, key: str) -> str:
        return f"{self.base_path}/{key}" if self.base_path else key

    def _url(self, key: str) -> str:
        return (f"{self.endpoint}/{self.container}/"
                f"{urllib.parse.quote(self._key(key))}")

    def _sign(self, req: "urllib.request.Request",
              payload: Optional[bytes]) -> None:
        """Azure SharedKey authorization (Blob service)."""
        import base64
        import datetime
        import hmac as hmac_mod

        now = datetime.datetime.now(datetime.timezone.utc)
        date = now.strftime("%a, %d %b %Y %H:%M:%S GMT")
        req.add_header("x-ms-date", date)
        req.add_header("x-ms-version", self.API_VERSION)
        length = str(len(payload)) if payload else ""
        # urllib would otherwise add its own Content-Type to data-bearing
        # requests AFTER signing — pin it explicitly so the signed value
        # and the wire value agree (a signature-checking endpoint rejects
        # any mismatch)
        ctype = ""
        if payload is not None:
            ctype = "application/octet-stream"
            req.add_header("Content-Type", ctype)
        parsed = urllib.parse.urlsplit(req.full_url)
        canon_headers = "".join(
            f"{k}:{v}\n" for k, v in sorted(
                (h.lower(), req.get_header(h.capitalize()) or
                 req.headers.get(h))
                for h in req.headers if h.lower().startswith("x-ms-"))
        )
        canon_resource = f"/{self.account}{parsed.path}"
        for qk, qv in sorted(urllib.parse.parse_qsl(
                parsed.query, keep_blank_values=True)):
            canon_resource += f"\n{qk}:{qv}"
        # VERB, Content-Encoding, Content-Language, Content-Length,
        # Content-MD5, Content-Type, Date, If-Modified-Since, If-Match,
        # If-None-Match, If-Unmodified-Since, Range
        string_to_sign = "\n".join([
            req.get_method(), "", "", length, "", ctype, "", "", "", "",
            "", "",
        ]) + canon_headers + canon_resource
        import hashlib as _hashlib
        sig = base64.b64encode(hmac_mod.new(
            base64.b64decode(self.key), string_to_sign.encode(),
            _hashlib.sha256).digest()).decode()
        req.add_header("Authorization",
                       f"SharedKey {self.account}:{sig}")

    def _request(self, method: str, url: str, data: Optional[bytes] = None,
                 headers: Optional[dict] = None):
        req = urllib.request.Request(url, data=data, method=method)
        for hk, hv in (headers or {}).items():
            req.add_header(hk, hv)
        if self.account and self.key:
            self._sign(req, data)
        return urllib.request.urlopen(req, timeout=30)

    @staticmethod
    def _unavailable(op: str, key: str, e: Exception) -> BlobStoreError:
        return BlobStoreUnavailableError(
            f"azure endpoint unavailable during {op} of [{key}]: {e}")

    def write_blob(self, key: str, data: bytes) -> None:
        try:
            with self._request("PUT", self._url(key), data,
                               {"x-ms-blob-type": "BlockBlob"}):
                pass
        except (urllib.error.HTTPError, urllib.error.URLError) as e:
            raise self._unavailable("put", key, e) from None

    def read_blob(self, key: str) -> bytes:
        try:
            with self._request("GET", self._url(key)) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise BlobStoreError(f"missing blob [{key}]") from None
            raise self._unavailable("get", key, e) from None
        except urllib.error.URLError as e:
            raise self._unavailable("get", key, e) from None

    def exists(self, key: str) -> bool:
        try:
            with self._request("HEAD", self._url(key)):
                return True
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return False
            raise self._unavailable("head", key, e) from None
        except urllib.error.URLError as e:
            raise self._unavailable("head", key, e) from None

    def delete_blob(self, key: str) -> None:
        try:
            with self._request("DELETE", self._url(key)):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise self._unavailable("delete", key, e) from None
        except urllib.error.URLError as e:
            raise self._unavailable("delete", key, e) from None

    def list_blobs(self, prefix: str = "") -> List[str]:
        full_prefix = self._key(prefix)
        keys: List[str] = []
        marker: Optional[str] = None
        while True:  # NextMarker pagination
            url = (f"{self.endpoint}/{self.container}"
                   f"?restype=container&comp=list&prefix="
                   f"{urllib.parse.quote(full_prefix, safe='')}")
            if marker:
                url += f"&marker={urllib.parse.quote(marker)}"
            try:
                with self._request("GET", url) as resp:
                    xml = resp.read().decode("utf-8")
            except (urllib.error.HTTPError, urllib.error.URLError) as e:
                raise BlobStoreError(f"azure list failed: {e}") from None
            keys.extend(re.findall(r"<Name>([^<]+)</Name>", xml))
            m = re.search(r"<NextMarker>([^<]+)</NextMarker>", xml)
            if m:
                marker = m.group(1)
            else:
                break
        strip = len(self.base_path) + 1 if self.base_path else 0
        return sorted(k[strip:] for k in keys)


def build_blob_store(rtype: str, settings: dict,
                     node_settings: Optional[dict] = None) -> BlobStore:
    """node_settings: the node's merged settings INCLUDING keystore secure
    settings — S3 credentials resolve from `s3.client.<name>.access_key` /
    `.secret_key` there when not inlined in the repository settings
    (reference: S3 creds come from the secure keystore, never the API)."""
    if rtype == "fs":
        location = settings.get("location")
        if not location:
            raise IllegalArgumentError(
                "[location] is required for fs repositories")
        return FsBlobStore(location)
    if rtype == "memory":
        return MemoryBlobStore(settings.get("location", "default"))
    if rtype == "url":
        url = settings.get("url")
        if not url:
            raise IllegalArgumentError("[url] is required for url "
                                       "repositories")
        return UrlBlobStore(url)
    if rtype == "s3":
        client = settings.get("client", "default")
        client_cfg = client if isinstance(client, dict) else {}
        client_name = client if isinstance(client, str) else "default"
        ns = node_settings or {}

        def secure(key_name, inline):
            return inline or str(
                ns.get(f"s3.client.{client_name}.{key_name}", ""))

        return S3BlobStore(
            endpoint=secure("endpoint",
                            settings.get("endpoint",
                                         client_cfg.get("endpoint", ""))),
            bucket=settings.get("bucket", ""),
            base_path=settings.get("base_path", ""),
            access_key=secure("access_key", settings.get("access_key", "")),
            secret_key=secure("secret_key", settings.get("secret_key", "")),
            region=str(settings.get(
                "region", ns.get(f"s3.client.{client_name}.region",
                                 "us-east-1"))))
    if rtype == "gcs":
        client_name = str(settings.get("client", "default"))
        ns = node_settings or {}
        return GcsBlobStore(
            endpoint=str(settings.get(
                "endpoint",
                ns.get(f"gcs.client.{client_name}.endpoint", ""))),
            bucket=settings.get("bucket", ""),
            base_path=settings.get("base_path", ""),
            token=str(settings.get(
                "token", ns.get(f"gcs.client.{client_name}.token", ""))))
    if rtype == "azure":
        client_name = str(settings.get("client", "default"))
        ns = node_settings or {}

        def secure(key_name, inline):
            return inline or str(
                ns.get(f"azure.client.{client_name}.{key_name}", ""))

        return AzureBlobStore(
            endpoint=secure("endpoint", settings.get("endpoint", "")),
            container=settings.get("container", ""),
            base_path=settings.get("base_path", ""),
            account=secure("account", settings.get("account", "")),
            key=secure("key", settings.get("key", "")))
    if rtype == "hdfs":
        raise IllegalArgumentError(
            "repository type [hdfs] requires a Hadoop client and is not "
            "available in this build; use [fs], [url], [s3], [gcs], or "
            "[azure]")
    raise IllegalArgumentError(f"unknown repository type [{rtype}]")
