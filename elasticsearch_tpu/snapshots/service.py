"""Snapshot/restore over a content-addressed blob repository.

Re-design of `snapshots/SnapshotsService` + `repositories/blobstore/
BlobStoreRepository.java` (SURVEY.md §2.10): repositories hold immutable
blobs addressed by content hash — re-snapshotting unchanged shard data
uploads nothing (the reference dedups at segment-file granularity; here the
unit is the shard commit file + translog state). Snapshot manifests list
index metadata + shard blob references; restore materializes data
directories from blobs and re-opens the index.

Backends: `fs` implemented; s3/gcs/azure/hdfs are registered-but-unavailable
(network egress), same gating as the reference's repository plugins.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError, ResourceAlreadyExistsError, ResourceNotFoundError,
    SearchEngineError,
)


class RepositoryError(SearchEngineError):
    status = 500


class Repository:
    """Content-addressed snapshot repository over any BlobStore backend
    (reference: BlobStoreRepository — one implementation, pluggable
    container underneath)."""

    def __init__(self, name: str, rtype: str, settings: dict,
                 node_settings: dict = None):
        from elasticsearch_tpu.snapshots.blobstore import build_blob_store
        self.name = name
        self.type = rtype
        self.settings = settings
        self.store = build_blob_store(rtype, settings,
                                      node_settings=node_settings)

    # -- content-addressed blobs ---------------------------------------------
    def put_blob(self, path: str) -> str:
        h = hashlib.sha256()
        with open(path, "rb") as f:  # chunked hash: segment files can be GBs
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        digest = h.hexdigest()
        key = f"blobs/{digest}"
        if not self.store.exists(key):          # incremental dedup
            self.store.write_blob_from_file(key, path)
        return digest

    def has_blob(self, digest: str) -> bool:
        return self.store.exists(f"blobs/{digest}")

    def put_bytes(self, data: bytes) -> str:
        """Store one content-addressed blob from memory (the recovery
        subsystem's block writes); returns the digest. Existing blobs
        upload nothing — that identity IS snapshot incrementality."""
        digest = hashlib.sha256(data).hexdigest()
        key = f"blobs/{digest}"
        if not self.store.exists(key):
            self.store.write_blob(key, data)
        return digest

    def get_bytes(self, digest: str) -> bytes:
        """Read one content-addressed blob, digest-VERIFIED on read-back:
        a blob whose bytes no longer hash to their address (partial
        upload, bit rot, a hostile store) raises instead of flowing into
        an engine (the TPU014 durability contract)."""
        from elasticsearch_tpu.snapshots.blobstore import BlobStoreError
        try:
            data = self.store.read_blob(f"blobs/{digest}")
        except BlobStoreError:
            raise RepositoryError(
                f"missing blob [{digest}] in repository [{self.name}]")
        if hashlib.sha256(data).hexdigest() != digest:
            # evict so the content-addressed dedup in put_bytes cannot
            # keep skipping the upload that would repair it — same
            # corrupt-at-rest-is-a-miss contract as the recovery
            # BlockCache
            try:
                self.store.delete_blob(f"blobs/{digest}")
            except Exception:
                pass  # read-only store: surface the corruption anyway
            raise RepositoryError(
                f"blob [{digest}] in repository [{self.name}] failed "
                f"digest verification (corrupt or partial)")
        return data

    def get_blob(self, digest: str, dest_path: str) -> None:
        data = self.get_bytes(digest)
        os.makedirs(os.path.dirname(dest_path), exist_ok=True)
        with open(dest_path, "wb") as f:
            f.write(data)

    # -- manifests ------------------------------------------------------------
    def put_manifest(self, snapshot: str, manifest: dict) -> None:
        self.store.write_blob(f"snapshots/{snapshot}.json",
                              json.dumps(manifest).encode("utf-8"))

    def get_manifest(self, snapshot: str) -> dict:
        from elasticsearch_tpu.snapshots.blobstore import BlobStoreError
        try:
            return json.loads(self.store.read_blob(
                f"snapshots/{snapshot}.json"))
        except BlobStoreError:
            raise ResourceNotFoundError(
                f"snapshot [{self.name}:{snapshot}] is missing")

    def list_snapshots(self) -> List[str]:
        return [k[len("snapshots/"):-len(".json")]
                for k in self.store.list_blobs("snapshots/")
                if k.endswith(".json")]

    def delete_manifest(self, snapshot: str) -> None:
        key = f"snapshots/{snapshot}.json"
        if not self.store.exists(key):
            raise ResourceNotFoundError(
                f"snapshot [{self.name}:{snapshot}] is missing")
        self.store.delete_blob(key)

    def verify(self) -> None:
        """Round-trip a marker blob (reference: VerifyRepositoryAction)."""
        if self.store.read_only:
            # read-only stores verify by listing
            self.store.list_blobs("snapshots/")
            return
        key = "tests-verify/marker"
        self.store.write_blob(key, b"ok")
        if self.store.read_blob(key) != b"ok":
            raise RepositoryError(
                f"repository [{self.name}] failed verification")
        self.store.delete_blob(key)


# back-compat alias (pre-BlobStore callers)
FsRepository = Repository
SUPPORTED_TYPES = {"fs", "memory", "url", "s3", "gcs", "azure"}


class SnapshotService:
    def __init__(self, node):
        self.node = node
        self.repositories: Dict[str, Repository] = {}

    # -- repositories ---------------------------------------------------------
    def put_repository(self, name: str, body: dict,
                       verify: bool = True) -> None:
        rtype = body.get("type")
        settings = dict(body.get("settings", {}) or {})
        loc = settings.get("location")
        if rtype == "fs" and loc and not os.path.isabs(str(loc)):
            # relative locations resolve under the node's repo root, not
            # the process CWD (reference: path.repo containment)
            ns = getattr(self.node, "settings", None)
            base = (ns.get("path.repo") if ns is not None
                    and hasattr(ns, "get") else None) \
                or os.path.join(getattr(self.node, "data_path", "."), "repos")
            settings["location"] = os.path.join(str(base), str(loc))
        repo = Repository(name, rtype, settings,
                          node_settings=getattr(self.node, "settings", None))
        if verify:
            repo.verify()
        self.repositories[name] = repo

    def verify_repository(self, name: str) -> dict:
        self.get_repository(name).verify()
        return {"nodes": {self.node.node_id: {"name": self.node.node_name}}}

    def get_repository(self, name: str) -> Repository:
        repo = self.repositories.get(name)
        if repo is None:
            raise ResourceNotFoundError(f"[{name}] missing", repository=name)
        return repo

    def delete_repository(self, name: str) -> None:
        if name not in self.repositories:
            raise ResourceNotFoundError(f"[{name}] missing")
        del self.repositories[name]

    # -- snapshot -------------------------------------------------------------
    def create_snapshot(self, repo_name: str, snapshot: str,
                        body: Optional[dict] = None) -> dict:
        repo = self.get_repository(repo_name)
        if snapshot in repo.list_snapshots():
            raise ResourceAlreadyExistsError(
                f"snapshot with the same name [{snapshot}] already exists")
        body = body or {}
        index_expr = body.get("indices", "_all")
        expr = index_expr if isinstance(index_expr, str) \
            else ",".join(index_expr)
        if body.get("ignore_unavailable"):
            parts = []
            for part in expr.split(","):
                try:
                    self.node.indices.resolve(part)
                    parts.append(part)
                except ResourceNotFoundError:
                    continue
            services = self.node.indices.resolve(",".join(parts)) \
                if parts else []
        else:
            services = self.node.indices.resolve(expr)
        from elasticsearch_tpu.version import __version__
        manifest = {"snapshot": snapshot, "state": "SUCCESS",
                    "start_time_in_millis": int(time.time() * 1000),
                    "include_global_state": bool(
                        body.get("include_global_state", True)),
                    "metadata": body.get("metadata"),
                    "version": __version__, "version_id": 8000099,
                    "indices": {}, "shards": {"total": 0, "failed": 0,
                                              "successful": 0}}
        for svc in services:
            svc.flush()  # commit everything so commit.bin is complete
            index_entry = {"settings": svc.settings.as_flat_dict(),
                           "mappings": svc.mapper_service.to_dict(),
                           "aliases": svc.aliases,
                           "shards": {}}
            for shard in svc.shards:
                # block-level shard snapshot (recovery/snapshot.py):
                # sealed segments, cached columnar blocks, the ledger
                # and trained IVF layouts, each a content-addressed
                # blob — the second snapshot of a churning index ships
                # only blocks the repository has never seen
                from elasticsearch_tpu.recovery.snapshot import (
                    snapshot_shard)
                shard_entry = snapshot_shard(
                    repo, shard.engine,
                    getattr(shard, "vector_store", None))
                index_entry["shards"][str(shard.shard_id)] = shard_entry
                manifest["shards"]["total"] += 1
                manifest["shards"]["successful"] += 1
            manifest["indices"][svc.name] = index_entry
        manifest["end_time_in_millis"] = int(time.time() * 1000)
        repo.put_manifest(snapshot, manifest)
        return {"snapshot": {"snapshot": snapshot, "state": "SUCCESS",
                             "version": manifest["version"],
                             "version_id": manifest["version_id"],
                             "indices": sorted(manifest["indices"]),
                             "shards": manifest["shards"]}}

    def get_snapshots(self, repo_name: str, expr: str = "_all") -> dict:
        repo = self.get_repository(repo_name)
        names = repo.list_snapshots()
        if expr not in ("_all", "*"):
            import fnmatch
            wanted = expr.split(",")
            names = [n for n in names
                     if any(fnmatch.fnmatch(n, w) for w in wanted)]
        out = []
        for n in names:
            m = repo.get_manifest(n)
            out.append({"snapshot": n, "state": m.get("state", "SUCCESS"),
                        "indices": sorted(m.get("indices", {})),
                        "start_time_in_millis": m.get("start_time_in_millis"),
                        "end_time_in_millis": m.get("end_time_in_millis")})
        return {"snapshots": out}

    def delete_snapshot(self, repo_name: str, snapshot: str) -> None:
        self.get_repository(repo_name).delete_manifest(snapshot)

    # -- restore --------------------------------------------------------------
    def restore_snapshot(self, repo_name: str, snapshot: str,
                         body: Optional[dict] = None) -> dict:
        repo = self.get_repository(repo_name)
        manifest = repo.get_manifest(snapshot)
        body = body or {}
        indices_expr = body.get("indices", "_all")
        rename_pattern = body.get("rename_pattern")
        rename_replacement = body.get("rename_replacement", "")
        restored = []
        import fnmatch
        import re as _re
        for index_name, entry in manifest["indices"].items():
            if indices_expr not in ("_all", "*"):
                wanted = indices_expr if isinstance(indices_expr, list) \
                    else indices_expr.split(",")
                if not any(fnmatch.fnmatch(index_name, w) for w in wanted):
                    continue
            target = index_name
            if rename_pattern:
                target = _re.sub(rename_pattern, rename_replacement, index_name)
            if self.node.indices.exists(target):
                svc = self.node.indices.get(target)
                if not svc.closed:
                    raise IllegalArgumentError(
                        f"cannot restore index [{target}] because an open "
                        f"index with same name already exists")
                # restoring over a CLOSED index replaces it
                # (RestoreService#validateExistingIndex)
                self.node.indices.delete_index(target)
            # materialize the data directory, then open the index from disk
            index_path = os.path.join(self.node.indices.data_path, target)
            num_shards = int(entry["settings"].get("index.number_of_shards", 1))
            restored_stats = {}
            for shard_id in range(num_shards):
                shard_entry = entry["shards"].get(str(shard_id), {"files": {}})
                shard_path = os.path.join(index_path, str(shard_id))
                if "blocks" in shard_entry:
                    # block manifest: digest-verified reassembly of the
                    # exact commit + derived-state sidecar — restore
                    # serves byte-identically with zero re-encoding
                    from elasticsearch_tpu.recovery.snapshot import (
                        restore_shard)
                    restored_stats[shard_id] = restore_shard(
                        repo, shard_entry, shard_path)
                else:  # pre-block manifests: raw files by digest
                    for fname, digest in shard_entry.get("files", {}).items():
                        repo.get_blob(digest,
                                      os.path.join(shard_path, fname))
            meta = {"settings": entry["settings"], "mappings": entry["mappings"],
                    "aliases": entry.get("aliases", {}), "uuid": f"{target}-restored"}
            os.makedirs(index_path, exist_ok=True)
            with open(os.path.join(index_path, "index_meta.json"), "w") as f:
                json.dump(meta, f)
            svc_r = self.node.indices.open_index(target)
            svc_r.recovery_source = {
                "type": "SNAPSHOT", "repository": repo_name,
                "snapshot": snapshot, "index": index_name,
                "version": manifest.get("version", "8.0.0")}
            # block-level restore accounting for `_recovery`/`_cat/recovery`
            svc_r.recovery_block_stats = {
                sid: st for sid, st in restored_stats.items()
                if st is not None}
            restored.append(target)
        return {"snapshot": {"snapshot": snapshot, "indices": restored,
                             "shards": {"total": len(restored), "failed": 0,
                                        "successful": len(restored)}}}
