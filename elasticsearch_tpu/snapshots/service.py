"""Snapshot/restore over a content-addressed blob repository.

Re-design of `snapshots/SnapshotsService` + `repositories/blobstore/
BlobStoreRepository.java` (SURVEY.md §2.10): repositories hold immutable
blobs addressed by content hash — re-snapshotting unchanged shard data
uploads nothing (the reference dedups at segment-file granularity; here the
unit is the shard commit file + translog state). Snapshot manifests list
index metadata + shard blob references; restore materializes data
directories from blobs and re-opens the index.

Backends: `fs` implemented; s3/gcs/azure/hdfs are registered-but-unavailable
(network egress), same gating as the reference's repository plugins.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError, ResourceAlreadyExistsError, ResourceNotFoundError,
    SearchEngineError,
)


class RepositoryError(SearchEngineError):
    status = 500


class FsRepository:
    def __init__(self, name: str, settings: dict):
        self.name = name
        self.settings = settings
        location = settings.get("location")
        if not location:
            raise IllegalArgumentError("[location] is required for fs repositories")
        self.root = location
        os.makedirs(os.path.join(self.root, "blobs"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "snapshots"), exist_ok=True)

    # -- content-addressed blobs ---------------------------------------------
    def put_blob(self, path: str) -> str:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        digest = h.hexdigest()
        target = os.path.join(self.root, "blobs", digest)
        if not os.path.exists(target):          # incremental dedup
            shutil.copyfile(path, target + ".tmp")
            os.replace(target + ".tmp", target)
        return digest

    def get_blob(self, digest: str, dest_path: str) -> None:
        src = os.path.join(self.root, "blobs", digest)
        if not os.path.exists(src):
            raise RepositoryError(f"missing blob [{digest}] in repository [{self.name}]")
        os.makedirs(os.path.dirname(dest_path), exist_ok=True)
        shutil.copyfile(src, dest_path)

    # -- manifests ------------------------------------------------------------
    def _manifest_path(self, snapshot: str) -> str:
        return os.path.join(self.root, "snapshots", f"{snapshot}.json")

    def put_manifest(self, snapshot: str, manifest: dict) -> None:
        path = self._manifest_path(snapshot)
        with open(path + ".tmp", "w") as f:
            json.dump(manifest, f)
        os.replace(path + ".tmp", path)

    def get_manifest(self, snapshot: str) -> dict:
        path = self._manifest_path(snapshot)
        if not os.path.exists(path):
            raise ResourceNotFoundError(
                f"snapshot [{self.name}:{snapshot}] is missing")
        with open(path) as f:
            return json.load(f)

    def list_snapshots(self) -> List[str]:
        out = []
        for fn in sorted(os.listdir(os.path.join(self.root, "snapshots"))):
            if fn.endswith(".json"):
                out.append(fn[:-5])
        return out

    def delete_manifest(self, snapshot: str) -> None:
        path = self._manifest_path(snapshot)
        if not os.path.exists(path):
            raise ResourceNotFoundError(f"snapshot [{self.name}:{snapshot}] is missing")
        os.remove(path)


REPOSITORY_TYPES = {"fs": FsRepository}
UNAVAILABLE_TYPES = {"s3", "gcs", "azure", "hdfs", "url"}


class SnapshotService:
    def __init__(self, node):
        self.node = node
        self.repositories: Dict[str, FsRepository] = {}

    # -- repositories ---------------------------------------------------------
    def put_repository(self, name: str, body: dict) -> None:
        rtype = body.get("type")
        if rtype in UNAVAILABLE_TYPES:
            raise IllegalArgumentError(
                f"repository type [{rtype}] requires an external service and is "
                f"not available in this build; use [fs]")
        cls = REPOSITORY_TYPES.get(rtype)
        if cls is None:
            raise IllegalArgumentError(f"unknown repository type [{rtype}]")
        self.repositories[name] = cls(name, body.get("settings", {}))

    def get_repository(self, name: str) -> FsRepository:
        repo = self.repositories.get(name)
        if repo is None:
            raise ResourceNotFoundError(f"[{name}] missing", repository=name)
        return repo

    def delete_repository(self, name: str) -> None:
        if name not in self.repositories:
            raise ResourceNotFoundError(f"[{name}] missing")
        del self.repositories[name]

    # -- snapshot -------------------------------------------------------------
    def create_snapshot(self, repo_name: str, snapshot: str,
                        body: Optional[dict] = None) -> dict:
        repo = self.get_repository(repo_name)
        if snapshot in repo.list_snapshots():
            raise ResourceAlreadyExistsError(
                f"snapshot with the same name [{snapshot}] already exists")
        body = body or {}
        index_expr = body.get("indices", "_all")
        services = self.node.indices.resolve(
            index_expr if isinstance(index_expr, str) else ",".join(index_expr))
        manifest = {"snapshot": snapshot, "state": "SUCCESS",
                    "start_time_in_millis": int(time.time() * 1000),
                    "indices": {}, "shards": {"total": 0, "failed": 0,
                                              "successful": 0}}
        for svc in services:
            svc.flush()  # commit everything so commit.bin is complete
            index_entry = {"settings": svc.settings.as_flat_dict(),
                           "mappings": svc.mapper_service.to_dict(),
                           "aliases": svc.aliases,
                           "shards": {}}
            for shard in svc.shards:
                commit = os.path.join(shard.engine.path, "commit.bin")
                files = {}
                if os.path.exists(commit):
                    files["commit.bin"] = repo.put_blob(commit)
                index_entry["shards"][str(shard.shard_id)] = {"files": files}
                manifest["shards"]["total"] += 1
                manifest["shards"]["successful"] += 1
            manifest["indices"][svc.name] = index_entry
        manifest["end_time_in_millis"] = int(time.time() * 1000)
        repo.put_manifest(snapshot, manifest)
        return {"snapshot": {"snapshot": snapshot, "state": "SUCCESS",
                             "indices": sorted(manifest["indices"]),
                             "shards": manifest["shards"]}}

    def get_snapshots(self, repo_name: str, expr: str = "_all") -> dict:
        repo = self.get_repository(repo_name)
        names = repo.list_snapshots()
        if expr not in ("_all", "*"):
            import fnmatch
            wanted = expr.split(",")
            names = [n for n in names
                     if any(fnmatch.fnmatch(n, w) for w in wanted)]
        out = []
        for n in names:
            m = repo.get_manifest(n)
            out.append({"snapshot": n, "state": m.get("state", "SUCCESS"),
                        "indices": sorted(m.get("indices", {})),
                        "start_time_in_millis": m.get("start_time_in_millis"),
                        "end_time_in_millis": m.get("end_time_in_millis")})
        return {"snapshots": out}

    def delete_snapshot(self, repo_name: str, snapshot: str) -> None:
        self.get_repository(repo_name).delete_manifest(snapshot)

    # -- restore --------------------------------------------------------------
    def restore_snapshot(self, repo_name: str, snapshot: str,
                         body: Optional[dict] = None) -> dict:
        repo = self.get_repository(repo_name)
        manifest = repo.get_manifest(snapshot)
        body = body or {}
        indices_expr = body.get("indices", "_all")
        rename_pattern = body.get("rename_pattern")
        rename_replacement = body.get("rename_replacement", "")
        restored = []
        import fnmatch
        import re as _re
        for index_name, entry in manifest["indices"].items():
            if indices_expr not in ("_all", "*"):
                wanted = indices_expr if isinstance(indices_expr, list) \
                    else indices_expr.split(",")
                if not any(fnmatch.fnmatch(index_name, w) for w in wanted):
                    continue
            target = index_name
            if rename_pattern:
                target = _re.sub(rename_pattern, rename_replacement, index_name)
            if self.node.indices.exists(target):
                raise IllegalArgumentError(
                    f"cannot restore index [{target}] because an open index with "
                    f"same name already exists")
            # materialize the data directory, then open the index from disk
            index_path = os.path.join(self.node.indices.data_path, target)
            num_shards = int(entry["settings"].get("index.number_of_shards", 1))
            for shard_id in range(num_shards):
                shard_entry = entry["shards"].get(str(shard_id), {"files": {}})
                for fname, digest in shard_entry["files"].items():
                    repo.get_blob(digest, os.path.join(index_path, str(shard_id), fname))
            meta = {"settings": entry["settings"], "mappings": entry["mappings"],
                    "aliases": entry.get("aliases", {}), "uuid": f"{target}-restored"}
            os.makedirs(index_path, exist_ok=True)
            with open(os.path.join(index_path, "index_meta.json"), "w") as f:
                json.dump(meta, f)
            self.node.indices.open_index(target)
            restored.append(target)
        return {"snapshot": {"snapshot": snapshot, "indices": restored,
                             "shards": {"total": len(restored), "failed": 0,
                                        "successful": len(restored)}}}
