"""CPU-resident int8 mirror of a vector field for latency serving.

A TPU dispatch costs a fixed host↔device round trip (~100 µs direct-attached,
far more through a tunnel); for small/medium corpora one VNNI pass on the
host CPU beats that overhead, so the serving layer (serving/batcher.py)
routes latency-sensitive searches here and keeps the device path for
throughput batches and large corpora. The reference has no such split —
Lucene scores every vector per-doc in Java (`ScoreScriptUtils.java:86-171`);
this mirror is the host-side analog of the device `Corpus`, sharing its
metric conventions (ops/similarity.py raw scores) so results are
path-independent.

Quality: rows are symmetric int8 (per-row scales); a bf16-rounded copy
re-scores an oversampled candidate set so final top-k ordering matches the
device's bf16 matmul quality rather than raw int8.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from elasticsearch_tpu import native
from elasticsearch_tpu.ops import similarity as sim

# over-retrieve factor for the int8 pass feeding the bf16 rescore
OVERSAMPLE = 3
MIN_CANDIDATES = 32


def packed_nbytes(n: int, dims: int) -> int:
    """Host memory the mirror will take (packed u8 + bf16 rescore copy)."""
    d4 = (dims + 3) // 4
    ng = (n + 15) // 16
    return ng * 16 * d4 * 4 + 2 * n * dims


class HostFieldCorpus:
    """Packed int8 corpus + bf16 rescore copy for one vector field."""

    __slots__ = ("packed", "n", "dims", "d4", "ng", "row_scales",
                 "metric", "sq_norms", "rescore_bf16")

    def __init__(self, vectors: np.ndarray, metric: str):
        vectors = np.asarray(vectors, dtype=np.float32)
        n, dims = vectors.shape
        if metric == sim.COSINE:
            norms = np.linalg.norm(vectors, axis=-1, keepdims=True)
            vectors = vectors / np.maximum(norms, 1e-30)
        self.n = n
        self.dims = dims
        self.metric = metric
        self.d4 = (dims + 3) // 4
        self.ng = (n + 15) // 16
        self.sq_norms = (vectors * vectors).sum(axis=-1).astype(np.float32)

        # the codec registry's one int8 recipe (max-abs/127 scale,
        # 1e-30 floor — an all-zero row round-trips to zeros either way)
        from elasticsearch_tpu.quant import codec as quant_codec
        enc = quant_codec.get("int8").encode_np(vectors)
        q, scales = enc.data, enc.scales
        # u8 with +128 offset: the corpus sits in vpdpbusd's unsigned operand
        rows_u8 = (q.astype(np.int16) + 128).astype(np.uint8)
        padded = np.full((self.ng * 16, self.d4 * 4), 128, dtype=np.uint8)
        padded[:n, :dims] = rows_u8
        self.packed = np.ascontiguousarray(
            padded.reshape(self.ng, 16, self.d4, 4).transpose(0, 2, 1, 3))
        self.row_scales = np.zeros(self.ng * 16, dtype=np.float32)
        self.row_scales[:n] = scales.astype(np.float32)
        # bf16-rounded copy for candidate rescore (2 bytes/element, matching
        # packed_nbytes' budget; candidate rows are widened to f32 at use)
        import ml_dtypes
        self.rescore_bf16 = vectors.astype(ml_dtypes.bfloat16)

    def nbytes(self) -> int:
        return self.packed.nbytes + self.rescore_bf16.nbytes

    def _prep(self, queries: np.ndarray) -> np.ndarray:
        queries = np.ascontiguousarray(queries, dtype=np.float32)
        if self.metric == sim.COSINE:
            qn = np.linalg.norm(queries, axis=-1, keepdims=True)
            queries = queries / np.maximum(qn, 1e-30)
        return queries

    def search(self, queries: np.ndarray, k: int,
               mask: Optional[np.ndarray] = None,
               rescore: bool = True) -> Tuple[np.ndarray, np.ndarray]:
        """Top-k host search. queries [B, D]; mask None / [n] / [B, n] bool.

        Returns (raw_scores [B, k], rows [B, k]) in ops/similarity.py raw
        conventions, -inf / -1 padding — the same contract as the device
        `knn_search`, so callers can't tell which path served them.
        """
        queries = self._prep(queries)
        b = queries.shape[0]
        k_eff = min(k, self.n)
        if k_eff == 0:
            return (np.full((b, k), -np.inf, dtype=np.float32),
                    np.full((b, k), -1, dtype=np.int32))
        m = k_eff if not rescore else min(
            self.n, max(OVERSAMPLE * k_eff, MIN_CANDIDATES))

        if self.metric == sim.L2_NORM:
            dot_mul, bias = 2.0, np.zeros(self.ng * 16, dtype=np.float32)
            bias[:self.n] = -self.sq_norms
        else:
            dot_mul, bias = 1.0, None

        kmask = None
        if mask is not None:
            mask = np.asarray(mask)
            if mask.ndim == 1:
                kmask = np.zeros(self.ng * 16, dtype=np.uint8)
                kmask[:self.n] = mask
            else:
                kmask = np.zeros((b, self.ng * 16), dtype=np.uint8)
                kmask[:, :self.n] = mask

        scores, rows = native.knn_i8p_topk(
            queries, self.packed, self.n, self.d4, self.row_scales,
            bias, dot_mul, kmask, m)

        if self.metric == sim.L2_NORM:
            # kernel returns 2·dot − ‖c‖²; raw convention subtracts ‖q‖² too
            q_sq = (queries * queries).sum(axis=-1, keepdims=True)
            scores = np.where(rows >= 0, scores - q_sq, scores)

        if not rescore:
            if scores.shape[1] < k:  # k > n: pad to the documented [B, k]
                pad = k - scores.shape[1]
                scores = np.pad(scores, ((0, 0), (0, pad)),
                                constant_values=-np.inf)
                rows = np.pad(rows, ((0, 0), (0, pad)), constant_values=-1)
            return scores[:, :k], rows[:, :k]

        # bf16 rescore of the oversampled candidates: removes the int8
        # quantization error from the final ordering (device-path quality)
        out_s = np.full((b, k), -np.inf, dtype=np.float32)
        out_r = np.full((b, k), -1, dtype=np.int32)
        for qi in range(b):
            cand = rows[qi][rows[qi] >= 0]
            if len(cand) == 0:
                continue
            sub = self.rescore_bf16[cand].astype(np.float32)
            dots = sub @ queries[qi]
            if self.metric == sim.L2_NORM:
                raw = 2.0 * dots - (queries[qi] * queries[qi]).sum() \
                    - self.sq_norms[cand]
            else:
                raw = dots
            kk = min(k, len(cand))
            sel = native.topk(raw.astype(np.float32), kk)
            out_s[qi, :kk] = raw[sel]
            out_r[qi, :kk] = cand[sel]
        return out_s, out_r
