"""Late-interaction (`rank_vectors`) field store: coarse-then-MaxSim.

Serving shape per field, mirroring the two-phase rescore the single-
vector packed rungs already run (`vectors/store.py`):

* build (lazy, per reader snapshot — `ops/bm25.LexicalShard`'s sync
  discipline): per-segment token blocks come codec-encoded from the
  columnar store (`columnar.STORE.token_block`, cached per (segment,
  field, encoding, metric, dims), so refresh re-encodes only delta
  segments), then assemble into ONE device tile [N_pad, cap, W] plus
  per-token scales [N_pad, cap] — cap is the pow-2 max tokens/doc,
  N_pad is `_pow2(n+1)` so at least one all-zero PADDING ROW always
  exists (invalid coarse candidates clamp onto it and score NEG_INF).
  The pooled per-doc centroids build a standard coarse corpus
  (`ops/knn.build_corpus`) at the mapping's coarse rung.

* search: pooled query centroids retrieve a top-(k·oversample)
  candidate window through the existing exact single-vector path
  (`knn.exact` — bucketed, warmed, strict-mode-clean), then ONE
  `maxsim.rescore` dispatch (`ops/pallas_maxsim.py`) rescores the
  whole batch's windows against the resident token tile. Ordering ties
  break by ascending global row, the engine-wide convention.

The exact oracle this path is recall-gated against is the pure-host
walker (`search/queries_ext.LateInteractionQuery`): raw f32 stored
tokens, no coarse pruning — recall@k measures what the centroid prune
plus the storage rung's quantization cost together.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.ops import dispatch, knn
from elasticsearch_tpu.ops.bm25 import _pow2
from elasticsearch_tpu.quant import tokens as quant_tokens

# widest device-eligible query, in tokens: ColBERT-style encoders emit
# 32-64; past this the [Q, Tq, D] query block's pad cost lands on every
# query sharing the batch, so wider bodies walk the host oracle (the
# plan layer counts the fallback)
MAX_QUERY_TOKENS = 128
_TQ_MIN = 8


class LateInteractionField:
    """One `rank_vectors` field's token tile + coarse corpus over a
    reader snapshot. Host numpy arrays are the source of truth; device
    mirrors upload lazily on first dispatch."""

    def __init__(self, field: str, dims: int, metric: str = "cosine",
                 encoding: str = "int8", coarse: str = "f32",
                 oversample: int = 4):
        self.field = field
        self.dims = int(dims)
        self.metric = metric
        self.encoding = encoding
        self.coarse_dtype = coarse
        self.oversample = int(oversample)
        self.version: tuple = ()
        self.n_docs = 0                 # docs bearing >= 1 token
        self.cap = 1                    # pow-2 max tokens/doc
        self.n_pad = 1                  # pow-2 tile rows (> n_docs)
        self.row_map = np.zeros(0, dtype=np.int64)
        self.tokens_total = 0
        self.tile = None                # [N_pad, cap, W] host
        self.tile_scales = None         # [N_pad, cap] f32 host
        self.coarse_corpus = None       # ops.knn.Corpus over pooled rows
        self.columnar_refresh: dict = {}
        self._device = None
        self._device_version: tuple = ()

    # ------------------------------------------------------------- build
    def sync(self, reader) -> bool:
        """(Re)assemble the token tile + coarse corpus; True if rebuilt.
        Per-segment encode work is cached in the columnar store keyed by
        (encoding, metric, dims), so a cap change (one long new doc)
        only re-assembles the tile, never re-encodes old segments."""
        from elasticsearch_tpu import columnar
        version = tuple((v.segment.seg_id, v.segment.num_docs,
                         int(v.live.sum())) for v in reader.views)
        if version == self.version:
            return False
        variant_blocks = []
        n_cached = n_extracted = 0
        for view in reader.views:
            blk, was_cached = columnar.STORE.token_block(
                view, self.field, self.encoding, self.metric, self.dims)
            if was_cached:
                n_cached += 1
            else:
                n_extracted += 1
            if blk is not None and blk.n_rows:
                variant_blocks.append(blk)
        mode = columnar.STORE.note_composition(
            self.field, "tokens", n_cached, n_extracted)
        self.columnar_refresh = {
            "blocks": n_cached + n_extracted, "cached": n_cached,
            "extracted": n_extracted, "mode": mode}

        n = sum(b.n_rows for b in variant_blocks)
        max_tokens = max((int(b.counts.max()) for b in variant_blocks
                          if len(b.counts)), default=1)
        w = quant_tokens.packed_width(self.encoding, self.dims)
        self.n_docs = n
        self.cap = _pow2(max(max_tokens, 1))
        self.n_pad = _pow2(n + 1)
        dtype = (variant_blocks[0].data.dtype if variant_blocks
                 else np.uint8)
        tile = np.zeros((self.n_pad, self.cap, w), dtype=dtype)
        scales = np.zeros((self.n_pad, self.cap), dtype=np.float32)
        pooled = np.zeros((max(n, 1), self.dims), dtype=np.float32)
        row_parts = []
        doc = 0
        total_tokens = 0
        for b in variant_blocks:
            row_parts.append(b.rows)
            pooled[doc:doc + b.n_rows] = b.pooled
            tok = 0
            for i in range(b.n_rows):
                c = int(b.counts[i])
                tile[doc + i, :c] = b.data[tok:tok + c]
                scales[doc + i, :c] = b.scales[tok:tok + c]
                tok += c
            total_tokens += tok
            doc += b.n_rows
        self.tokens_total = total_tokens
        self.row_map = (np.concatenate(row_parts) if row_parts
                        else np.zeros(0, dtype=np.int64))
        self.tile = tile
        self.tile_scales = scales
        self.coarse_corpus = (knn.build_corpus(
            pooled[:n], metric=self.metric, dtype=self.coarse_dtype,
            residual=False) if n else None)
        self.version = version
        return True

    def nbytes(self) -> int:
        if self.tile is None:
            return 0
        return int(self.tile.nbytes + self.tile_scales.nbytes)

    def _device_arrays(self):
        if self._device is not None and self._device_version == self.version:
            return self._device
        self._device = (jnp.asarray(self.tile),
                        jnp.asarray(self.tile_scales))
        self._device_version = self.version
        return self._device

    # ------------------------------------------------------------ search
    def coarse_window(self, k: int) -> int:
        """Bucketed candidate-window width for the fused rescore: the
        oversampled k, clamped to the coarse corpus then rounded up the
        k ladder (a clamp lands on the LANE-padded corpus row count,
        which the maxsim grid also admits)."""
        rows = int(self.coarse_corpus.matrix.shape[0])
        win = min(max(k * self.oversample, k), max(self.n_docs, 1))
        return dispatch.bucket_k(win, limit=rows)

    def plan_queries(self, queries: Sequence[Tuple[np.ndarray, float]]
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(q_tokens [Qp, Tq_pad, d_pad] f32, centroids [Qp, dims] f32,
        boosts [n_real]) — tokens metric-prepped through the SAME
        `quant/tokens.py` prep the stored blocks ran, zero-padded to a
        pow-2 token count and the tile's lane width; the query batch
        pads to its dispatch bucket with all-zero queries."""
        n_real = len(queries)
        n_bucket = dispatch.bucket_queries(max(n_real, 1))
        tq = 1
        prepped = []
        boosts = np.ones(n_real, dtype=np.float32)
        for i, (tokens, boost) in enumerate(queries):
            t = quant_tokens.prep_tokens(
                np.asarray(tokens, dtype=np.float32).reshape(-1, self.dims),
                self.metric)
            prepped.append(t)
            boosts[i] = np.float32(boost)
            tq = max(tq, len(t))
        tq_pad = _pow2(max(tq, _TQ_MIN))
        d_pad = quant_tokens.pad_dim(self.dims)
        q = np.zeros((n_bucket, tq_pad, d_pad), dtype=np.float32)
        cent = np.zeros((n_bucket, self.dims), dtype=np.float32)
        for i, t in enumerate(prepped):
            q[i, :len(t), :self.dims] = t
            cent[i] = quant_tokens.pool_doc(t, self.metric)
        return q, cent, boosts

    def search_batch(self, queries: Sequence[Tuple[np.ndarray, float]],
                     k: int) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Two-phase batch: coarse centroid top-W through `knn.exact`,
        fused `maxsim.rescore` over the window, per-query top-k with
        (-score, ascending row) ties. Returns [(global rows, f32
        scores)] per query."""
        empty = (np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.float32))
        if self.n_docs == 0:
            return [empty for _ in queries]
        q, cent, boosts = self.plan_queries(queries)
        wc = self.coarse_window(k)
        _scores_c, ids_c = knn.knn_search(
            jnp.asarray(cent), self.coarse_corpus, k=wc,
            metric=self.metric)
        ids_np = np.asarray(ids_c)
        # invalid coarse slots (padding rows of the coarse corpus, or
        # windows wider than the live doc count) clamp onto the token
        # tile's reserved all-zero padding row -> NEG_INF in the board
        invalid = (ids_np < 0) | (ids_np >= self.n_docs)
        ids_np = np.where(invalid, self.n_docs, ids_np).astype(np.int32)
        toks_d, scales_d = self._device_arrays()
        from elasticsearch_tpu.ops import pallas_maxsim
        board = np.asarray(pallas_maxsim.maxsim_rescore(
            jnp.asarray(ids_np), jnp.asarray(q), toks_d, scales_d))
        out = []
        for qi in range(len(queries)):
            s = board[qi]
            keep = ~invalid[qi] & (s > -np.inf) & np.isfinite(s)
            cand = ids_np[qi][keep]
            sv = s[keep]
            rows = self.row_map[cand]
            order = np.lexsort((rows, -sv))[:k]
            out.append((rows[order],
                        (sv[order] * boosts[qi]).astype(np.float32)))
        return out


class LateInteractionShard:
    """Per-reader late-interaction store: one LateInteractionField per
    `rank_vectors` field, lazily synced on first hybrid use."""

    def __init__(self):
        self._fields: Dict[str, LateInteractionField] = {}
        self._lock = threading.Lock()
        self.stats = {"searches": 0, "queries": 0, "rebuilds": 0,
                      "score_nanos": 0}

    def field(self, reader, mapper) -> LateInteractionField:
        """mapper: the field's RankVectorsFieldMapper (geometry +
        encoding come from the mapping, not the caller)."""
        with self._lock:
            lf = self._fields.get(mapper.name)
            if lf is None:
                lf = LateInteractionField(
                    mapper.name, mapper.dims, metric=mapper.similarity,
                    encoding=mapper.encoding, coarse=mapper.coarse,
                    oversample=mapper.oversample)
                self._fields[mapper.name] = lf
            if lf.sync(reader):
                self.stats["rebuilds"] += 1
            return lf

    def search_batch(self, reader, mapper, queries, k: int):
        lf = self.field(reader, mapper)
        t0 = time.perf_counter_ns()
        out = lf.search_batch(queries, k)
        self.stats["searches"] += 1
        self.stats["queries"] += len(queries)
        self.stats["score_nanos"] += time.perf_counter_ns() - t0
        return out

    def field_stats(self) -> Dict[str, dict]:
        with self._lock:
            return {name: {
                "docs": lf.n_docs, "tokens": lf.tokens_total,
                "cap": lf.cap, "encoding": lf.encoding,
                "tile_bytes": lf.nbytes(),
                "columnar_refresh": dict(lf.columnar_refresh),
            } for name, lf in self._fields.items()}

    def warmup_entries(self, reader, mapper, k: int = 10):
        """Shape-only `maxsim.rescore` warmup entries for this field's
        CURRENT tile geometry (call after a sync; a later cap/N change
        warms again on its first dispatch)."""
        import jax

        from elasticsearch_tpu.ops import pallas_maxsim
        lf = self.field(reader, mapper)
        if lf.n_docs == 0:
            return []
        w = quant_tokens.packed_width(lf.encoding, lf.dims)
        tok_dtype = jnp.uint8 if lf.encoding == "int4" else \
            jnp.asarray(lf.tile[:1, :1]).dtype
        return pallas_maxsim.warmup_entries(
            lf.n_pad, lf.cap, w, tok_dtype,
            tq_rungs=(_TQ_MIN, 32), w_buckets=(lf.coarse_window(k),),
            query_buckets=(1, 8))
