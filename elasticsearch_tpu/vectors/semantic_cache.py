"""Device-resident semantic cache: near-duplicate query reuse for kNN.

Zipf-shaped query streams (autocomplete, RAG front-ends, dashboard
refreshes) re-ask the same handful of embeddings with tiny numerical
drift — close enough that the exact top-k barely moves, far enough that
a byte-keyed request cache never hits. This cache keeps a small ring of
recent query embeddings RESIDENT on the accelerator and answers "have I
seen a query within `threshold` cosine of this one?" with one batched
matmul per coalesced batch, probed through `ops/dispatch` under its own
closed grid (`semcache.probe`: query count on the shared bucket ladder,
ring slots a fixed power of two) so steady-state probing costs zero
recompiles.

A probe hit is never served blind. The candidate entry carries the
exact f32 vectors of its cached top-k window (gathered once, at insert,
through the columnar `RowSource`), and the incoming query is rescored
against that window in exact f32 (`quant/rescore.exact_scores`). The
guard then checks dominance: for normalized metrics (cosine,
dot_product — the mapper enforces unit vectors for the latter), any doc
OUTSIDE the cached window scores at most `floor + ||q' - q||` for the
new query, where `floor` is the cached query's k-th exact score and
`||q' - q|| = sqrt(2 - 2*sim)`. Serving happens only when the rescored
k-th score clears that bound — otherwise the probe REJECTS and the
query falls through to the full device dispatch. Unnormalized metrics
(l2_norm, max_inner_product) admit no such bound from a cosine probe,
so they serve only effectively-identical resends. Windows that covered
the whole corpus (`complete`) have no "outside" and serve whenever the
threshold matches.

Invalidation is by reader identity: the store drops the ring whenever
the field's columnar fingerprint (`fc.version`) moves — refresh,
delete, or merge each mint a new fingerprint, so a stale ring can never
serve rows from a superseded snapshot. Filtered queries bypass the
cache entirely (the window is computed unfiltered).

Opt-in per index: `index.knn.semantic_cache.{enabled,size,threshold}`.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.quant import rescore as quant_rescore

# guard slack: exact-f32 rescore vs the sqrt-derived drift bound — one
# part in a thousand of the score scale, far below any ranking margin
# the threshold (default 0.995) admits
GUARD_EPS = 1e-3

# "effectively identical" query drift for unnormalized metrics
_IDENTICAL_EPS = 1e-5

_MIN_SLOTS = 8


def _probe_impl(ring, queries):
    """Max cosine similarity of each (normalized) query against the
    (normalized) resident ring — ONE [B, D] @ [D, S] matmul plus the
    row-wise max/argmax. f32 accumulation: the threshold compare
    happens at ~1e-3 granularity and bf16 products would smear it."""
    import jax.numpy as jnp
    sims = jnp.matmul(queries, ring.T,
                      preferred_element_type=jnp.float32)
    return (jnp.max(sims, axis=1),
            jnp.argmax(sims, axis=1).astype(jnp.int32))


def _grid_semcache(statics, sigs) -> bool:
    """Closed grid: ring slots a power of two (fixed per cache
    lifetime), query count on the shared bucket ladder."""
    s_slots = sigs[0][0][0]       # ring [S, D]
    q_count = sigs[1][0][0]       # queries [B, D]
    return (dispatch.is_query_bucket(q_count)
            and s_slots >= _MIN_SLOTS
            and (s_slots & (s_slots - 1)) == 0)


dispatch.DISPATCH.register("semcache.probe", _probe_impl,
                           grid_check=_grid_semcache)


def _pow2_slots(n: int) -> int:
    p = _MIN_SLOTS
    while p < n:
        p *= 2
    return p


def _normalize(q: np.ndarray) -> np.ndarray:
    q = np.asarray(q, dtype=np.float32).reshape(-1)
    return q / max(float(np.linalg.norm(q)), 1e-30)


def gather_exact_rows(fc, rows: np.ndarray) -> Optional[np.ndarray]:
    """Exact f32 vectors for engine GLOBAL rows, in `rows` order, via
    whichever exact row source the field carries: the monolithic
    columnar RowSource (rows positional in the ascending row_map), or
    the generational corpus' per-generation sources (flat-id space).
    None when no source can resolve every row — e.g. a board landed
    against a superseded snapshot — so callers skip instead of caching
    a wrong window."""
    rows = np.asarray(rows, dtype=np.int64)
    if rows.size == 0:
        return np.zeros((0, fc.dims), dtype=np.float32)
    try:
        if fc.source is not None:
            row_map = fc.row_map
            pos = np.searchsorted(row_map, rows)
            if (np.any(pos >= len(row_map))
                    or np.any(row_map[np.minimum(pos, len(row_map) - 1)]
                              != rows)):
                return None
            order = np.argsort(pos, kind="stable")
            vecs = np.asarray(fc.source.gather(pos[order]),
                              dtype=np.float32)
        elif fc.gens is not None:
            snap = fc.gens.snapshot()
            flat = np.full(rows.shape, -1, dtype=np.int64)
            for gen, off in zip(snap.generations, snap.offsets[:-1]):
                rm = gen.row_map
                if len(rm) == 0:
                    continue
                p = np.searchsorted(rm, rows)
                ok = ((p < len(rm))
                      & (rm[np.minimum(p, len(rm) - 1)] == rows)
                      & (flat < 0))
                flat[ok] = int(off) + p[ok]
            if np.any(flat < 0):
                return None
            order = np.argsort(flat, kind="stable")
            vecs = np.asarray(snap.gather_rows(flat[order]),
                              dtype=np.float32)
        else:
            return None
    except (ValueError, IndexError, AttributeError):
        return None
    if vecs.shape[0] != rows.size:
        return None
    inv = np.empty_like(order)
    inv[order] = np.arange(order.size)
    return vecs[inv]                             # back to board order


class SemanticCache:
    """Ring of recent query embeddings + their exact top-k windows for
    ONE (field, reader-fingerprint) pair. Thread-safe; the store holds
    one per field and replaces it when the fingerprint moves."""

    def __init__(self, size: int, threshold: float, dims: int,
                 metric: str, version: tuple):
        self.slots = _pow2_slots(max(int(size), 1))
        self.threshold = float(threshold)
        self.dims = int(dims)
        self.metric = metric
        self.version = version    # reader fingerprint this ring serves
        # probe side: normalized embeddings, padded rows stay zero
        # (cosine vs a zero row is 0, below any sane threshold)
        self._ring = np.zeros((self.slots, self.dims), dtype=np.float32)
        self._entries: List[Optional[dict]] = [None] * self.slots
        self._next = 0            # round-robin insertion cursor
        self._device_ring = None  # lazily uploaded; dropped on insert
        self._lock = threading.RLock()

    # ------------------------------------------------------------ probe
    def probe(self, requests, k: int, precision: str,
              num_candidates) -> Tuple[Dict[int, tuple], dict]:
        """Probe one coalesced batch of (query_vector, filter_rows)
        requests. Returns (served, stats): `served` maps request index
        -> (global_rows, raw_scores) for guard-approved hits; `stats`
        counts {"probed", "hits", "rejects", "nanos"}. Filtered
        requests and empty rings are never probed."""
        stats = {"probed": 0, "hits": 0, "rejects": 0, "nanos": 0}
        served: Dict[int, tuple] = {}
        eligible = [i for i, (q, fr) in enumerate(requests) if fr is None]
        if not eligible:
            return served, stats
        with self._lock:
            if not any(e is not None for e in self._entries):
                return served, stats
            import jax.numpy as jnp
            if self._device_ring is None:
                self._device_ring = jnp.asarray(self._ring)
            ring_dev = self._device_ring
            # snapshot entries under the lock; the guard below runs
            # lock-free on the immutable entry dicts
            entries = list(self._entries)
        t0 = time.monotonic_ns()
        n = len(eligible)
        qs = np.zeros((dispatch.bucket_queries(n), self.dims),
                      dtype=np.float32)
        for row, i in enumerate(eligible):
            qs[row] = _normalize(requests[i][0])
        best_sim, best_idx = dispatch.call(
            "semcache.probe", ring_dev, jnp.asarray(qs))
        # one bulk sync of the tiny [B] verdict boards
        best_sim = np.asarray(best_sim)[:n]
        best_idx = np.asarray(best_idx)[:n]
        stats["probed"] = n
        for row, i in enumerate(eligible):
            s = float(best_sim[row])
            if s < self.threshold:
                continue
            entry = entries[int(best_idx[row])]
            res = self._try_serve(entry, requests[i][0], k, precision,
                                  num_candidates, s)
            if res is not None:
                served[i] = res
                stats["hits"] += 1
            else:
                stats["rejects"] += 1
        stats["nanos"] = time.monotonic_ns() - t0
        return served, stats

    def _try_serve(self, entry: Optional[dict], query: np.ndarray,
                   k: int, precision: str, num_candidates,
                   probe_sim: float) -> Optional[tuple]:
        """Exact-f32 rescore of the cached window for the NEW query +
        the dominance guard. None = reject (fall through to device)."""
        if entry is None:
            return None
        if (entry["k"] < k or entry["precision"] != precision
                or entry["num_candidates"] != num_candidates):
            return None
        q = np.asarray(query, dtype=np.float32).reshape(-1)
        if q.shape[0] != self.dims:
            return None
        w = entry["rows"].shape[0]
        if w == 0:
            # complete-and-empty window: the snapshot genuinely had no
            # rows to return
            return ((np.zeros(0, dtype=np.int64),
                     np.zeros(0, dtype=np.float32))
                    if entry["complete"] else None)
        exact = quant_rescore.exact_scores(
            q[None, :], entry["vecs"][None], self.metric)[0]
        order = np.argsort(-exact, kind="stable")[:k]
        if not entry["complete"]:
            if self.metric in (sim.COSINE, sim.DOT_PRODUCT):
                margin = float(np.sqrt(max(0.0, 2.0 - 2.0 * probe_sim)))
            else:
                # cosine probe bounds nothing for l2/mip: only serve
                # effectively-identical resends
                if float(np.linalg.norm(q - entry["query"])) > _IDENTICAL_EPS:
                    return None
                margin = 0.0
            kth = float(exact[order[-1]])
            floor = float(entry["floor"])
            if kth < floor + margin - GUARD_EPS:
                return None
        return (entry["rows"][order].astype(np.int64),
                exact[order].astype(np.float32))

    # ----------------------------------------------------------- insert
    def insert_many(self, requests, results, fc, k: int, precision: str,
                    num_candidates) -> int:
        """Record freshly computed (query, top-k) pairs. `results` are
        the landed (global_rows, raw_scores) boards for `requests`
        (parallel lists). The window's exact f32 vectors are gathered
        once HERE through the columnar RowSource (or the generational
        corpus' per-generation sources) and its scores recomputed
        exactly — the floor the serve-time guard compares against must
        be exact, not coarse. Returns inserts done."""
        inserted = 0
        for (query, filter_rows), res in zip(requests, results):
            if filter_rows is not None or res is None:
                continue
            rows = np.asarray(res[0], dtype=np.int64)
            vecs = gather_exact_rows(fc, rows)
            if vecs is None:
                # the board and this snapshot disagree (or no exact row
                # source exists) — skip rather than cache a wrong window
                continue
            q = np.asarray(query, dtype=np.float32).reshape(-1)
            if rows.size:
                exact = quant_rescore.exact_scores(
                    q[None, :], vecs[None], self.metric)[0]
            else:
                exact = np.zeros(0, dtype=np.float32)
            # fewer rows than asked = the window IS the corpus: no doc
            # exists outside it, the dominance floor vanishes
            complete = rows.size < k
            entry = {
                "query": q,
                "rows": rows,
                "vecs": vecs,
                "floor": (float(exact.min()) if exact.size else -np.inf),
                "complete": bool(complete),
                "k": int(k),
                "precision": precision,
                "num_candidates": num_candidates,
            }
            with self._lock:
                slot = self._next
                self._next = (self._next + 1) % self.slots
                self._entries[slot] = entry
                self._ring[slot] = _normalize(q)
                self._device_ring = None         # re-upload lazily
            inserted += 1
        return inserted

    # ------------------------------------------------------------ intro
    def memory_size_in_bytes(self) -> int:
        with self._lock:
            total = int(self._ring.nbytes)
            for e in self._entries:
                if e is None:
                    continue
                total += int(e["query"].nbytes + e["rows"].nbytes
                             + e["vecs"].nbytes) + 64
            return total

    def entry_count(self) -> int:
        with self._lock:
            return sum(1 for e in self._entries if e is not None)
