"""Per-shard device vector store: segments → HBM-resident corpus.

The TPU-side half of `dense_vector` (SURVEY.md §2.8): where the reference
stores one BinaryDocValues blob per doc and scores with a per-doc scripted
loop, this store mirrors each vector field of a shard into a device-resident
`Corpus` (padded matrix + norms + optional int8) rebuilt from the engine's
sealed segments at refresh, with a row map joining device rows back to the
engine's global rows (and thence _id).

Refresh contract: the engine's reader is the source of truth; `sync(reader)`
re-ingests when the segment set or tombstones changed. Vectors are
append-mostly, so unchanged segments' blocks are cached and concatenation is
cheap; a full device upload happens only for new/changed segments
(refresh-cycle analog of Lucene NRT reopen).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.index.mapping import DenseVectorFieldMapper
from elasticsearch_tpu.index.segment import ShardReader
from elasticsearch_tpu.ops import knn as knn_ops
from elasticsearch_tpu.ops import similarity as sim

_METRIC_MAP = {
    "cosine": sim.COSINE,
    "dot_product": sim.DOT_PRODUCT,
    "l2_norm": sim.L2_NORM,
    "max_inner_product": sim.MAX_INNER_PRODUCT,
}


class FieldCorpus:
    """Device corpus for one vector field + host-side row maps."""

    __slots__ = ("corpus", "row_map", "metric", "dims", "version")

    def __init__(self, corpus, row_map: np.ndarray, metric: str, dims: int, version: tuple):
        self.corpus = corpus          # knn_ops.Corpus (device pytree)
        self.row_map = row_map        # device row -> engine global row
        self.metric = metric
        self.dims = dims
        self.version = version        # cache key: segment/tombstone fingerprint


def extract_field_rows(reader: ShardReader, field: str
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """(matrix [m, d] f32, row_map [m] engine global rows) for one vector
    field from ONE reader snapshot — the single source of truth for both
    the per-shard store sync and the mesh-sharded layout (keeping the two
    row spaces aligned by construction)."""
    mats: List[np.ndarray] = []
    rows: List[np.ndarray] = []
    for view in reader.views:
        seg = view.segment
        if field not in seg.vectors:
            continue
        mat, present = seg.vectors[field]
        keep = present & view.live
        locs = np.nonzero(keep)[0]
        if len(locs):
            mats.append(np.asarray(mat[locs], dtype=np.float32))
            rows.append(locs.astype(np.int64) + seg.base)
    if not mats:
        return (np.zeros((0, 0), dtype=np.float32),
                np.zeros(0, dtype=np.int64))
    return np.concatenate(mats, axis=0), np.concatenate(rows)


class VectorStoreShard:
    def __init__(self, dtype: str = "bf16"):
        self.dtype = dtype
        self._fields: Dict[str, FieldCorpus] = {}

    @staticmethod
    def _fingerprint(reader: ShardReader, field: str) -> tuple:
        parts = []
        for view in reader.views:
            seg = view.segment
            if field in seg.vectors:
                parts.append((seg.seg_id, seg.num_docs, int(view.live.sum())))
        return tuple(parts)

    def sync(self, reader: ShardReader,
             vector_mappers: Dict[str, DenseVectorFieldMapper]) -> None:
        """Re-ingest vector fields whose segment composition changed."""
        for field, mapper in vector_mappers.items():
            version = self._fingerprint(reader, field)
            cached = self._fields.get(field)
            if cached is not None and cached.version == version:
                continue
            full, row_map = extract_field_rows(reader, field)
            metric = _METRIC_MAP[mapper.similarity]
            if len(row_map) == 0:
                self._fields[field] = FieldCorpus(None, np.zeros(0, dtype=np.int64),
                                                  metric, mapper.dims, version)
                continue
            dtype = self.dtype
            if mapper.params.get("index_options", {}).get("type") == "int8_flat":
                dtype = "int8"
            corpus = knn_ops.build_corpus(full, metric=metric, dtype=dtype)
            self._fields[field] = FieldCorpus(corpus, row_map, metric,
                                              mapper.dims, version)

    def field(self, name: str) -> Optional[FieldCorpus]:
        return self._fields.get(name)

    def search(self, field: str, query_vector: np.ndarray, k: int,
               filter_rows: Optional[np.ndarray] = None,
               precision: str = "bf16") -> Tuple[np.ndarray, np.ndarray]:
        """Top-k device search. Returns (global_rows [m], raw_scores [m]),
        m <= k (padding/filtered slots removed).

        filter_rows: sorted engine global rows allowed to match (pre-filter
        bitset from a boolean query; host → device additive mask).
        """
        import jax.numpy as jnp

        fc = self._fields.get(field)
        if fc is None or fc.corpus is None or len(fc.row_map) == 0:
            return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.float32)

        mask = None
        if filter_rows is not None:
            allowed = np.isin(fc.row_map, filter_rows)
            n_pad = fc.corpus.matrix.shape[0]
            m = np.zeros(n_pad, dtype=bool)
            m[: len(allowed)] = allowed
            mask = jnp.asarray(m)

        k_eff = min(k, fc.corpus.matrix.shape[0])
        q = jnp.asarray(np.asarray(query_vector, dtype=np.float32)[None, :])
        scores, ids = knn_ops.knn_search_auto(q, fc.corpus, k=k_eff, metric=fc.metric,
                                              filter_mask=mask, precision=precision)
        scores = np.asarray(scores[0])
        ids = np.asarray(ids[0])
        valid = scores > -1e37
        ids, scores = ids[valid], scores[valid]
        in_range = ids < len(fc.row_map)
        ids, scores = ids[in_range], scores[in_range]
        return fc.row_map[ids], scores
