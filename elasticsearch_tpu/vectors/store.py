"""Per-shard device vector store: segments → HBM-resident corpus.

The TPU-side half of `dense_vector` (SURVEY.md §2.8): where the reference
stores one BinaryDocValues blob per doc and scores with a per-doc scripted
loop, this store mirrors each vector field of a shard into a device-resident
`Corpus` (padded matrix + norms + optional int8) rebuilt from the engine's
sealed segments at refresh, with a row map joining device rows back to the
engine's global rows (and thence _id).

Refresh contract: the engine's reader is the source of truth; `sync(reader)`
re-ingests when the segment set or tombstones changed. With generational
segments enabled (`index.segments.enabled`, default on — `segments/`),
a changed field absorbs the refresh as an O(delta) L0 seal plus
per-generation tombstones and a background merge scheduler amortizes
consolidation; the monolithic full build below runs only for first
builds, dtype changes, and engine-level segment rewrites (each counted
and logged — `_nodes/stats indices.segments`).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("elasticsearch_tpu.vectors")

from elasticsearch_tpu import native
from elasticsearch_tpu.index.mapping import DenseVectorFieldMapper
from elasticsearch_tpu.index.segment import ShardReader
from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import knn as knn_ops
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.quant import rescore as quant_rescore
from elasticsearch_tpu.serving.batcher import CombiningBatcher, CostModel
from elasticsearch_tpu.telemetry import metrics as _telemetry_metrics
from elasticsearch_tpu.vectors.host_corpus import HostFieldCorpus, packed_nbytes

# host int8 mirrors are built for corpora whose packed+rescore footprint is
# below this (3 bytes/element); larger corpora serve from the device only
HOST_MIRROR_MAX_BYTES = 512_000_000

# below this many rows the exhaustive matmul beats IVF routing overhead;
# tpu_ivf fields smaller than this quietly serve exhaustive
IVF_MIN_ROWS = 512

_METRIC_MAP = {
    "cosine": sim.COSINE,
    "dot_product": sim.DOT_PRODUCT,
    "l2_norm": sim.L2_NORM,
    "max_inner_product": sim.MAX_INNER_PRODUCT,
}


class _InflightSlot:
    """One dispatched-not-finalized batch's entry in the store's
    in-flight gauge. Handles carry their slot so the gauge can never
    leak: `finalize_many` releases it on the normal path, and an
    ABANDONED pending handle (a caller that dispatched several legs and
    raised before finalizing them all) releases at GC via ``__del__`` —
    a leaked increment would otherwise bias the dp router toward group
    routes for the process lifetime. release() is idempotent; GC can't
    race an explicit release because ``__del__`` only runs once nothing
    references the handle."""

    __slots__ = ("_store",)

    def __init__(self, store):
        self._store = store

    def release(self) -> None:
        store = self._store
        self._store = None
        if store is not None:
            store._end_dispatch()

    def __del__(self):
        self.release()


class FieldCorpus:
    """Device corpus for one vector field + host-side row maps."""

    __slots__ = ("corpus", "row_map", "metric", "dims", "version", "host",
                 "router", "mesh_state", "gens", "encoding", "rescore",
                 "rescore_oversample", "rescore_candidates", "source")

    def __init__(self, corpus, row_map: np.ndarray, metric: str, dims: int,
                 version: tuple, host=None, router=None, mesh_state=None,
                 gens=None, encoding: str = "bf16", rescore: bool = False,
                 rescore_oversample: int = 4,
                 rescore_candidates: int = 128, source=None):
        self.corpus = corpus          # knn_ops.Corpus (device pytree)
        self.row_map = row_map        # device row -> engine global row
        self.metric = metric
        self.dims = dims
        self.version = version        # cache key: segment/tombstone fingerprint
        self.host = host              # HostFieldCorpus latency mirror (or None)
        self.router = router          # ann.IVFRouter (tpu_ivf engine) or None
        # parallel.sharded_knn.ShardedFieldState: the mesh-resident
        # row-sharded copy + slot maps (None when the mesh router would
        # never pick this corpus)
        self.mesh_state = mesh_state
        # segments.GenerationalCorpus: the live generation lifecycle this
        # view was derived from (None = legacy monolithic field). The
        # serving path re-snapshots per dispatch, so a merge installing
        # mid-flight never invalidates an in-progress search.
        self.gens = gens
        # quantization-ladder state (`elasticsearch_tpu/quant/`): the
        # TARGET storage encoding, whether packed serving runs two-phase
        # (coarse packed top-(k·oversample) + exact f32 rescore of the
        # window), the rescore window sizes, and the columnar RowSource
        # the rescore gathers exact rows through
        self.encoding = encoding
        self.rescore = rescore
        self.rescore_oversample = rescore_oversample
        self.rescore_candidates = rescore_candidates
        self.source = source


def _pad_batch(queries: np.ndarray, n_real: int) -> np.ndarray:
    """Pad a coalesced query batch to the dispatch layer's query bucket
    (pow-2): the device jits (exhaustive and IVF alike) specialize on the
    query-count dimension, and a fresh compile per distinct batch size
    would stall serving. Pad results are sliced away by the caller."""
    b_pad = dispatch.bucket_queries(n_real)
    if b_pad != n_real:
        queries = np.concatenate(
            [queries, np.zeros((b_pad - n_real, queries.shape[1]),
                               dtype=np.float32)])
    return queries


def extract_field_rows(reader: ShardReader, field: str
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """(matrix [m, d] f32, row_map [m] engine global rows) for one vector
    field from ONE reader snapshot — now a segment-block-store read
    (`elasticsearch_tpu/columnar/`): per-segment blocks extract once and
    cache by fingerprint, so only delta segments pay extraction. This
    entry MATERIALIZES the full matrix (block concatenation) and exists
    for consumers that genuinely need the whole corpus contiguous (the
    multi-shard mesh layout build in `node.py`); the per-shard sync path
    below reads the lazy `FieldRowsView` instead and stays O(delta) on
    append-only refreshes."""
    from elasticsearch_tpu import columnar
    view = columnar.STORE.vector_view(reader, field)
    return view.matrix(), view.row_map


# index_options.type -> storage encoding (the quant codec ladder); the
# engine half of the mapping lives in `_field_engine`
_OPTION_TYPE_ENCODING = {
    "flat": None, "ivf": None,
    "int8_flat": "int8", "int8_ivf": "int8",
    "int4_flat": "int4", "int4_ivf": "int4",
    "binary_flat": "binary", "binary_ivf": "binary",
}

_DTYPE_ALIASES = {"bfloat16": "bf16", "float32": "f32"}


def device_corpus_nbytes(n_rows: int, dims: int, dtype: str) -> int:
    """Estimated resident device bytes of one field's corpus (packed
    matrix + f32 norms + per-row aux scales, `quant/codec.bytes_per_doc`)
    — the per-field accounting the mesh policy's dp-aware HBM budget
    reads (`parallel/policy.eligible`) and `_nodes/stats indices.knn`
    reports as `bytes_per_doc`."""
    from elasticsearch_tpu.quant import codec as quant_codec
    n = max(int(n_rows), 0)
    name = _DTYPE_ALIASES.get(dtype, dtype)
    try:
        return n * quant_codec.bytes_per_doc(name, int(dims))
    except (KeyError, ValueError):
        return n * (int(dims) * 4 + 4)


class VectorStoreShard:
    def __init__(self, dtype: str = "bf16",
                 host_mirror_max_bytes: int = HOST_MIRROR_MAX_BYTES,
                 knn_engine: str = "tpu", knn_nlist=None,
                 knn_nprobe="auto", knn_recall_target: float = 0.95,
                 warmup: Optional[bool] = None, topup: bool = True,
                 target_batch_latency_ms: float = 2.0,
                 async_depth: int = 2,
                 segments_enabled: bool = True,
                 segments_tier_size: int = 4,
                 segments_max_l0: int = 8,
                 segments_merge_budget_ms: float = 50.0,
                 segments_background_merge: bool = True,
                 semantic_cache_enabled: bool = False,
                 semantic_cache_size: int = 128,
                 semantic_cache_threshold: float = 0.995):
        self.dtype = dtype
        self.host_mirror_max_bytes = host_mirror_max_bytes
        self.knn_engine = knn_engine        # "tpu" (exhaustive) | "tpu_ivf"
        self.knn_nlist = knn_nlist          # None = pick_nlist(n)
        self.knn_nprobe = knn_nprobe        # "auto" | int
        self.knn_recall_target = knn_recall_target
        # None = auto: warm the dispatch grid only where compiles are the
        # serving bottleneck (real accelerator backends) or when forced
        # via ES_TPU_DISPATCH_WARMUP=1 / the node's search.dispatch.warmup
        self.warmup = warmup
        # continuous-batching knobs for the per-(field, k) batchers:
        # bucket top-up window and pipelined dispatch depth. Depth 2
        # (double buffering) holds even on the CPU floor HERE — this
        # batcher's dispatch stage is a thin launch and its finalize is
        # a GIL-releasing device wait, so keeping a second batch in
        # flight feeds the XLA queue (measured: 1cl/4cl closed-loop
        # p99/p50 1.67/1.66 at depth 2 vs 3.06/4.56 at depth 1). The
        # HYBRID executor's scheduler is the one that drops to depth 1
        # on CPU floors — its dispatch stage does real host work.
        self.topup = topup
        self.target_batch_latency_ms = target_batch_latency_ms
        self.async_depth = async_depth
        # generational device segments (elasticsearch_tpu/segments/):
        # refresh seals O(delta) L0 generations instead of rebuilding,
        # deletes tombstone, a background tiered merger consolidates
        # (`index.segments.{enabled,tier_size,max_l0,merge_budget_ms}`)
        self.segments_enabled = segments_enabled
        self.segments_tier_size = segments_tier_size
        self.segments_max_l0 = segments_max_l0
        self.segments_merge_budget_ms = segments_merge_budget_ms
        self.segments_background_merge = segments_background_merge
        self._gens: Dict[str, "GenerationalCorpus"] = {}
        # serializes FieldCorpus view installs between the refresh
        # thread (sync) and the merge thread's view_cb — without it a
        # merge install could clobber a freshly REBUILT field with a
        # view over the superseded GenerationalCorpus (stale row maps)
        self._views_lock = threading.Lock()
        # full-rebuild accounting (the pre-subsystem stall made
        # measurable): every monolithic rebuild of a previously-resident
        # corpus counts here with its reason; incremental refreshes the
        # generational path absorbed count as avoided
        self.segment_counters: Dict[str, object] = {
            "full_rebuilds": 0, "rebuilds_avoided": 0,
            "rebuild_reasons": {}}
        # per-field columnar composition summary of the LAST sync
        # ({blocks, cached, extracted, mode}) — the `columnar`
        # annotation `profile.knn` attaches so the O(delta) refresh
        # claim is inspectable per search
        self.columnar_refresh: Dict[str, dict] = {}
        # per-field quantization-ladder plan (`_encoding_plan`): target
        # encoding + two-phase rescore windows, refreshed every sync
        self._field_plans: Dict[str, dict] = {}
        # device-resident semantic cache (vectors/semantic_cache.py):
        # opt-in ring of recent query embeddings per field, probed with
        # one batched matmul before the full dispatch; invalidated by
        # the field's reader fingerprint (fc.version)
        self.semantic_cache_enabled = semantic_cache_enabled
        self.semantic_cache_size = semantic_cache_size
        self.semantic_cache_threshold = semantic_cache_threshold
        self._sem_caches: Dict[str, object] = {}
        self._fields: Dict[str, FieldCorpus] = {}
        self._batchers: Dict[tuple, CombiningBatcher] = {}
        self._batchers_lock = threading.Lock()
        # live dispatch gauge: how many coalesced batches this shard has
        # in flight (dispatched, not yet finalized). Together with the
        # batchers' queued entries it is the load signal the mesh
        # policy's dp-vs-shard router reads — queued work means a dp
        # group dispatch leaves the other groups free for it
        self._active_lock = threading.Lock()
        self._active_dispatches = 0
        # scheduler counters of batchers retired at refresh (sync drops
        # stale (field, k) variants; their history must not vanish from
        # _nodes/stats)
        self._sched_retired: Dict[str, int] = {}
        # per-phase serving telemetry (profile "knn" section, _nodes/stats)
        # restored IVF layouts (recovery/seed.py): consumed by the next
        # sync's IVF build so a restored/relocated shard re-places rows
        # into the snapshotted centroids instead of re-training k-means
        self._restored_ivf: Dict[str, dict] = {}
        self.knn_stats: Dict[str, int] = {
            "searches": 0, "ivf_searches": 0, "fallback_searches": 0,
            "ivf_trains": 0, "ivf_restores": 0,
            "mesh_searches": 0, "fused_probe_searches": 0,
            "rescore_searches": 0, "rescore_window_rows": 0,
            "rescore_promoted": 0, "rescore_nanos": 0,
            "route_nanos": 0, "score_nanos": 0, "merge_nanos": 0,
            "semantic_probes": 0, "semantic_hits": 0,
            "semantic_rejects": 0, "semantic_inserts": 0,
            "semantic_invalidations": 0, "semantic_probe_nanos": 0}
        self.last_knn_phases: dict = {}

    def _field_engine(self, mapper: DenseVectorFieldMapper) -> str:
        """Effective engine for one field: explicit index_options beat the
        index-level `index.knn.engine` setting."""
        otype = (mapper.params.get("index_options") or {}).get("type")
        if otype is not None and otype.endswith("ivf"):
            return "tpu_ivf"
        if otype is not None and otype.endswith("flat"):
            return "tpu"
        return self.knn_engine

    def _encoding_plan(self, field: str, mapper: DenseVectorFieldMapper
                       ) -> dict:
        """Resolve one field's quantization-ladder plan from its
        index_options: storage encoding, two-phase rescore enablement,
        and the rescore window sizes. Unknown `type` values raise a
        mapper error HERE too (defense in depth — the mapper validates
        at parse time, but a store fed a hand-built mapper must not
        silently fall back to f32 flat)."""
        from elasticsearch_tpu.common.errors import MapperParsingError
        from elasticsearch_tpu.quant import rescore as quant_rescore
        opts = mapper.params.get("index_options") or {}
        otype = opts.get("type")
        if otype is not None and otype not in _OPTION_TYPE_ENCODING:
            raise MapperParsingError(
                f"[{field}] unknown index_options type [{otype}]; "
                f"expected one of {sorted(_OPTION_TYPE_ENCODING)}")
        encoding = _OPTION_TYPE_ENCODING.get(otype) or self.dtype
        packed = encoding in ("int4", "binary")
        # packed rungs serve two-phase by default — the recall contract
        # (recall@10 >= 0.95 vs exact f32) is the window's, not the
        # coarse encoding's; int8 `rescore` keeps the device residual
        # path
        rescore = bool(opts.get("rescore", packed))
        oversample = int(opts.get(
            "rescore_oversample",
            quant_rescore.DEFAULT_OVERSAMPLE.get(encoding, 4)))
        return {
            "encoding": encoding,
            "rescore": rescore,
            "rescore_oversample": max(oversample, 1),
            # the int8 residual path's device window (the old fixed 128
            # == default oversample 4 x 32), now `rescore_oversample`-
            # driven — the `"rescore": true` small fix
            "rescore_candidates": max(oversample, 1) * 32,
        }

    # ------------------------------------------------- durable elasticity
    def export_ivf_layout(self) -> Dict[str, dict]:
        """Trained IVF layouts of every field currently routed through
        an IVFIndex (corpus-independent: centroids + shape), for the
        recovery subsystem's shard snapshots."""
        from elasticsearch_tpu.ann.ivf_index import export_layout
        out: Dict[str, dict] = {}
        with self._views_lock:
            fields = dict(self._fields)
        for field, fc in fields.items():
            router = getattr(fc, "router", None)
            index = getattr(router, "index", None)
            if index is not None:
                out[field] = export_layout(index)
        return out

    def restore_ivf_layout(self, layouts: Dict[str, dict]) -> None:
        """Stage restored layouts for the next sync's IVF build (see
        `sync`); unknown/incompatible layouts are simply never consumed
        and the build falls back to training."""
        self._restored_ivf.update(layouts or {})

    @staticmethod
    def _fingerprint(reader: ShardReader, field: str) -> tuple:
        parts = []
        for view in reader.views:
            seg = view.segment
            if field in seg.vectors:
                parts.append((seg.seg_id, seg.num_docs, int(view.live.sum())))
        return tuple(parts)

    def sync(self, reader: ShardReader,
             vector_mappers: Dict[str, DenseVectorFieldMapper]) -> None:
        """Re-ingest vector fields whose segment composition changed.

        Generational path first: an established field absorbs the
        refresh as tombstones + an O(delta) L0 seal
        (`GenerationalCorpus.try_incremental`) — no corpus re-upload, no
        IVF retrain, no mesh rebuild on this thread. Only first builds
        and incompatible reader shapes (dtype change, engine segment
        rewrite) fall through to the monolithic full build, which is
        counted and logged as the rebuild stall it is."""
        from elasticsearch_tpu import columnar
        for field, mapper in vector_mappers.items():
            version = self._fingerprint(reader, field)
            cached = self._fields.get(field)
            plan = self._encoding_plan(field, mapper)
            # a mapping update (dtype rung, rescore window) must re-sync
            # even when the reader fingerprint is unchanged — the
            # generational path absorbs it as a merge-thread re-encode
            # retarget, never a serving-path rebuild
            if (cached is not None and cached.version == version
                    and self._field_plans.get(field) == plan):
                continue
            # block-store read: per-segment extraction is delta-only by
            # construction; nothing corpus-sized materializes unless a
            # monolithic rebuild below actually needs the full matrix
            view = columnar.STORE.vector_view(reader, field)
            row_map = view.row_map
            self.columnar_refresh[field] = view.refresh
            metric = _METRIC_MAP[mapper.similarity]
            # recorded BEFORE the empty-field continue too: the
            # plan-equality short-circuit above must fire for empty
            # fields on the next refresh, not re-sync them forever
            self._field_plans[field] = plan
            if len(row_map) == 0:
                self._fields[field] = FieldCorpus(None, np.zeros(0, dtype=np.int64),
                                                  metric, mapper.dims, version)
                self._gens.pop(field, None)
                continue
            dtype = plan["encoding"]
            opts = mapper.params.get("index_options", {})
            # the residual level is the int8 rung's device-side rescore
            # store; packed rungs rescore host-side through the columnar
            # RowSource instead, so their corpus never carries one
            residual = plan["rescore"] and dtype == "int8"
            gc = self._gens.get(field) if self.segments_enabled else None
            if gc is not None:
                if cached is None or self._reader_prefix_ok(
                        cached.version, version):
                    outcome = gc.try_incremental(
                        view, row_map, dtype=dtype, metric=metric,
                        rescore=residual)
                else:
                    # the engine rewrote segments (merge): row ids were
                    # re-based, so identical ids no longer name
                    # identical docs — only a rebuild is sound
                    gc.last_rebuild_reason = "segment_rewrite"
                    outcome = None
                if outcome is not None:
                    if outcome != "noop":
                        self.segment_counters["rebuilds_avoided"] += 1
                    with self._views_lock:
                        self._fields[field] = self._generational_view(
                            gc, metric, mapper.dims, version, plan=plan)
                    with self._batchers_lock:
                        for key in [k for k in self._batchers
                                    if k[0] == field]:
                            self._retire_sched(self._batchers.pop(key))
                    continue
            rebuild_reason = (gc.last_rebuild_reason if gc is not None
                              else self._rebuild_reason(cached, row_map,
                                                        dtype))
            # monolithic rebuild: the ONE sync shape that materializes
            # the whole matrix (block concatenation — extraction itself
            # was still delta-cached above)
            full = view.matrix()
            # `"rescore": true` on the int8 rung additionally keeps the
            # residual rescore level — the analog of Lucene retaining raw
            # f32 vectors beside the quantized copy (reference
            # DenseVectorFieldMapper int8 path), at 2 B/dim total instead
            # of 5. Off by default: int8_flat deployments size HBM against
            # 1 B/dim, and the main scan never reads the residual.
            if dtype in ("int4", "binary"):
                # packed rungs assemble from the columnar store's
                # per-segment ENCODED blocks (cached per fingerprint
                # like the f32 rows — only delta segments re-encode);
                # byte-identical to encoding `full` monolithically
                data, enc_scales, enc_rows, _mode = \
                    columnar.STORE.encoded_rows(reader, field, dtype,
                                                mapper.similarity)
                corpus = knn_ops.corpus_from_encoded(
                    data, enc_scales, full, metric=metric, dtype=dtype)
            else:
                corpus = knn_ops.build_corpus(
                    full, metric=metric, dtype=dtype, residual=residual)
            host = None
            # quantized fields score their packed encoding on the device;
            # a bf16-rescored host mirror would make result quality depend
            # on routing — skip it so the route stays invisible to callers
            if (native.AVAILABLE
                    and dtype not in ("int8", "int4", "binary")
                    and packed_nbytes(len(row_map), mapper.dims)
                    <= self.host_mirror_max_bytes):
                host = HostFieldCorpus(full, metric)
            router = None
            if (self._field_engine(mapper) == "tpu_ivf"
                    and len(row_map) >= IVF_MIN_ROWS):
                # partition layout built from the SAME extraction as the
                # flat corpus, so IVF row ids index the corpus matrix (and
                # row_map) directly; the flat corpus stays resident as the
                # router's exhaustive escape hatch
                from elasticsearch_tpu.ann import (
                    IVFRouter, build_ivf_index)
                old = cached.router if cached is not None else None
                old_n = len(cached.row_map) if cached is not None else 0
                if (old is not None and not old.index.needs_retrain
                        and old.index.dtype == dtype
                        and old.index.metric == metric
                        and 0 < old_n <= len(row_map)
                        and np.array_equal(row_map[:old_n],
                                           cached.row_map)):
                    # append-only refresh (new sealed segments, no
                    # deletes): place only the delta rows into the
                    # existing layout — keeps the trained centroids and
                    # the tuned nprobe instead of retraining k-means on
                    # every refresh. Drift accumulates in the
                    # displacement/spill counters until the retrain
                    # threshold forces the full rebuild below.
                    old.index.add(full[old_n:],
                                  np.arange(old_n, len(row_map),
                                            dtype=np.int32))
                    if not old.index.needs_retrain:
                        router = old
                if router is None:
                    nlist = opts.get("nlist", self.knn_nlist)
                    nprobe = opts.get("nprobe", self.knn_nprobe)
                    ivf = None
                    layout = self._restored_ivf.pop(field, None)
                    if layout is not None:
                        # durable elasticity: a restored/relocated shard
                        # re-places rows into the snapshotted trained
                        # centroids — zero k-means retraining, identical
                        # probe routing (recovery/seed.py installs the
                        # layout before this first sync)
                        from elasticsearch_tpu.ann.ivf_index import (
                            ivf_from_layout, layout_compatible)
                        if layout_compatible(layout, len(row_map),
                                             mapper.dims, metric, dtype):
                            ivf = ivf_from_layout(layout, full)
                            self.knn_stats["ivf_restores"] += 1
                    if ivf is None:
                        ivf = build_ivf_index(
                            full, metric=metric,
                            nlist=int(nlist) if nlist is not None else None,
                            dtype=dtype, seed=0)
                        self.knn_stats["ivf_trains"] += 1
                    router = IVFRouter(
                        ivf, nprobe=nprobe,
                        recall_target=self.knn_recall_target)
            mesh_state = None
            from elasticsearch_tpu.parallel import policy as mesh_policy
            if mesh_policy.eligible(
                    len(row_map),
                    device_bytes=device_corpus_nbytes(
                        len(row_map), mapper.dims, dtype)):
                from elasticsearch_tpu.parallel.sharded_knn import (
                    extend_or_build)
                mesh = mesh_policy.serving_mesh()
                old_ms = cached.mesh_state if cached is not None else None
                old_n = len(cached.row_map) if cached is not None else 0
                # append-only refresh (new sealed segments, no deletes):
                # ship ONLY the delta rows into the per-shard padded
                # headroom (`mesh.append`, copy-on-write — in-flight
                # searches keep the old state's buffers). Deletes or a
                # mesh/dtype change rebuild the sharded copy.
                prefix = old_n if (old_ms is not None
                                   and 0 < old_n <= len(row_map)
                                   and np.array_equal(row_map[:old_n],
                                                      cached.row_map)) \
                    else 0
                mesh_state, _ = extend_or_build(
                    old_ms if prefix else None, full, prefix, mesh,
                    metric, dtype)
            if (cached is not None and cached.corpus is not None
                    and rebuild_reason is not None):
                self.segment_counters["full_rebuilds"] += 1
                reasons = self.segment_counters["rebuild_reasons"]
                reasons[rebuild_reason] = \
                    reasons.get(rebuild_reason, 0) + 1
                logger.info(
                    "full corpus rebuild for field [%s]: reason=%s "
                    "rows=%d (the generational segments path avoids "
                    "this stall for append/delete refreshes)",
                    field, rebuild_reason, len(row_map))
            gens = None
            if self.segments_enabled:
                from elasticsearch_tpu.segments import (
                    GenerationalCorpus, TieredMergePolicy)
                gens = GenerationalCorpus.from_monolithic(
                    corpus, row_map, view.as_source(), metric, dtype,
                    residual, mapper.dims, host=host, router=router,
                    mesh_state=mesh_state,
                    policy=TieredMergePolicy(self.segments_tier_size,
                                             self.segments_max_l0),
                    merge_budget_ms=self.segments_merge_budget_ms,
                    background=self.segments_background_merge,
                    warmup_cb=self._segments_warmup_cb,
                    view_cb=(lambda g, _f=field:
                             self._reinstall_view(_f, g)),
                    knn_params={
                        "engine": self._field_engine(mapper),
                        "nlist": opts.get("nlist", self.knn_nlist),
                        "nprobe": opts.get("nprobe", self.knn_nprobe),
                        "recall_target": self.knn_recall_target,
                        "min_rows": IVF_MIN_ROWS,
                        "host_mirror_max_bytes":
                            self.host_mirror_max_bytes})
            with self._views_lock:
                if gens is not None:
                    self._gens[field] = gens
                self._fields[field] = FieldCorpus(
                    corpus, row_map, metric, mapper.dims, version,
                    host=host, router=router, mesh_state=mesh_state,
                    gens=gens, encoding=dtype, rescore=plan["rescore"],
                    rescore_oversample=plan["rescore_oversample"],
                    rescore_candidates=plan["rescore_candidates"],
                    source=view.as_source())
            with self._batchers_lock:
                for key in [k for k in self._batchers if k[0] == field]:
                    self._retire_sched(self._batchers.pop(key))
            self._schedule_warmup(self._fields[field])

    @staticmethod
    def _reader_prefix_ok(old_version: tuple, new_version: tuple) -> bool:
        """Incremental refreshes require the old reader's segment set to
        be a PREFIX of the new one (same seg ids/sizes, live counts only
        shrinking, new segments appended) — the Lucene NRT contract. An
        engine segment rewrite re-bases rows, so an identical row id no
        longer names an identical doc and the row-id delta classifier
        would silently mis-seal."""
        if len(old_version) > len(new_version):
            return False
        return all(o[0] == n[0] and o[1] == n[1] and o[2] >= n[2]
                   for o, n in zip(old_version, new_version))

    @staticmethod
    def _rebuild_reason(cached: Optional[FieldCorpus],
                        row_map: np.ndarray,
                        dtype: str) -> Optional[str]:
        """Why a monolithic full build is replacing a resident corpus
        (None = first build, not a rebuild) — the pre-subsystem stall
        accounting the generational path is measured against."""
        if cached is None or cached.corpus is None \
                or len(cached.row_map) == 0:
            return None
        from elasticsearch_tpu.quant import codec as quant_codec
        want = quant_codec.MATRIX_DTYPES.get(dtype, dtype)
        if str(cached.corpus.matrix.dtype) != want:
            return "dtype_change"
        old = cached.row_map
        if len(row_map) >= len(old) \
                and np.array_equal(row_map[:len(old)], old):
            # the monolithic path re-uploads the whole corpus for a pure
            # append — the exact headroom-exhaustion stall the
            # generational seal removes
            return "append_headroom"
        if np.isin(old, row_map, invert=True).any():
            return "deletes"
        return "segment_rewrite"

    def _segments_warmup_cb(self, entries) -> None:
        """Pre-compile a freshly sealed/merged generation's search grid
        (policy-gated like every other warmup)."""
        if self.warmup_enabled():
            dispatch.DISPATCH.warmup(entries, background=True)

    def _generational_view(self, gc, metric: str, dims: int,
                           version: tuple,
                           plan: Optional[dict] = None) -> FieldCorpus:
        """FieldCorpus snapshot-view over the current generation set:
        base fields for the single-generation fast path, the FLAT row
        map (concatenated generation row maps — tombstoned slots stay,
        masked at search) for the fan-out path."""
        snap = gc.snapshot()
        base = snap.generations[0]
        plan = plan or {}
        from elasticsearch_tpu.quant import rescore as quant_rescore
        enc = plan.get("encoding", gc.dtype)
        return FieldCorpus(
            base.corpus, snap.row_map, metric, dims, version,
            host=base.host if snap.simple else None,
            router=base.router, mesh_state=base.mesh_state, gens=gc,
            encoding=enc,
            rescore=plan.get("rescore", enc in ("int4", "binary")),
            rescore_oversample=plan.get(
                "rescore_oversample",
                quant_rescore.DEFAULT_OVERSAMPLE.get(enc, 4)),
            rescore_candidates=plan.get("rescore_candidates", 128))

    def _reinstall_view(self, field: str, gc) -> None:
        """Refresh the installed view after a background merge installs
        a new generation set, and retire the field's batchers (their
        closures captured the pre-merge view) — together these drop the
        stale device refs so the pre-merge base corpus can be reclaimed
        once in-flight searches land. Guarded by `_views_lock` against a
        concurrent sync() REBUILD: the install only lands while `gc` is
        still the field's authoritative lifecycle."""
        with self._views_lock:
            if self._gens.get(field) is not gc:
                return
            fc = self._fields.get(field)
            if fc is None or fc.gens is not gc:
                return
            self._fields[field] = self._generational_view(
                gc, fc.metric, fc.dims, fc.version,
                plan=self._field_plans.get(field))
        with self._batchers_lock:
            for key in [k for k in self._batchers if k[0] == field]:
                self._retire_sched(self._batchers.pop(key))

    def segment_stats(self) -> dict:
        """Generational-segment counters for `_nodes/stats
        indices.segments`: rebuilds (+reasons) and rebuilds avoided at
        the store level, generation/tier/merge counters summed over this
        shard's fields."""
        out = {
            "full_rebuilds": self.segment_counters["full_rebuilds"],
            "rebuilds_avoided": self.segment_counters["rebuilds_avoided"],
            "rebuild_reasons": dict(self.segment_counters
                                    ["rebuild_reasons"]),
            "enabled": self.segments_enabled,
        }
        agg: Dict[str, int] = {}
        tiers: Dict[str, dict] = {}
        for gc in list(self._gens.values()):
            st = gc.segment_stats()
            for key, val in st.items():
                if key == "tiers":
                    for t, tv in val.items():
                        slot = tiers.setdefault(
                            t, {k: 0 for k in tv})
                        for k2, v2 in tv.items():
                            slot[k2] += v2
                elif isinstance(val, (int, float)):
                    agg[key] = agg.get(key, 0) + val
        out.update(agg)
        out["tiers"] = tiers
        return out

    def warmup_enabled(self) -> bool:
        return dispatch.warmup_enabled(self.warmup)

    def _schedule_warmup(self, fc: FieldCorpus) -> None:
        """Pre-compile the bucket grid for a freshly-synced corpus on a
        background thread (warmup-at-open): the first real query of any
        interactive bucket then finds its executable cached instead of
        stalling the serving queue behind an XLA compile. Entries mirror
        `knn_search_auto`'s routing so the warmed program IS the one the
        serving path executes."""
        if fc.corpus is None or not self.warmup_enabled():
            return
        from elasticsearch_tpu.ops import pallas_knn_binned as binned
        corpus_spec = dispatch.specs_like(fc.corpus)
        n_pad = fc.corpus.matrix.shape[0]
        packed = str(fc.corpus.matrix.dtype) in ("uint8", "uint32")
        binned_ok = (fc.metric in (sim.COSINE, sim.DOT_PRODUCT,
                                   sim.MAX_INNER_PRODUCT)
                     and not packed
                     and n_pad % binned.BLOCK_N == 0
                     and not binned.default_interpret())
        entries = []
        for q in dispatch.WARMUP_QUERY_BUCKETS:
            qspec = dispatch.query_spec(q, fc.dims)
            for k in dispatch.WARMUP_K_BUCKETS:
                if packed and fc.rescore:
                    # two-phase fields dispatch the WIDENED coarse k —
                    # warm the programs serving traffic actually runs
                    k = quant_rescore.coarse_window(
                        min(k, n_pad), fc.rescore_oversample, limit=n_pad)
                k_b = dispatch.bucket_k(min(k, n_pad), limit=n_pad)
                if binned_ok and k_b <= 64:
                    if fc.corpus.residual is not None:
                        entries.append((
                            "knn.binned_rescored_packed",
                            (qspec, corpus_spec),
                            {"k": k_b, "metric": fc.metric,
                             "rescore_candidates": fc.rescore_candidates,
                             "interpret": False}))
                    else:
                        entries.append((
                            "knn.binned", (qspec, corpus_spec),
                            {"k": k_b, "metric": fc.metric,
                             "interpret": False}))
                else:
                    entries.append((
                        "knn.exact", (qspec, corpus_spec, None),
                        {"k": k_b, "metric": fc.metric,
                         "precision": "bf16", "block_size": None}))
        if fc.mesh_state is not None:
            # the sharded serving grid pre-compiles alongside the
            # single-device one, so the first mesh-routed query of any
            # interactive bucket finds its SPMD program ready
            entries.extend(fc.mesh_state.warmup_entries(fc.dims))
        if fc.router is not None:
            from elasticsearch_tpu.parallel import policy as mesh_policy
            from elasticsearch_tpu.parallel import sharded_ivf
            idx = fc.router.index
            mesh = (mesh_policy.serving_mesh()
                    if mesh_policy.eligible(
                        len(fc.row_map),
                        device_bytes=device_corpus_nbytes(
                            len(fc.row_map), fc.dims,
                            str(fc.corpus.matrix.dtype)))
                    else None)
            nprobe_known = (fc.router.nprobe_setting != "auto"
                            or fc.router._tuned_nprobe is not None)
            from elasticsearch_tpu.ops import pallas_ivf_fused as ivf_fused
            from elasticsearch_tpu.quant import codec as quant_codec
            if (idx.total > 0 and nprobe_known
                    and ivf_fused.fused_eligible(
                        quant_codec.MATRIX_DTYPES.get(idx.dtype,
                                                      "float32"),
                        fc.metric)
                    and ivf_fused.fused_preferred()):
                # pre-compile the fused gather+score grid the router
                # will dispatch (single-device probes) — shape-only,
                # so sync never pays the partition-layout upload here
                entries.extend(ivf_fused.warmup_entries_for_index(
                    idx, fc.router.effective_nprobe(10),
                    dispatch.WARMUP_K_BUCKETS,
                    dispatch.WARMUP_QUERY_BUCKETS, metric=fc.metric))
            if mesh is not None and idx.total > 0 and nprobe_known:
                # shape-only: the specs derive from the host layout, so
                # refresh never pays the sharded posting-list upload
                # here (IVFIndex.add invalidates the cached upload, so
                # an eager build would re-transfer the corpus every
                # refresh); an untuned "auto" nprobe is skipped — the
                # tuner runs real searches, far too heavy for warmup
                entries.extend(sharded_ivf.warmup_entries(
                    idx, mesh, fc.router.effective_nprobe(10)))
        dispatch.DISPATCH.warmup(entries, background=True)

    def field(self, name: str) -> Optional[FieldCorpus]:
        return self._fields.get(name)

    def pending_requests(self, field: str) -> int:
        """Queued-but-unexecuted searches across this field's batchers —
        the coalescing signal the mesh-vs-host cost router folds into its
        batch-size estimate."""
        with self._batchers_lock:
            return sum(b.pending() for key, b in self._batchers.items()
                       if key[0] == field)

    def _begin_dispatch(self) -> int:
        """Count this dispatch in flight; returns how many OTHERS were
        already in flight (the dp router's concurrency half of the load
        signal). Mirrored onto the telemetry registry so `_nodes/stats
        telemetry` shows the live in-flight gauge next to the latency
        histograms (resolved per call — a cached Gauge handle would
        detach from the registry across a test-time `reset()`)."""
        _telemetry_metrics.gauge("serving.inflight_dispatches").inc()
        with self._active_lock:
            n = self._active_dispatches
            self._active_dispatches += 1
            return n

    def _end_dispatch(self) -> None:
        _telemetry_metrics.gauge("serving.inflight_dispatches").dec()
        with self._active_lock:
            self._active_dispatches = max(0, self._active_dispatches - 1)

    def _queued_requests(self) -> int:
        """Requests waiting in this shard's batcher queues (the
        continuous-batching scheduler's live backlog,
        `CombiningBatcher.load()` — the other half of the dp router's
        load signal; in-flight batches are already counted by the
        `_active_dispatches` gauge)."""
        with self._batchers_lock:
            return sum(b.load()["pending"]
                       for b in self._batchers.values())

    def _retire_sched(self, batcher: CombiningBatcher) -> None:
        """Fold a dropped batcher's scheduler counters into the retired
        total (caller holds `_batchers_lock`)."""
        for key, val in batcher.sched.items():
            self._sched_retired[key] = self._sched_retired.get(key, 0) + val

    def scheduler_stats(self) -> Dict[str, int]:
        """Continuous-batching scheduler counters summed over this
        shard's kNN batchers (live + retired): batches, top-ups,
        schedule-time deadline sheds, dispatch/finalize overlap hits, and
        cumulative queue-wait / dispatch / finalize time — the closed-
        loop tail attribution the 1cl/4cl bench rows record."""
        out = dict(self._sched_retired)
        with self._batchers_lock:
            batchers = list(self._batchers.values())
        for b in batchers:
            for key, val in b.sched.items():
                out[key] = out.get(key, 0) + val
        return out

    def field_stats(self) -> Dict[str, dict]:
        """Per-field quantization-ladder stats for `_nodes/stats
        indices.knn.fields`: the serving encoding, device bytes/doc
        (packed row + aux + norms, `quant/codec.bytes_per_doc`), row
        count, and the two-phase rescore window."""
        from elasticsearch_tpu.quant import codec as quant_codec
        out: Dict[str, dict] = {}
        for field, fc in list(self._fields.items()):
            if fc.corpus is None:
                continue
            enc = quant_codec.encoding_of(fc.corpus.matrix.dtype)
            try:
                bpd = quant_codec.bytes_per_doc(enc, fc.dims)
            except (KeyError, ValueError):
                bpd = fc.dims * 4 + 4
            plan = self._field_plans.get(field, {})
            out[field] = {
                "encoding": enc,
                "target_encoding": plan.get("encoding", enc),
                "bytes_per_doc": bpd,
                "rows": len(fc.row_map),
                "device_bytes": device_corpus_nbytes(
                    len(fc.row_map), fc.dims, enc),
                "rescore": bool(fc.rescore),
                "rescore_oversample": (fc.rescore_oversample
                                       if fc.rescore else 0),
            }
        return out

    def search(self, field: str, query_vector: np.ndarray, k: int,
               filter_rows: Optional[np.ndarray] = None,
               precision: str = "bf16",
               num_candidates: Optional[int] = None,
               deadline_at: Optional[float] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Top-k search. Returns (global_rows [m], raw_scores [m]), m <= k
        (padding/filtered slots removed).

        filter_rows: sorted engine global rows allowed to match (pre-filter
        bitset from a boolean query; host → device additive mask).

        Concurrent callers coalesce through a per-(field, k) combining
        batcher into ONE dispatch, which a cost model routes to either the
        host VNNI mirror or the device matmul program (serving/batcher.py) —
        the round-3 path paid a full device round-trip per query.
        """
        fc = self._fields.get(field)
        if fc is None or fc.corpus is None or len(fc.row_map) == 0:
            return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.float32)

        key = (field, fc.version, k, precision, num_candidates)
        with self._batchers_lock:
            batcher = self._batchers.get(key)
            if batcher is None:
                def execute(reqs, fc=fc, k=k, precision=precision,
                            num_candidates=num_candidates, field=field):
                    return self._execute_batch(fc, k, precision, reqs,
                                               num_candidates=num_candidates,
                                               field=field)

                def dispatch_fn(reqs, fc=fc, k=k, precision=precision,
                                num_candidates=num_candidates, field=field):
                    return self._dispatch_many(
                        fc, k, precision, reqs,
                        num_candidates=num_candidates, field=field)

                # pipelined: the runner holds the batch lock only for the
                # un-synced device dispatch; the d2h sync + row-map join
                # of batch N overlap batch N+1's dispatch
                batcher = CombiningBatcher(
                    execute, dispatch_fn=dispatch_fn,
                    finalize_fn=self.finalize_many,
                    topup=self.topup,
                    target_batch_latency_ms=self.target_batch_latency_ms,
                    async_depth=self.async_depth)
                if len(self._batchers) > 64:  # stale (field, k) variants
                    for stale in self._batchers.values():
                        self._retire_sched(stale)
                    self._batchers.clear()
                self._batchers[key] = batcher
        # deadline_at: the propagated cross-node deadline (monotonic s) —
        # the EDF queue sheds this entry at schedule time if it expires
        # before a runner claims it (EsRejectedExecutionError to the
        # caller, counted in sched["deadline_sheds"])
        return batcher.submit(
            (np.asarray(query_vector, dtype=np.float32), filter_rows),
            deadline_at=deadline_at)

    def search_many(self, field: str, requests, k: int,
                    precision: str = "bf16",
                    num_candidates: Optional[int] = None) -> list:
        """Score a whole batch of (query_vector, filter_rows) requests in
        ONE dispatch — the hybrid plan's kNN leg. Where `search` relies on
        concurrent callers colliding in the combining batcher, this entry
        is for a caller that already holds a batch (the hybrid executor's
        runner thread) and wants exactly one device/host round-trip."""
        return self.finalize_many(
            self.search_many_async(field, requests, k, precision=precision,
                                   num_candidates=num_candidates))

    def search_many_async(self, field: str, requests, k: int,
                          precision: str = "bf16",
                          num_candidates: Optional[int] = None):
        """Launch a whole batch's kNN WITHOUT syncing: route + dispatch
        the device program and return an opaque handle whose un-synced
        arrays `finalize_many` lands later — the hybrid executor's
        pipelined score stage (host RRF/hydrate of batch N overlaps the
        device dispatch of batch N+1). Routes that are host-side or that
        sync internally (host mirror, IVF, mesh) complete here and the
        handle is already final; results are byte-identical either way."""
        fc = self._fields.get(field)
        if fc is None or fc.corpus is None or len(fc.row_map) == 0:
            return ("done", [(np.zeros(0, dtype=np.int64),
                              np.zeros(0, dtype=np.float32))
                             for _ in requests])
        reqs = [(np.asarray(q, dtype=np.float32), fr)
                for q, fr in requests]
        return self._dispatch_many(fc, k, precision, reqs,
                                   num_candidates=num_candidates,
                                   field=field)

    def finalize_many(self, handle) -> list:
        """Land the results of a `search_many_async` handle: one bulk
        device→host transfer of the score/id boards, then the validity
        mask + row-map join. The blocking sync lives HERE, at response-
        assembly time, never inside the dispatch critical section."""
        kind, payload, *rest = handle
        if kind == "done":
            return payload
        if kind == "sem":
            # semantic-cache wrapper: land the miss dispatch, feed the
            # fresh boards back into the ring, splice served + computed
            # results back into request order
            (sem, inner, served, miss_idx, miss_reqs, fc,
             k, precision, num_candidates) = payload
            miss_results = self.finalize_many(inner)
            self.knn_stats["semantic_inserts"] += sem.insert_many(
                miss_reqs, miss_results, fc, k, precision,
                num_candidates)
            out = [None] * (len(miss_idx) + len(served))
            for pos, i in enumerate(miss_idx):
                out[i] = miss_results[pos]
            for i, res in served.items():
                out[i] = res
            return out
        try:
            if kind == "mesh":
                return self._finalize_mesh(payload)
            fc, s, i, k_eff, n_valid, n_real, rescore_ctx = payload
            scores = np.asarray(s)[:, :k_eff]
            ids = np.asarray(i)[:, :k_eff]
            if rescore_ctx is not None:
                # phase two: exact f32 re-rank of the coarse window (the
                # blocking gather+score lives HERE, at response-assembly
                # time, with the device sync — never in dispatch)
                scores, ids = self._apply_rescore(rescore_ctx, scores,
                                                  ids, n_real)
            return self._land_results(fc, scores, ids, -1e37, n_valid,
                                      n_real)
        finally:
            # every pending handle was counted in flight at dispatch;
            # its slot releases the gauge exactly once
            for slot in rest:
                slot.release()

    def _execute_batch(self, fc: FieldCorpus, k: int, precision: str,
                       requests, num_candidates: Optional[int] = None,
                       field: Optional[str] = None) -> list:
        """Serve one coalesced batch of (query_vector, filter_rows)
        synchronously (dispatch + finalize back to back — the combining
        batcher's serial-retry path and the non-pipelined callers)."""
        return self.finalize_many(
            self._dispatch_many(fc, k, precision, requests,
                                num_candidates=num_candidates,
                                field=field))

    def _semantic_cache_for(self, field: Optional[str], fc: FieldCorpus):
        """The field's live SemanticCache, or None (feature off, no
        field identity, or no columnar source to gather exact windows
        through). A ring keyed to a superseded reader fingerprint is
        DROPPED here — refresh/delete/merge each mint a new fc.version,
        so stale entries can never serve rows from an old snapshot."""
        if not self.semantic_cache_enabled or field is None:
            return None
        if fc.source is None and fc.gens is None:
            # no exact row source to build guard windows through
            return None
        from elasticsearch_tpu.vectors import semantic_cache as _semc
        cur = self._sem_caches.get(field)
        if cur is not None and cur.version != fc.version:
            self.knn_stats["semantic_invalidations"] += 1
            cur = None
        if cur is None:
            cur = _semc.SemanticCache(
                self.semantic_cache_size, self.semantic_cache_threshold,
                fc.dims, fc.metric, fc.version)
            self._sem_caches[field] = cur
        return cur

    def _dispatch_many(self, fc: FieldCorpus, k: int, precision: str,
                       requests, num_candidates: Optional[int] = None,
                       field: Optional[str] = None):
        """Dispatch stage of one coalesced batch, fronted by the
        semantic cache when the index opted in: probe the device ring
        first, dispatch only the misses, and hand `finalize_many` a
        handle that splices served and computed boards back into
        request order (and feeds the misses back into the ring)."""
        sem = (self._semantic_cache_for(field, fc) if requests else None)
        served = {}
        if sem is not None:
            served, pstats = sem.probe(requests, k, precision,
                                       num_candidates)
            st = self.knn_stats
            st["semantic_probes"] += pstats["probed"]
            st["semantic_hits"] += pstats["hits"]
            st["semantic_rejects"] += pstats["rejects"]
            st["semantic_probe_nanos"] += pstats["nanos"]
            if len(served) == len(requests):
                # whole batch served from the ring: no device dispatch
                self.last_knn_phases = {
                    "engine": "semantic_cache", "queries": len(requests),
                    "k": int(k)}
                return ("done",
                        [served[i] for i in range(len(requests))])
        miss_idx = [i for i in range(len(requests)) if i not in served]
        miss_reqs = ([requests[i] for i in miss_idx] if served
                     else requests)
        inner = self._dispatch_many_inner(
            fc, k, precision, miss_reqs, num_candidates=num_candidates)
        if sem is None:
            return inner
        return ("sem", (sem, inner, served, miss_idx, miss_reqs, fc,
                        k, precision, num_candidates))

    def _dispatch_many_inner(self, fc: FieldCorpus, k: int,
                             precision: str, requests,
                             num_candidates: Optional[int] = None):
        """Route, build masks, and LAUNCH the device program. The
        exhaustive device paths (single-device AND mesh) return
        un-synced arrays in the handle; host/IVF routes complete here
        (they are host-side or sync internally). Tracks the in-flight
        gauge the dp router reads."""
        others = self._begin_dispatch()
        slot = _InflightSlot(self)
        try:
            handle = self._dispatch_many_routed(
                fc, k, precision, requests, others,
                num_candidates=num_candidates)
        except BaseException:
            slot.release()
            raise
        if handle[0] == "done":
            slot.release()
            return handle
        # pending handle: the slot rides along so finalize (or GC of an
        # abandoned handle) releases the gauge
        return handle + (slot,)

    def _dispatch_many_routed(self, fc: FieldCorpus, k: int,
                              precision: str, requests, others: int,
                              num_candidates: Optional[int] = None):
        import jax.numpy as jnp

        if fc.gens is not None:
            # generational field: serve from the CURRENT copy-on-write
            # snapshot (a background merge may have installed since this
            # view was built). One clean generation degenerates to the
            # monolithic path below on its base corpus — byte-identical
            # to the pre-generational store; anything else fans out.
            snap = fc.gens.snapshot()
            if not snap.simple:
                return self._dispatch_generational(
                    snap, fc, k, precision, requests, num_candidates)
            base = snap.generations[0]
            if base.corpus is not fc.corpus or fc.source is None:
                fc = FieldCorpus(base.corpus, base.row_map, fc.metric,
                                 fc.dims, fc.version, host=base.host,
                                 router=base.router,
                                 mesh_state=base.mesh_state,
                                 gens=fc.gens, encoding=fc.encoding,
                                 rescore=fc.rescore,
                                 rescore_oversample=fc.rescore_oversample,
                                 rescore_candidates=fc.rescore_candidates,
                                 source=base.source)

        n_valid = len(fc.row_map)
        queries = np.stack([q for q, _ in requests])
        any_filter = any(fr is not None for _, fr in requests)

        # two-phase plan: packed encodings (int4/binary) serve coarse
        # top-(k·oversample) on the packed matrix, then an exact f32
        # rescore of the window at response-assembly time. k widens
        # BEFORE the bucket ladder so the coarse phase stays in-grid.
        k_req = min(k, fc.corpus.matrix.shape[0])
        rescore_ctx = self._rescore_ctx(fc, queries, k_req)
        k_eff = (k_req if rescore_ctx is None
                 else quant_rescore.coarse_window(
                     k_req, fc.rescore_oversample,
                     limit=fc.corpus.matrix.shape[0]))

        self.knn_stats["searches"] += 1
        # cleared up front so a router-less dispatch can never leave a
        # previous query's phase timings behind for the profiler to read
        self.last_knn_phases = {}
        if fc.router is not None:
            reason = fc.router.should_fallback(k_eff, any_filter, precision)
            if reason is None:
                return ("done",
                        self._execute_ivf(fc, k_eff, n_valid, queries,
                                          len(requests), num_candidates,
                                          rescore_ctx=rescore_ctx))
            self.knn_stats["fallback_searches"] += 1
            self.last_knn_phases = {"engine": "tpu_exhaustive",
                                    "fallback_reason": reason}

        # mesh router: a corpus past the policy's row floor with a
        # sharded resident copy serves as ONE SPMD program (shard-local
        # matmul + ICI all-gather merge); everything else takes the
        # single-device / host paths below. With dp > 1 the policy also
        # picks the dp-vs-shard split from this batch's bucket and the
        # live load (queued requests + other in-flight dispatches) — a
        # loaded queue routes to one dp group so concurrent batches
        # overlap on disjoint device groups. k deeper than a shard slice
        # can't merge losslessly — those requests stay single-device.
        from elasticsearch_tpu.parallel import policy as mesh_policy
        mesh = mesh_policy.decide(
            "knn", n_valid, has_mesh_state=fc.mesh_state is not None,
            batch=dispatch.bucket_queries(len(requests)),
            queue_depth=others + self._queued_requests())
        if mesh is not None:
            if k_eff <= fc.mesh_state.layout.rows_per_shard:
                return self._execute_mesh(fc, k_eff, n_valid, queries,
                                          requests, any_filter,
                                          precision, mesh,
                                          rescore_ctx=rescore_ctx)
            mesh_policy.reclassify_single("knn_k_deeper_than_shard")

        use_host = (fc.host is not None and precision != "f32"
                    and rescore_ctx is None
                    and CostModel.prefer_host(len(requests), fc.host.n,
                                              fc.host.dims))
        if use_host:
            mask = None
            if any_filter:
                mask = np.ones((len(requests), n_valid), dtype=bool)
                for i, (_, fr) in enumerate(requests):
                    if fr is not None:
                        mask[i] = np.isin(fc.row_map, fr)
            scores, ids = fc.host.search(queries, k_eff, mask=mask)
            return ("done",
                    self._land_results(fc, np.asarray(scores),
                                       np.asarray(ids), -np.inf, n_valid,
                                       len(requests)))

        queries = _pad_batch(queries, len(requests))
        b_pad = len(queries)
        mask = None
        if any_filter:
            n_pad = fc.corpus.matrix.shape[0]
            m = np.zeros((b_pad, n_pad), dtype=bool)
            for i, (_, fr) in enumerate(requests):
                if fr is None:
                    m[i, :n_valid] = True
                else:
                    m[i, :n_valid] = np.isin(fc.row_map, fr)
            mask = jnp.asarray(m)
        # k rounds up the dispatch bucket ladder so a workload that
        # sweeps k (10, 12, 13, ...) reuses one compiled program per
        # rung; the extra columns slice away at finalize (top-k prefixes
        # are exact)
        k_b = dispatch.bucket_k(k_eff,
                                limit=fc.corpus.matrix.shape[0])
        s, i = knn_ops.knn_search_auto(
            jnp.asarray(queries), fc.corpus, k=k_b, metric=fc.metric,
            filter_mask=mask, precision=precision,
            rescore_candidates=fc.rescore_candidates)
        # un-synced: s/i are device futures until finalize_many reads
        # them — count the deferred sync so `_nodes/stats
        # indices.dispatch` shows how much serving load pipelines
        dispatch.DISPATCH.note_async()
        return ("pending", (fc, s, i, k_eff, n_valid, len(requests),
                            rescore_ctx))

    def _dispatch_generational(self, snap, fc: FieldCorpus, k: int,
                               precision: str, requests,
                               num_candidates: Optional[int]):
        """Fan one dispatch per live generation and fuse through
        `merge_top_k` (`segments/generational.py`) — the serving shape
        between merges: L0 seals and tombstoned generations search as a
        stable-ordered board merge, byte-identical to the monolithic
        corpus. Returns a pending handle whose flat-space boards land in
        `finalize_many` (the snapshot rides in the handle, so a merge
        installing mid-flight cannot swap the row map under us)."""
        n_valid = len(snap.row_map)
        queries_real = np.stack([q for q, _ in requests])
        k_req = min(k, snap.total_pad)
        # two-phase when the SNAPSHOT actually serves packed generations
        # (mid-re-encode a still-int8 base stays single-phase and
        # byte-stable; the first packed generation turns the exact
        # rescore on, which also makes the mixed-encoding board merge
        # exact again)
        rescore_ctx = None
        k_eff = k_req
        if fc.rescore and any(
                g.corpus is not None
                and str(g.corpus.matrix.dtype) in ("uint8", "uint32")
                for g in snap.generations):
            rescore_ctx = {"queries": queries_real, "k": k_req,
                           "metric": fc.metric,
                           "gather": snap.gather_rows}
            k_eff = quant_rescore.coarse_window(
                k_req, fc.rescore_oversample, limit=snap.total_pad)
        queries = _pad_batch(queries_real, len(requests))
        self.knn_stats["searches"] += 1
        self.last_knn_phases = {}
        s, i, phases = snap.search_async(
            queries, len(requests), k_eff, [fr for _, fr in requests],
            fc.metric, precision, num_candidates=num_candidates,
            knn_stats=self.knn_stats)
        self.last_knn_phases = phases
        # un-synced boards: the device sync happens at response-assembly
        # time in finalize_many, like the monolithic pipelined path
        dispatch.DISPATCH.note_async()
        return ("pending", (snap, s, i, k_eff, n_valid, len(requests),
                            rescore_ctx))

    @staticmethod
    def _rescore_ctx(fc: FieldCorpus, queries: np.ndarray,
                     k_final: int) -> Optional[dict]:
        """Two-phase rescore context for one coalesced batch, or None
        when this dispatch serves single-phase. Active exactly when the
        SERVING corpus is a packed encoding with rescore on — a field
        mid-re-encode (int8 base still serving after an int8→int4
        mapping change) stays single-phase and byte-stable until the
        merge thread installs the packed generations."""
        if not fc.rescore or fc.source is None:
            return None
        if str(fc.corpus.matrix.dtype) not in ("uint8", "uint32"):
            return None
        return {"queries": queries, "k": k_final, "metric": fc.metric,
                "gather": fc.source.gather}

    def _apply_rescore(self, ctx: dict, scores: np.ndarray,
                       ids: np.ndarray, n_real: int):
        """Run the exact-rescore phase over coarse boards (flat/device
        row ids) and fold the window stats into knn_stats /
        profile.knn."""
        import time as _time

        t0 = _time.perf_counter_ns()
        out_s, out_i, stats = quant_rescore.rescore_boards(
            ctx["queries"][:n_real], scores[:n_real], ids[:n_real],
            ctx["k"], ctx["gather"], ctx["metric"])
        nanos = _time.perf_counter_ns() - t0
        self.knn_stats["rescore_searches"] += 1
        self.knn_stats["rescore_window_rows"] += stats["window"] * n_real
        self.knn_stats["rescore_promoted"] += stats["promoted"]
        self.knn_stats["rescore_nanos"] += nanos
        phases = dict(self.last_knn_phases or {})
        phases["rescore"] = {"window": stats["window"],
                             "promoted": stats["promoted"],
                             "rescore_nanos": nanos}
        self.last_knn_phases = phases
        return out_s, out_i

    @staticmethod
    def _land_results(fc, scores: np.ndarray, ids: np.ndarray,
                      floor: float, n_valid: int, n_real: int) -> list:
        out = []
        for qi in range(n_real):
            sc, rid = scores[qi], ids[qi]
            valid = (sc > floor) & (rid >= 0) & (rid < n_valid)
            sc, rid = sc[valid], rid[valid]
            out.append((fc.row_map[rid], sc.astype(np.float32)))
        return out

    def _execute_mesh(self, fc: FieldCorpus, k_eff: int, n_valid: int,
                      queries: np.ndarray, requests, any_filter: bool,
                      precision: str, mesh, rescore_ctx=None):
        """Launch one coalesced exact-kNN batch as ONE SPMD program over
        the mesh-resident sharded corpus (`parallel/sharded_knn.py`):
        shard-local matmul + top-k, all-gather candidate merge, k-ladder
        slice-back at finalize. `mesh` is whatever the dp-vs-shard
        router picked — the full serving mesh or one dp-group submesh
        (the corpus view for a group is a free re-layout of the
        dp-replicated arrays). Returns an UN-SYNCED handle: the device
        sync lands in `_finalize_mesh` at response-assembly time, so
        batch N's merge overlaps batch N+1's dispatch — with dp > 1 the
        overlapping dispatch runs on a DIFFERENT device group, which is
        the replicated mesh's whole throughput story. Result-identical
        to the single-device path (the tier-1 mesh suite pins byte
        parity)."""
        import time as _time

        import jax
        import jax.numpy as jnp

        from elasticsearch_tpu.parallel.sharded_knn import (
            distributed_knn_search)

        from elasticsearch_tpu.parallel import mesh as mesh_lib

        ms = fc.mesh_state
        if (mesh is not ms.mesh
                and mesh_lib.shard_size(mesh) != ms.layout.n_shards):
            # the policy was reconfigured under this state (its layout
            # is baked for its own shard count): serve on the state's
            # mesh until the next sync rebuilds against the new policy
            mesh = ms.mesh
        queries = _pad_batch(queries, len(requests))
        b_pad = len(queries)
        per = ms.layout.rows_per_shard
        k_b = dispatch.bucket_k(k_eff, limit=per)
        t0 = _time.perf_counter_ns()
        mask = None
        if any_filter:
            m = np.zeros((b_pad, len(ms.slot_map)), dtype=bool)
            valid_slots = ms.slot_map >= 0  # == filter_mask(all-ones)
            for i, (_, fr) in enumerate(requests):
                if fr is None:
                    m[i] = valid_slots
                else:
                    m[i] = ms.filter_mask(np.isin(fc.row_map, fr))
            mask = jax.device_put(jnp.asarray(m),
                                  ms.mask_sharding(2, mesh))
        q = jax.device_put(jnp.asarray(queries), ms.query_sharding(mesh))
        scores, gids = distributed_knn_search(
            q, ms.corpus_for(mesh), k_b, mesh, metric=fc.metric,
            filter_mask=mask, precision=precision)
        # un-synced boards: the device sync is deferred to finalize
        dispatch.DISPATCH.note_async()
        return ("mesh", (fc, ms, mesh, scores, gids, k_eff, k_b, b_pad,
                         n_valid, len(requests), t0, rescore_ctx))

    def _finalize_mesh(self, payload) -> list:
        """Land one mesh dispatch: device sync, k slice-back, slot-map
        join, and the router/leg accounting."""
        import time as _time

        from elasticsearch_tpu.parallel import mesh as mesh_lib
        from elasticsearch_tpu.parallel import policy as mesh_policy

        (fc, ms, mesh, scores, gids, k_eff, k_b, b_pad, n_valid, n_real,
         t0, rescore_ctx) = payload
        gids.block_until_ready()
        t1 = _time.perf_counter_ns()
        scores = np.asarray(scores)[:, :k_eff]
        gids = np.asarray(gids)[:, :k_eff]
        flat = ms.map_ids(gids)
        rescore_info = None
        if rescore_ctx is not None:
            # exact phase over flat corpus rows (the slot-map join
            # already happened, so the window gathers through the same
            # RowSource as the single-device path)
            scores, flat = self._apply_rescore(rescore_ctx, scores,
                                               flat, n_real)
            rescore_info = (self.last_knn_phases or {}).get("rescore")
        out = []
        for qi in range(n_real):
            sc, rid = scores[qi], flat[qi]
            valid = (sc > -1e37) & (rid >= 0) & (rid < n_valid)
            sc, rid = sc[valid], rid[valid]
            out.append((fc.row_map[rid], sc.astype(np.float32)))
        t2 = _time.perf_counter_ns()
        n_shards = mesh_lib.shard_size(mesh)
        gather = mesh_policy.gather_bytes(n_shards, b_pad, k_b)
        mesh_policy.record_leg("knn", t1 - t0, t2 - t1, gather)
        self.knn_stats["mesh_searches"] += 1
        self.knn_stats["score_nanos"] += t1 - t0
        self.knn_stats["merge_nanos"] += t2 - t1
        self.last_knn_phases = {
            "engine": "tpu_mesh", "mesh_shards": n_shards,
            "mesh_dp": mesh_lib.dp_size(ms.mesh),
            "dp_group": mesh is not ms.mesh,
            "rows_per_shard": ms.layout.rows_per_shard,
            "collective_bytes": gather,
            "route_nanos": 0, "score_nanos": t1 - t0,
            "merge_nanos": t2 - t1}
        if rescore_ctx is not None and rescore_info is not None:
            self.last_knn_phases["rescore"] = rescore_info
        return out

    def _execute_ivf(self, fc: FieldCorpus, k_eff: int, n_valid: int,
                     queries: np.ndarray, n_real: int,
                     num_candidates: Optional[int],
                     rescore_ctx: Optional[dict] = None) -> list:
        """Serve one coalesced batch through the tpu_ivf router (the
        mesh policy decides single-device vs SPMD execution; packed
        encodings rescore the coarse window exactly before landing)."""
        import time as _time

        from elasticsearch_tpu.parallel import policy as mesh_policy

        queries = _pad_batch(queries, n_real)
        k_b = dispatch.bucket_k(k_eff, limit=len(fc.row_map))
        mesh = mesh_policy.decide("ivf", len(fc.row_map),
                                  batch=len(queries),
                                  queue_depth=self._queued_requests())
        scores, rows, phases = fc.router.search(
            queries, k_b, num_candidates=num_candidates, mesh=mesh)
        scores, rows = scores[:, :k_eff], rows[:, :k_eff]
        phases = dict(phases)
        if rescore_ctx is not None:
            scores, rows = self._apply_rescore(rescore_ctx, scores, rows,
                                               n_real)
            phases["rescore"] = (self.last_knn_phases
                                 or {}).get("rescore")
        t0 = _time.perf_counter_ns()
        out = []
        for qi in range(n_real):
            sc, rid = scores[qi], rows[qi]
            valid = (sc > -1e37) & (rid >= 0) & (rid < n_valid)
            sc, rid = sc[valid], rid[valid]
            out.append((fc.row_map[rid], sc.astype(np.float32)))
        phases["merge_nanos"] += _time.perf_counter_ns() - t0
        self.knn_stats["ivf_searches"] += 1
        if phases.get("engine") == "tpu_ivf_mesh":
            self.knn_stats["mesh_searches"] += 1
        if phases.get("fused_probe"):
            self.knn_stats["fused_probe_searches"] += 1
        for ph in ("route_nanos", "score_nanos", "merge_nanos"):
            self.knn_stats[ph] += phases[ph]
        self.last_knn_phases = phases
        return out
