"""Plugin CLI (reference: `distribution/tools/plugin-cli` —
install/list/remove subcommands).

Usage:
    python -m elasticsearch_tpu.plugin_cli install SRC --data DATA
    python -m elasticsearch_tpu.plugin_cli list --data DATA
    python -m elasticsearch_tpu.plugin_cli remove NAME --data DATA

SRC is a plugin directory (containing plugin.py) or a .zip of one.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import zipfile


def _plugins_dir(data: str) -> str:
    return os.path.join(data, "plugins")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="plugin_cli")
    parser.add_argument("command", choices=["install", "list", "remove"])
    parser.add_argument("target", nargs="?")
    parser.add_argument("--data", default="./data",
                        help="node data path (plugins live in "
                             "<data>/plugins)")
    args = parser.parse_args(argv)
    pdir = _plugins_dir(args.data)

    if args.command == "list":
        if not os.path.isdir(pdir):
            return 0
        for entry in sorted(os.listdir(pdir)):
            meta_path = os.path.join(pdir, entry, "plugin.json")
            version = ""
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    version = json.load(f).get("version", "")
            print(f"{entry} {version}".strip())
        return 0

    if not args.target:
        print("plugin name/path required", file=sys.stderr)
        return 1

    if args.command == "install":
        src = args.target
        if src.endswith(".zip"):
            name = os.path.basename(src)[:-4]
            dest = os.path.join(pdir, name)
            if os.path.exists(dest):
                print(f"plugin [{name}] already installed", file=sys.stderr)
                return 1
            os.makedirs(dest, exist_ok=True)
            with zipfile.ZipFile(src) as zf:
                root = os.path.normpath(dest)
                for member in zf.namelist():
                    # zip-slip guard: trailing separator so a sibling dir
                    # sharing the prefix ("foo-evil") can't pass
                    target = os.path.normpath(os.path.join(root, member))
                    if target != root and not target.startswith(root + os.sep):
                        print(f"refusing path [{member}]", file=sys.stderr)
                        shutil.rmtree(dest, ignore_errors=True)
                        return 1
                zf.extractall(dest)
        else:
            if not os.path.exists(os.path.join(src, "plugin.py")):
                print(f"[{src}] is not a plugin directory (no plugin.py)",
                      file=sys.stderr)
                return 1
            name = os.path.basename(os.path.normpath(src))
            dest = os.path.join(pdir, name)
            if os.path.exists(dest):
                print(f"plugin [{name}] already installed", file=sys.stderr)
                return 1
            shutil.copytree(src, dest)
        print(f"installed [{name}]")
        return 0

    if args.command == "remove":
        dest = os.path.join(pdir, args.target)
        if not os.path.isdir(dest):
            print(f"plugin [{args.target}] not found", file=sys.stderr)
            return 1
        shutil.rmtree(dest)
        print(f"removed [{args.target}]")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
