"""Plugin system: discovery, loading, and the SPI extension points.

Re-design of the reference's plugin architecture (`server/src/main/java/org/
elasticsearch/plugins/` — `PluginsService.java`, `Plugin` + the per-layer
interfaces `SearchPlugin`/`MapperPlugin`/`AnalysisPlugin`/`IngestPlugin`/
`ActionPlugin`/`ScriptPlugin`, SURVEY.md §2.1 "Plugin system" and the
`plugins/examples/` SPI documentation).

A plugin is a directory containing `plugin.py` (defining one `Plugin`
subclass) plus `plugin-descriptor.properties`-style metadata in
`plugin.json`. Loading uses importlib with a unique module name per plugin
(the Python analog of the reference's per-plugin classloader isolation —
two plugins can both ship a `util` module without clashing).

Extension points mirror the reference interfaces:
- get_analyzers()      -> AnalysisPlugin#getAnalyzers
- get_field_mappers()  -> MapperPlugin#getMappers
- get_queries()        -> SearchPlugin#getQueries
- get_processors()     -> IngestPlugin#getProcessors
- get_rest_handlers()  -> ActionPlugin#getRestHandlers
- get_settings()       -> Plugin#getSettings
- on_node_start()      -> lifecycle component hook
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys
from typing import Callable, Dict, List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentError

# SearchPlugin-contributed query parsers, consulted by parse_extended after
# the built-in table misses (reference: SearchModule collects
# SearchPlugin.getQueries into the named-parser registry)
EXTRA_QUERY_PARSERS: Dict[str, Callable] = {}

_ABSENT = object()  # sentinel: key did not exist before a plugin installed it


class Plugin:
    """Base class for plugins (reference: plugins/Plugin.java)."""

    name = "unnamed"
    description = ""
    version = "0.0.0"

    def get_settings(self) -> dict:
        """Default settings this plugin contributes."""
        return {}

    def get_analyzers(self) -> list:
        """[Analyzer] to register globally."""
        return []

    def get_field_mappers(self) -> list:
        """[FieldMapper subclass] — each registered by its type_name."""
        return []

    def get_queries(self) -> Dict[str, Callable]:
        """{query_name: parser(spec) -> Query}."""
        return {}

    def get_processors(self) -> list:
        """[Processor subclass] — each registered by its kind."""
        return []

    def get_rest_handlers(self, rest_controller, node) -> None:
        """Register REST routes (called during node wiring)."""

    def on_node_start(self, node) -> None:
        """Lifecycle hook after the node's services exist."""


class PluginInfo:
    def __init__(self, name: str, description: str, version: str, path: str):
        self.name = name
        self.description = description
        self.version = version
        self.path = path

    def to_dict(self) -> dict:
        return {"name": self.name, "description": self.description,
                "version": self.version}


class PluginsService:
    """Discovers and applies plugins (reference: PluginsService.java)."""

    def __init__(self, plugin_dir: Optional[str] = None):
        self.plugin_dir = plugin_dir
        self.plugins: List[Plugin] = []
        self.infos: List[PluginInfo] = []
        self._applied = False
        self._node_started = False
        self._installed: list = []

    # ------------------------------------------------------------ discovery
    def load_all(self) -> None:
        if not self.plugin_dir or not os.path.isdir(self.plugin_dir):
            return
        for entry in sorted(os.listdir(self.plugin_dir)):
            path = os.path.join(self.plugin_dir, entry)
            if os.path.isdir(path) and os.path.exists(
                    os.path.join(path, "plugin.py")):
                self.load_plugin(path)

    def load_plugin(self, path: str) -> Plugin:
        """Load one plugin directory under an isolated module name."""
        meta = {}
        meta_path = os.path.join(path, "plugin.json")
        if os.path.exists(meta_path):
            with open(meta_path, "r", encoding="utf-8") as f:
                meta = json.load(f)
        # unique module name = classloader isolation analog
        mod_name = f"tpu_search_plugin_{os.path.basename(path)}_{len(self.plugins)}"
        spec = importlib.util.spec_from_file_location(
            mod_name, os.path.join(path, "plugin.py"))
        module = importlib.util.module_from_spec(spec)
        sys.modules[mod_name] = module
        try:
            spec.loader.exec_module(module)
        except Exception as e:
            del sys.modules[mod_name]
            raise IllegalArgumentError(
                f"failed to load plugin [{path}]: {e}") from e
        # the plugin's OWN class: defined in this module (imported Plugin
        # subclasses, e.g. a shared base, must not be instantiated), most
        # derived wins if several are defined
        candidates = [obj for obj in vars(module).values()
                      if isinstance(obj, type) and issubclass(obj, Plugin)
                      and obj is not Plugin
                      and obj.__module__ == mod_name]
        plugin_cls = None
        for cls in candidates:
            if not any(cls is not other and issubclass(other, cls)
                       for other in candidates):
                plugin_cls = cls
                break
        if plugin_cls is None:
            del sys.modules[mod_name]
            raise IllegalArgumentError(
                f"plugin [{path}] defines no Plugin subclass")
        plugin = plugin_cls()
        plugin.name = meta.get("name", plugin.name if plugin.name != "unnamed"
                               else os.path.basename(path))
        plugin.description = meta.get("description", plugin.description)
        plugin.version = meta.get("version", plugin.version)
        self.plugins.append(plugin)
        self.infos.append(PluginInfo(plugin.name, plugin.description,
                                     plugin.version, path))
        return plugin

    def register(self, plugin: Plugin) -> None:
        """Programmatic registration (tests, embedded use)."""
        self.plugins.append(plugin)
        self.infos.append(PluginInfo(plugin.name, plugin.description,
                                     plugin.version, "<embedded>"))

    # ------------------------------------------------------------- applying
    def apply_extensions(self) -> None:
        """Install every plugin's contributions into the shared registries,
        remembering what was installed so remove_extensions() can undo it
        when the owning node closes."""
        if self._applied:
            return
        self._applied = True
        from elasticsearch_tpu.index import analysis as _analysis
        from elasticsearch_tpu.index.mapping import FIELD_TYPES
        from elasticsearch_tpu.ingest.service import PROCESSORS

        # (registry, key, previous value or _ABSENT) per installed entry so
        # removal restores what a contribution shadowed — popping outright
        # would destroy shadowed built-ins and other nodes' registrations
        self._installed = []

        def install(registry: dict, key: str, value) -> None:
            self._installed.append(
                (registry, key, registry.get(key, _ABSENT)))
            registry[key] = value

        for plugin in self.plugins:
            for analyzer in plugin.get_analyzers():
                install(_analysis.DEFAULT_REGISTRY._analyzers,
                        analyzer.name, analyzer)
            for mapper_cls in plugin.get_field_mappers():
                install(FIELD_TYPES, mapper_cls.type_name, mapper_cls)
            for name, parser in plugin.get_queries().items():
                install(EXTRA_QUERY_PARSERS, name, parser)
            for proc_cls in plugin.get_processors():
                install(PROCESSORS, proc_cls.kind, proc_cls)

    def remove_extensions(self) -> None:
        """Uninstall this node's plugin contributions, restoring whatever
        each one shadowed (a closed node's query kinds must stop parsing,
        but built-ins it overrode must come back)."""
        if not self._applied:
            return
        self._applied = False
        for registry, key, previous in reversed(self._installed):
            if previous is _ABSENT:
                registry.pop(key, None)
            else:
                registry[key] = previous
        self._installed = []

    def start_node(self, node) -> None:
        """Fire on_node_start once per node, REST or not."""
        if getattr(self, "_node_started", False):
            return
        self._node_started = True
        for plugin in self.plugins:
            plugin.on_node_start(node)

    def register_rest(self, rest_controller, node) -> None:
        """Register plugin REST routes on a controller (idempotent per
        controller since each register_all builds a fresh table)."""
        for plugin in self.plugins:
            plugin.get_rest_handlers(rest_controller, node)

    def info(self) -> List[dict]:
        return [i.to_dict() for i in self.infos]
