"""SQL engine: parse SQL, plan onto query DSL + aggregations, execute.

Reference: `x-pack/plugin/sql` (69k LoC — ANTLR parser, logical/physical
planner, query folding into search requests). This implementation keeps the
same lowering strategy the reference uses:

- filter-only queries fold into a `_search` body (WHERE → bool query,
  ORDER BY → sort, LIMIT → size, SELECT list → _source filtering)
- GROUP BY folds into a `composite` aggregation with metric sub-aggs
  (the reference folds into composite too — `QueryFolder`/`Aggs.java`)
- HAVING is applied to reduced buckets (reference: bucket_selector pipeline)
- `_sql/translate` exposes the folded search body verbatim

Cursors paginate filter queries by from-offset, base64-encoded like the
reference's opaque cursor strings.
"""

from __future__ import annotations

import base64
import json
import re
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import IllegalArgumentError, ParsingError

# ---------------------------------------------------------------------------
# lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<number>\d+\.\d+|\d+)
    | (?P<string>'(?:[^']|'')*')
    | (?P<qident>"[^"]+")
    | (?P<ident>[A-Za-z_][A-Za-z0-9_.*-]*)
    | (?P<op><>|!=|<=|>=|=|<|>|\(|\)|,|\*|\.)
    )""", re.VERBOSE)

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "and", "or", "not", "like", "in", "between", "is", "null", "as", "asc",
    "desc", "distinct", "match", "count", "sum", "avg", "min", "max",
}


class _Tok:
    def __init__(self, kind: str, value: Any):
        self.kind = kind       # number | string | ident | kw | op | eof
        self.value = value

    def __repr__(self):
        return f"{self.kind}:{self.value}"


def _lex(sql: str) -> List[_Tok]:
    out: List[_Tok] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if m is None or m.end() == pos:
            if sql[pos:].strip():
                raise ParsingError(f"SQL lexing error at: {sql[pos:pos+20]!r}")
            break
        pos = m.end()
        if m.group("number") is not None:
            text = m.group("number")
            out.append(_Tok("number", float(text) if "." in text else int(text)))
        elif m.group("string") is not None:
            out.append(_Tok("string", m.group("string")[1:-1].replace("''", "'")))
        elif m.group("qident") is not None:
            out.append(_Tok("ident", m.group("qident")[1:-1]))
        elif m.group("ident") is not None:
            word = m.group("ident")
            if word.lower() in _KEYWORDS:
                out.append(_Tok("kw", word.lower()))
            else:
                out.append(_Tok("ident", word))
        else:
            out.append(_Tok("op", m.group("op")))
    out.append(_Tok("eof", None))
    return out


# ---------------------------------------------------------------------------
# AST + parser (recursive descent)
# ---------------------------------------------------------------------------

AGG_FUNCS = {"count", "sum", "avg", "min", "max"}


class SelectItem:
    def __init__(self, expr: Any, alias: Optional[str]):
        self.expr = expr        # ("col", name) | ("func", fname, arg) | ("lit", v)
        self.alias = alias

    @property
    def name(self) -> str:
        if self.alias:
            return self.alias
        e = self.expr
        if e[0] == "col":
            return e[1]
        if e[0] == "func":
            arg = "*" if e[2] is None else e[2]
            return f"{e[1].upper()}({arg})"
        return str(e[1])

    @property
    def is_agg(self) -> bool:
        return self.expr[0] == "func" and self.expr[1] in AGG_FUNCS


class SqlQuery:
    def __init__(self):
        self.select: List[SelectItem] = []
        self.star = False
        self.table: str = ""
        self.where: Optional[Any] = None
        self.group_by: List[str] = []
        self.having: Optional[Any] = None
        self.order_by: List[Tuple[Any, str]] = []   # (expr, asc|desc)
        self.limit: Optional[int] = None


class _Parser:
    def __init__(self, toks: List[_Tok]):
        self.toks = toks
        self.i = 0

    def peek(self) -> _Tok:
        return self.toks[self.i]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws: str) -> Optional[str]:
        t = self.peek()
        if t.kind == "kw" and t.value in kws:
            self.next()
            return t.value
        return None

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise ParsingError(f"expected {kw.upper()}, got [{self.peek().value}]")

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "op" and t.value == op:
            self.next()
            return True
        return False

    # -- grammar -------------------------------------------------------------
    def parse(self) -> SqlQuery:
        q = SqlQuery()
        self.expect_kw("select")
        distinct = bool(self.accept_kw("distinct"))  # DISTINCT cols == GROUP BY
        if self.accept_op("*"):
            q.star = True
        else:
            q.select.append(self._select_item())
            while self.accept_op(","):
                q.select.append(self._select_item())
        self.expect_kw("from")
        t = self.next()
        if t.kind not in ("ident", "string"):
            raise ParsingError(f"expected table name, got [{t.value}]")
        q.table = t.value
        if self.accept_kw("where"):
            q.where = self._expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            q.group_by.append(self._column_name())
            while self.accept_op(","):
                q.group_by.append(self._column_name())
        elif distinct and q.select and all(it.expr[0] == "col" for it in q.select):
            q.group_by = [it.expr[1] for it in q.select]
        if self.accept_kw("having"):
            q.having = self._expr()
        if self.accept_kw("order"):
            self.expect_kw("by")
            q.order_by.append(self._order_item())
            while self.accept_op(","):
                q.order_by.append(self._order_item())
        if self.accept_kw("limit"):
            t = self.next()
            if t.kind != "number":
                raise ParsingError("LIMIT expects a number")
            q.limit = int(t.value)
        if self.peek().kind != "eof":
            raise ParsingError(f"unexpected trailing input [{self.peek().value}]")
        return q

    def _column_name(self) -> str:
        t = self.next()
        if t.kind != "ident":
            raise ParsingError(f"expected column name, got [{t.value}]")
        return t.value

    def _order_item(self) -> Tuple[Any, str]:
        expr = self._operand()
        direction = self.accept_kw("asc", "desc") or "asc"
        return expr, direction

    def _select_item(self) -> SelectItem:
        expr = self._operand()
        alias = None
        if self.accept_kw("as"):
            alias = self._column_name()
        elif self.peek().kind == "ident":
            alias = self.next().value
        return SelectItem(expr, alias)

    def _operand(self) -> Any:
        t = self.peek()
        if t.kind == "kw" and t.value in AGG_FUNCS:
            fname = self.next().value
            if not self.accept_op("("):
                raise ParsingError(f"{fname.upper()} requires (...)")
            if self.accept_op("*"):
                arg = None
            else:
                self.accept_kw("distinct")
                arg = self._column_name()
            if not self.accept_op(")"):
                raise ParsingError("expected )")
            return ("func", fname, arg)
        if t.kind == "kw" and t.value == "match":
            self.next()
            if not self.accept_op("("):
                raise ParsingError("MATCH requires (field, 'text')")
            field = self._column_name()
            if not self.accept_op(","):
                raise ParsingError("MATCH requires (field, 'text')")
            text = self.next()
            if not self.accept_op(")"):
                raise ParsingError("expected )")
            return ("match", field, text.value)
        if t.kind == "ident":
            return ("col", self.next().value)
        if t.kind in ("number", "string"):
            return ("lit", self.next().value)
        if t.kind == "kw" and t.value == "null":
            self.next()
            return ("lit", None)
        raise ParsingError(f"unexpected token [{t.value}]")

    def _expr(self) -> Any:
        left = self._and_expr()
        while self.accept_kw("or"):
            left = ("or", left, self._and_expr())
        return left

    def _and_expr(self) -> Any:
        left = self._not_expr()
        while self.accept_kw("and"):
            left = ("and", left, self._not_expr())
        return left

    def _not_expr(self) -> Any:
        if self.accept_kw("not"):
            return ("not", self._not_expr())
        if self.accept_op("("):
            e = self._expr()
            if not self.accept_op(")"):
                raise ParsingError("expected )")
            return e
        return self._predicate()

    def _predicate(self) -> Any:
        left = self._operand()
        if left[0] == "match":
            return left
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "!=", "<>", "<", "<=", ">", ">="):
            op = self.next().value
            right = self._operand()
            return ("cmp", op, left, right)
        if self.accept_kw("like"):
            pat = self.next()
            if pat.kind != "string":
                raise ParsingError("LIKE expects a string pattern")
            return ("like", left, pat.value)
        if self.accept_kw("is"):
            negate = bool(self.accept_kw("not"))
            self.expect_kw("null")
            return ("isnull", left, negate)
        if self.accept_kw("in"):
            if not self.accept_op("("):
                raise ParsingError("IN expects (...)")
            vals = [self._operand()]
            while self.accept_op(","):
                vals.append(self._operand())
            if not self.accept_op(")"):
                raise ParsingError("expected )")
            return ("in", left, [v[1] for v in vals])
        if self.accept_kw("between"):
            lo = self._operand()
            self.expect_kw("and")
            hi = self._operand()
            return ("between", left, lo[1], hi[1])
        raise ParsingError(f"incomplete predicate near [{t.value}]")


def parse_sql(sql: str) -> SqlQuery:
    q = _Parser(_lex(sql)).parse()
    q._original = sql   # retained for cursor state round-trips
    return q


# ---------------------------------------------------------------------------
# planner: WHERE expr → query DSL
# ---------------------------------------------------------------------------

def _col_of(e) -> str:
    if e[0] != "col":
        raise IllegalArgumentError("expected a column on the left of a predicate")
    return e[1]


def _lit_of(e) -> Any:
    if e[0] != "lit":
        raise IllegalArgumentError("expected a literal on the right of a predicate")
    return e[1]


def _ident_resolver(field: str) -> str:
    return field


def where_to_dsl(expr, exact=_ident_resolver) -> dict:
    """`exact` maps a column to its exact-match field (the `.keyword`
    subfield for analyzed text — reference: SQL's FieldAttribute.exactAttribute)."""
    kind = expr[0]
    if kind == "and":
        return {"bool": {"must": [where_to_dsl(expr[1], exact),
                                  where_to_dsl(expr[2], exact)]}}
    if kind == "or":
        return {"bool": {"should": [where_to_dsl(expr[1], exact),
                                    where_to_dsl(expr[2], exact)],
                         "minimum_should_match": 1}}
    if kind == "not":
        return {"bool": {"must_not": [where_to_dsl(expr[1], exact)]}}
    if kind == "cmp":
        op, left, right = expr[1], expr[2], expr[3]
        col, lit = _col_of(left), _lit_of(right)
        if op == "=":
            return {"term": {exact(col): {"value": lit}}}
        if op in ("!=", "<>"):
            return {"bool": {"must_not": [{"term": {exact(col): {"value": lit}}}]}}
        range_op = {"<": "lt", "<=": "lte", ">": "gt", ">=": "gte"}[op]
        return {"range": {col: {range_op: lit}}}
    if kind == "like":
        pattern = expr[2].replace("%", "*").replace("_", "?")
        return {"wildcard": {exact(_col_of(expr[1])): {"value": pattern}}}
    if kind == "isnull":
        exists = {"exists": {"field": _col_of(expr[1])}}
        if expr[2]:   # IS NOT NULL
            return exists
        return {"bool": {"must_not": [exists]}}
    if kind == "in":
        return {"terms": {exact(_col_of(expr[1])): expr[2]}}
    if kind == "between":
        return {"range": {_col_of(expr[1]): {"gte": expr[2], "lte": expr[3]}}}
    if kind == "match":
        return {"match": {expr[1]: {"query": expr[2]}}}
    raise IllegalArgumentError(f"unsupported WHERE construct [{kind}]")


_AGG_DSL = {"sum": "sum", "avg": "avg", "min": "min", "max": "max",
            "count": "value_count"}


def translate(q: SqlQuery, default_fetch_size: int = 1000,
              exact=_ident_resolver, sort_field=_ident_resolver) -> dict:
    """Fold the parsed query into one `_search` body (`_sql/translate`)."""
    body: dict = {}
    if q.where is not None:
        body["query"] = where_to_dsl(q.where, exact)
    has_aggs = q.group_by or any(it.is_agg for it in q.select)
    if not has_aggs:
        body["size"] = q.limit if q.limit is not None else default_fetch_size
        if q.order_by:
            body["sort"] = [{sort_field(e[1]): {"order": d}}
                            for e, d in q.order_by if e[0] == "col"]
        if not q.star:
            cols = [it.expr[1] for it in q.select if it.expr[0] == "col"]
            body["_source"] = {"includes": cols}
        return body
    # aggregation fold
    body["size"] = 0
    metric_aggs = {}
    for i, it in enumerate(q.select):
        if not it.is_agg:
            continue
        fname, arg = it.expr[1], it.expr[2]
        if fname == "count" and arg is None:
            continue   # doc_count
        metric_aggs[f"m{i}"] = {_AGG_DSL[fname]: {"field": arg}}
    if q.group_by:
        sources = [{g: {"terms": {"field": sort_field(g)}}} for g in q.group_by]
        comp: dict = {"composite": {"sources": sources, "size": 1000}}
        if metric_aggs:
            comp["aggs"] = metric_aggs
        body["aggs"] = {"groupby": comp}
    else:
        body["aggs"] = metric_aggs or {}
    return body


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------

_TYPE_MAP = {
    "keyword": "keyword", "text": "text", "long": "long", "integer": "integer",
    "short": "short", "byte": "byte", "double": "double", "float": "float",
    "half_float": "half_float", "scaled_float": "scaled_float", "date": "datetime",
    "boolean": "boolean", "ip": "ip", "dense_vector": "dense_vector",
}


def _eval_having(expr, row_vals: Dict[str, Any]) -> bool:
    kind = expr[0]
    if kind == "and":
        return _eval_having(expr[1], row_vals) and _eval_having(expr[2], row_vals)
    if kind == "or":
        return _eval_having(expr[1], row_vals) or _eval_having(expr[2], row_vals)
    if kind == "not":
        return not _eval_having(expr[1], row_vals)
    if kind == "cmp":
        op, left, right = expr[1], expr[2], expr[3]
        lv = _having_operand(left, row_vals)
        rv = _lit_of(right)
        if lv is None:
            return False
        return {"=": lv == rv, "!=": lv != rv, "<>": lv != rv, "<": lv < rv,
                "<=": lv <= rv, ">": lv > rv, ">=": lv >= rv}[op]
    raise IllegalArgumentError(f"unsupported HAVING construct [{kind}]")


def _having_operand(e, row_vals: Dict[str, Any]):
    if e[0] == "col":
        return row_vals.get(e[1])
    if e[0] == "func":
        arg = "*" if e[2] is None else e[2]
        return row_vals.get(f"{e[1].upper()}({arg})")
    if e[0] == "lit":
        return e[1]
    return None


class SqlEngine:
    def __init__(self, node):
        self.node = node

    def translate(self, body: dict) -> dict:
        q = parse_sql(body.get("query", ""))
        exact = self._exact(q.table)
        return translate(q, body.get("fetch_size", 1000), exact, exact)

    def execute(self, body: dict) -> dict:
        cursor = body.get("cursor")
        if cursor:
            return self._fetch_cursor(cursor)
        sql = body.get("query", "")
        fetch_size = int(body.get("fetch_size", 1000))
        q = parse_sql(sql)
        has_aggs = bool(q.group_by or any(it.is_agg for it in q.select))
        if has_aggs:
            return self._execute_aggs(q)
        return self._execute_filter(q, fetch_size, from_=0)

    def close_cursor(self, body: dict) -> dict:
        return {"succeeded": True}

    # -- filter-mode ---------------------------------------------------------
    def _columns_for(self, q: SqlQuery, index: str) -> List[dict]:
        mappings = self._field_types(index)
        if q.star:
            return [{"name": n, "type": _TYPE_MAP.get(t, t)}
                    for n, t in sorted(mappings.items())]
        cols = []
        for it in q.select:
            if it.expr[0] == "col":
                t = mappings.get(it.expr[1], "keyword")
                cols.append({"name": it.name, "type": _TYPE_MAP.get(t, t)})
            elif it.expr[0] == "lit":
                cols.append({"name": it.name, "type": "keyword"})
        return cols

    def _field_defs(self, index: str) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        try:
            services = self.node.indices.resolve(index)
        except Exception:
            return out
        for svc in services:
            def walk(props, prefix=""):
                for fname, fdef in props.items():
                    full = prefix + fname
                    if "properties" in fdef:
                        walk(fdef["properties"], full + ".")
                    else:
                        out[full] = fdef
            walk(svc.mapper_service.to_dict().get("properties", {}))
        return out

    def _field_types(self, index: str) -> Dict[str, str]:
        return {n: d.get("type", "object")
                for n, d in self._field_defs(index).items()}

    def _exact(self, index: str):
        """Column → exact-match field: text with a keyword subfield resolves
        to `col.keyword` (reference: FieldAttribute.exactAttribute())."""
        defs = self._field_defs(index)

        def resolve(field: str) -> str:
            d = defs.get(field)
            if d is not None and d.get("type") == "text" and \
                    "keyword" in d.get("fields", {}):
                return field + ".keyword"
            return field
        return resolve

    def _execute_filter(self, q: SqlQuery, fetch_size: int, from_: int) -> dict:
        exact = self._exact(q.table)
        search_body = translate(q, fetch_size, exact, exact)
        total_wanted = q.limit if q.limit is not None else None
        page = fetch_size if total_wanted is None else min(fetch_size, total_wanted - from_)
        search_body["size"] = max(page, 0)
        search_body["from"] = from_
        result = self.node.search(q.table, search_body)
        hits = result["hits"]["hits"]
        columns = self._columns_for(q, q.table)
        col_names = [c["name"] for c in columns]
        select_exprs = None if q.star else [it.expr for it in q.select]
        rows = []
        for h in hits:
            src = h.get("_source", {})
            if q.star:
                rows.append([_get_dotted(src, n) for n in col_names])
            else:
                row = []
                for e in select_exprs:
                    row.append(_get_dotted(src, e[1]) if e[0] == "col" else e[1])
                rows.append(row)
        out = {"columns": columns, "rows": rows}
        total = result["hits"]["total"]["value"]
        next_from = from_ + len(hits)
        remaining = (total if total_wanted is None else min(total, total_wanted))
        if len(hits) == search_body["size"] and next_from < remaining:
            state = {"sql": _unparse(q), "fetch_size": fetch_size, "from": next_from}
            out["cursor"] = base64.b64encode(json.dumps(state).encode()).decode()
        return out

    def _fetch_cursor(self, cursor: str) -> dict:
        try:
            state = json.loads(base64.b64decode(cursor))
        except Exception:
            raise IllegalArgumentError("invalid cursor")
        q = parse_sql(state["sql"])
        return self._execute_filter(q, state["fetch_size"], state["from"])

    # -- agg-mode ------------------------------------------------------------
    def _execute_aggs(self, q: SqlQuery) -> dict:
        exact = self._exact(q.table)
        search_body = translate(q, exact=exact, sort_field=exact)
        result = self.node.search(q.table, search_body)
        aggs = result.get("aggregations", {})
        columns = []
        mappings = self._field_types(q.table)
        for it in q.select:
            if it.is_agg:
                fname = it.expr[1]
                typ = "long" if fname == "count" else "double"
                columns.append({"name": it.name, "type": typ})
            else:
                t = mappings.get(it.expr[1], "keyword")
                columns.append({"name": it.name, "type": _TYPE_MAP.get(t, t)})
        rows = []
        if q.group_by:
            buckets = aggs.get("groupby", {}).get("buckets", [])
            for b in buckets:
                row_vals: Dict[str, Any] = {}
                for g in q.group_by:
                    row_vals[g] = b["key"].get(g)
                for i, it in enumerate(q.select):
                    if not it.is_agg:
                        continue
                    fname, arg = it.expr[1], it.expr[2]
                    if fname == "count" and arg is None:
                        row_vals[it.name] = b["doc_count"]
                    else:
                        row_vals[it.name] = b.get(f"m{i}", {}).get("value")
                if q.having is not None and not _eval_having(q.having, row_vals):
                    continue
                row = []
                for it in q.select:
                    row.append(row_vals.get(it.name if it.is_agg else it.expr[1]))
                rows.append(row)
            rows = _order_rows(rows, q, columns)
            if q.limit is not None:
                rows = rows[:q.limit]
        else:
            row = []
            total = None
            for i, it in enumerate(q.select):
                fname, arg = it.expr[1], it.expr[2]
                if fname == "count" and arg is None:
                    if total is None:
                        r2 = self.node.search(
                            q.table, {"size": 0,
                                      **({"query": where_to_dsl(q.where)}
                                         if q.where else {})})
                        total = r2["hits"]["total"]["value"]
                    row.append(total)
                else:
                    row.append(aggs.get(f"m{i}", {}).get("value"))
            rows = [row]
        return {"columns": columns, "rows": rows}


def _order_rows(rows, q: SqlQuery, columns) -> list:
    if not q.order_by:
        return rows
    names = [c["name"] for c in columns]
    for expr, direction in reversed(q.order_by):
        if expr[0] == "col":
            key_name = expr[1]
        else:
            arg = "*" if expr[2] is None else expr[2]
            key_name = f"{expr[1].upper()}({arg})"
        if key_name not in names:
            continue
        idx = names.index(key_name)
        rows.sort(key=lambda r: (r[idx] is None, r[idx]),
                  reverse=(direction == "desc"))
    return rows


def _get_dotted(src: dict, path: str):
    cur: Any = src
    for p in path.split("."):
        if not isinstance(cur, dict) or p not in cur:
            return None
        cur = cur[p]
    return cur


def _unparse(q: SqlQuery) -> str:
    """Round-trip the query for cursor state (we re-parse the original)."""
    return q._original


# ---------------------------------------------------------------------------
# text format (the CLI table renderer, `format=txt`)
# ---------------------------------------------------------------------------

def to_text_table(result: dict) -> str:
    cols = [c["name"] for c in result["columns"]]
    rows = [[("null" if v is None else str(v)) for v in r]
            for r in result["rows"]]
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    header = "|".join(c.center(w + 2) for c, w in zip(cols, widths))
    sep = "+".join("-" * (w + 2) for w in widths)
    lines = [header, sep]
    for r in rows:
        lines.append("|".join(v.ljust(w + 1).rjust(w + 2)
                              for v, w in zip(r, widths)))
    return "\n".join(lines) + "\n"
