"""Transform (continuous pivot/latest materialization) + rollup jobs.

Reference: `x-pack/plugin/transform` (11k LoC) — a transform pivots a source
index through composite aggregations into a dest index, checkpointed on a
sync field for continuous mode (`TransformIndexer`); `x-pack/plugin/rollup`
(4.8k) downsamples into rollup docs keyed by date-histogram buckets. Both
are tick-driven here (`run_once`/`trigger`) like their SchedulerEngine
scheduling in the reference.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    ResourceAlreadyExistsError,
    ResourceNotFoundError,
    ValidationError,
)


# consecutive tick failures before a continuous transform/rollup task flips
# to failed instead of retrying forever (reference TransformTask keeps a
# failure count and fails the task, recording the reason in _stats)
MAX_CONSECUTIVE_FAILURES = 10

# consecutive unchanged-fingerprint ticks an indexer may skip before it
# must run one pass anyway. The fingerprint only sees THIS node's
# searchable state while the indexer's search is cluster-wide, so change
# detection is an optimization that must never gate liveness — bucket
# doc-ids make the periodic re-run an idempotent no-op on the dest.
MAX_FP_SKIPS = 15


def _record_indexer_failure(st: dict, exc: Exception,
                            state_key: str = "state") -> None:
    """state_key: 'state' for transforms, 'job_state' for rollup jobs —
    the two services track their lifecycle under different keys."""
    st["failure_count"] = st.get("failure_count", 0) + 1
    st["last_failure"] = f"{type(exc).__name__}: {exc}"
    if st["failure_count"] >= MAX_CONSECUTIVE_FAILURES \
            and st.get(state_key) == "started":
        st[state_key] = "failed"
        st["reason"] = (
            f"task has failed {st['failure_count']} consecutive times: "
            f"{st['last_failure']}")


def _exact_resolver(node, indices: str):
    """Field → exact/aggregatable field (.keyword subfield for text), the
    same resolution the reference's transform does via field_caps."""
    defs: Dict[str, dict] = {}
    try:
        services = node.indices.resolve(indices)
    except Exception:
        services = []
    for svc in services:
        def walk(props, prefix=""):
            for fname, fdef in props.items():
                full = prefix + fname
                if "properties" in fdef:
                    walk(fdef["properties"], full + ".")
                else:
                    defs[full] = fdef
        walk(svc.mapper_service.to_dict().get("properties", {}))

    def resolve(field: str) -> str:
        d = defs.get(field)
        if d is not None and d.get("type") == "text" and \
                "keyword" in d.get("fields", {}):
            return field + ".keyword"
        return field
    return resolve


def _doc_id_for(keys: Dict[str, Any]) -> str:
    """Stable dest doc id from group-by values (reference:
    TransformIndexer creates ids by hashing the composite key)."""
    blob = json.dumps(keys, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:20]


class TransformService:
    def __init__(self, node):
        self.node = node
        self.transforms: Dict[str, dict] = {}
        self.state: Dict[str, dict] = {}

    # -- CRUD -----------------------------------------------------------------
    def put(self, transform_id: str, body: dict) -> None:
        if transform_id in self.transforms:
            raise ResourceAlreadyExistsError(
                f"transform [{transform_id}] already exists")
        if "source" not in body or "dest" not in body:
            raise ValidationError("transform requires [source] and [dest]")
        if "pivot" not in body and "latest" not in body:
            raise ValidationError("transform requires [pivot] or [latest]")
        self.transforms[transform_id] = body
        self.state[transform_id] = {"state": "stopped", "checkpoint": 0,
                                    "docs_indexed": 0, "search_total": 0}

    def get(self, transform_id: Optional[str] = None) -> dict:
        if transform_id in (None, "_all", "*"):
            return {"count": len(self.transforms),
                    "transforms": [{"id": tid, **cfg}
                                   for tid, cfg in self.transforms.items()]}
        if transform_id not in self.transforms:
            raise ResourceNotFoundError(f"transform [{transform_id}] not found")
        return {"count": 1, "transforms": [{"id": transform_id,
                                            **self.transforms[transform_id]}]}

    def delete(self, transform_id: str) -> None:
        if transform_id not in self.transforms:
            raise ResourceNotFoundError(f"transform [{transform_id}] not found")
        del self.transforms[transform_id]
        self.state.pop(transform_id, None)

    def stats(self, transform_id: str) -> dict:
        if transform_id not in self.transforms:
            raise ResourceNotFoundError(f"transform [{transform_id}] not found")
        st = self.state[transform_id]
        entry = {"id": transform_id,
                 "state": st["state"],
                 "checkpointing": {"last": {"checkpoint": st["checkpoint"]}},
                 "stats": {"documents_indexed": st["docs_indexed"]}}
        if st.get("reason"):
            entry["reason"] = st["reason"]
        if st.get("failure_count"):
            entry["stats"]["index_failures"] = st["failure_count"]
        return {"count": 1, "transforms": [entry]}

    # -- execution ------------------------------------------------------------
    def start(self, transform_id: str) -> None:
        if transform_id not in self.transforms:
            raise ResourceNotFoundError(f"transform [{transform_id}] not found")
        self.state[transform_id]["state"] = "started"
        self.trigger(transform_id)

    def stop(self, transform_id: str) -> None:
        if transform_id not in self.transforms:
            raise ResourceNotFoundError(f"transform [{transform_id}] not found")
        self.state[transform_id]["state"] = "stopped"

    def _source_fingerprint(self, indices) -> tuple:
        """Cheap change detector: (doc_count, max_seq_no) over the source —
        ticks skip when nothing advanced (TransformIndexer change
        detection; re-running on an unchanged source would spin
        checkpoints forever).

        Measured on the SEARCHABLE reader snapshot, not the live engine
        counters: engine doc_count/max_seq_no advance at index time, but
        the indexer's search only sees refreshed segments. A fingerprint
        recorded ahead of searchable state would mark docs as processed
        that the pass never saw — the tick then skips forever and the
        delta is lost (the wall-clock race the rollup cluster test used
        to lose).

        The fingerprint is still only LOCAL visibility, while the
        indexer's search is cluster-wide (a remote primary may hold
        refreshed docs this node's replica never shows) — so skipping is
        bounded by MAX_FP_SKIPS rather than trusted outright."""
        if isinstance(indices, list):
            indices = ",".join(indices)
        total, max_seq = 0, -1
        try:
            for svc in self.node.indices.resolve(indices):
                for shard in svc.shards:
                    reader = shard.engine.acquire_searcher()
                    total += reader.num_docs
                    # the seq_no scan is O(live docs); readers are
                    # immutable point-in-time snapshots keyed by gen, so
                    # cache per reader generation — ticks against an
                    # unchanged reader stay O(1)
                    cached = getattr(shard, "_fp_seq_cache", None)
                    if cached is not None and cached[0] == reader.gen:
                        shard_max = cached[1]
                    else:
                        shard_max = -1
                        for view in reader.views:
                            if view.live.any():
                                shard_max = max(shard_max, int(
                                    view.segment.seq_nos[view.live].max()))
                        shard._fp_seq_cache = (reader.gen, shard_max)
                    max_seq = max(max_seq, shard_max)
        except Exception:
            return ("unresolvable",)
        return (total, max_seq)

    def run_once(self) -> None:
        """Scheduler tick: re-index started continuous transforms whose
        source advanced since the last checkpoint."""
        for tid in list(self.transforms):
            cfg = self.transforms.get(tid)
            st = self.state.get(tid)
            if cfg is None or st is None or st.get("state") != "started" \
                    or "sync" not in cfg:
                continue
            fp = self._source_fingerprint(cfg["source"].get("index"))
            if st.get("last_source_fp") == fp \
                    and st.get("fp_skips", 0) < MAX_FP_SKIPS:
                st["fp_skips"] = st.get("fp_skips", 0) + 1
                continue
            try:
                self.trigger(tid)
                st["last_source_fp"] = fp
                st["fp_skips"] = 0
                st.pop("failure_count", None)
            except Exception as e:  # a tick failure must not kill the
                _record_indexer_failure(st, e)  # scheduler — but it must
                # surface in state/_stats, and a permanently broken
                # transform flips to failed instead of retrying forever
                # (reference TransformTask.fail + _stats reason)

    def preview(self, body: dict) -> dict:
        docs = self._compute(body)
        return {"preview": docs[:100]}

    def trigger(self, transform_id: str) -> dict:
        """Run one checkpoint: recompute the pivot and upsert into dest.
        (The reference advances bucket-by-bucket off change detection; a full
        recompute reaches the same dest state.)"""
        cfg = self.transforms[transform_id]
        st = self.state[transform_id]
        docs = self._compute(cfg)
        dest = cfg["dest"]["index"]
        for doc in docs:
            self.node.index_doc(dest, doc.pop("_id"), doc)
        if self.node.indices.exists(dest):
            self.node.indices.get(dest).refresh()
        st["checkpoint"] += 1
        st["docs_indexed"] += len(docs)
        if "sync" not in cfg:     # batch transform: done after one pass
            st["state"] = "stopped"
        return {"documents_indexed": len(docs)}

    def _compute(self, cfg: dict) -> List[dict]:
        source = cfg["source"]
        indices = source.get("index")
        if isinstance(indices, list):
            indices = ",".join(indices)
        query = source.get("query", {"match_all": {}})
        if "pivot" in cfg:
            return self._compute_pivot(indices, query, cfg["pivot"])
        return self._compute_latest(indices, query, cfg["latest"])

    def _compute_pivot(self, indices: str, query: dict, pivot: dict) -> List[dict]:
        group_by = pivot.get("group_by", {})
        aggs_def = pivot.get("aggregations", pivot.get("aggs", {}))
        exact = _exact_resolver(self.node, indices)
        sources = []
        for name, g in group_by.items():
            kind, spec = next(iter(g.items()))
            if "field" in spec:
                spec = {**spec, "field": exact(spec["field"])}
            sources.append({name: {kind: spec}})
        body = {"size": 0, "query": query,
                "aggs": {"_pivot": {"composite": {"sources": sources,
                                                  "size": 10000},
                                    "aggs": aggs_def}}}
        result = self.node.search(indices, body)
        docs = []
        for bucket in result["aggregations"]["_pivot"]["buckets"]:
            doc = dict(bucket["key"])
            for agg_name in aggs_def:
                val = bucket.get(agg_name, {})
                doc[agg_name] = val.get("value", val)
            doc["_id"] = _doc_id_for(bucket["key"])
            docs.append(doc)
        return docs

    def _compute_latest(self, indices: str, query: dict, latest: dict) -> List[dict]:
        unique_key = latest["unique_key"]
        if isinstance(unique_key, str):
            unique_key = [unique_key]
        sort_field = latest["sort"]
        result = self.node.search(indices, {
            "size": 10000, "query": query,
            "sort": [{sort_field: {"order": "desc"}}]})
        seen = set()
        docs = []
        for h in result["hits"]["hits"]:
            src = h["_source"]
            key = tuple(str(_dot(src, k)) for k in unique_key)
            if key in seen:
                continue
            seen.add(key)
            docs.append({**src, "_id": _doc_id_for(dict(zip(unique_key, key)))})
        return docs


class RollupService:
    def __init__(self, node):
        self.node = node
        self.jobs: Dict[str, dict] = {}
        self.state: Dict[str, dict] = {}

    def put_job(self, job_id: str, body: dict) -> None:
        if job_id in self.jobs:
            raise ResourceAlreadyExistsError(f"job [{job_id}] already exists")
        for req in ("index_pattern", "rollup_index", "groups"):
            if req not in body:
                raise ValidationError(f"rollup job requires [{req}]")
        if "date_histogram" not in body["groups"]:
            raise ValidationError("rollup requires groups.date_histogram")
        self.jobs[job_id] = body
        self.state[job_id] = {"job_state": "stopped", "documents_processed": 0,
                              "rollups_indexed": 0}

    def get_job(self, job_id: Optional[str] = None) -> dict:
        if job_id in (None, "_all"):
            jobs = list(self.jobs)
        else:
            if job_id not in self.jobs:
                raise ResourceNotFoundError(f"job [{job_id}] not found")
            jobs = [job_id]
        return {"jobs": [{"config": {**self.jobs[j], "id": j},
                          "status": {"job_state":
                                     self.state[j]["job_state"]},
                          "stats": {"rollups_indexed":
                                    self.state[j]["rollups_indexed"]}}
                         for j in jobs]}

    def delete_job(self, job_id: str) -> None:
        if job_id not in self.jobs:
            raise ResourceNotFoundError(f"job [{job_id}] not found")
        del self.jobs[job_id]
        self.state.pop(job_id, None)

    def start_job(self, job_id: str) -> dict:
        if job_id not in self.jobs:
            raise ResourceNotFoundError(f"job [{job_id}] not found")
        self.state[job_id]["job_state"] = "started"
        self.trigger(job_id)
        return {"started": True}

    def stop_job(self, job_id: str) -> dict:
        if job_id not in self.jobs:
            raise ResourceNotFoundError(f"job [{job_id}] not found")
        self.state[job_id]["job_state"] = "stopped"
        return {"stopped": True}

    def run_once(self) -> None:
        """Scheduler tick (RollupJobTask's scheduled indexer): started jobs
        whose source advanced run one pass; bucket doc-ids make re-runs
        idempotent upserts, so each tick checkpoints the dest."""
        for jid in list(self.jobs):
            cfg = self.jobs.get(jid)
            st = self.state.get(jid)
            if cfg is None or st is None \
                    or st.get("job_state") != "started":
                continue
            fp = TransformService._source_fingerprint(
                self, cfg["index_pattern"])
            if st.get("last_source_fp") == fp \
                    and st.get("fp_skips", 0) < MAX_FP_SKIPS:
                st["fp_skips"] = st.get("fp_skips", 0) + 1
                continue
            try:
                self.trigger(jid)
                st["last_source_fp"] = fp
                st["fp_skips"] = 0
                st.pop("failure_count", None)
            except Exception as e:  # a tick failure must not kill the
                # scheduler (see transform)
                _record_indexer_failure(st, e, state_key="job_state")

    def trigger(self, job_id: str) -> dict:
        """Run one rollup pass: composite over (date_histogram [+ terms])
        with the configured metric sub-aggs, one rollup doc per bucket."""
        cfg = self.jobs[job_id]
        groups = cfg["groups"]
        exact = _exact_resolver(self.node, cfg["index_pattern"])
        dh = dict(groups["date_histogram"])
        date_field = dh.pop("field")
        sources: List[dict] = [
            {f"{date_field}.date_histogram":
             {"date_histogram": {"field": date_field, **dh}}}]
        term_fields = groups.get("terms", {}).get("fields", [])
        for tf in term_fields:
            sources.append({f"{tf}.terms": {"terms": {"field": exact(tf)}}})
        aggs = {}
        for m in cfg.get("metrics", []):
            for metric in m.get("metrics", []):
                agg_kind = "value_count" if metric == "value_count" else metric
                aggs[f"{m['field']}.{metric}"] = {agg_kind: {"field": m["field"]}}
        body = {"size": 0,
                "aggs": {"_rollup": {"composite": {"sources": sources,
                                                   "size": 10000},
                                     **({"aggs": aggs} if aggs else {})}}}
        result = self.node.search(cfg["index_pattern"], body)
        n = 0
        for bucket in result["aggregations"]["_rollup"]["buckets"]:
            doc = {"_rollup.id": job_id, "_rollup.version": 2}
            for k, v in bucket["key"].items():
                doc[k] = v
            doc[f"{date_field}.date_histogram._count"] = bucket["doc_count"]
            for agg_name in aggs:
                doc[agg_name] = bucket.get(agg_name, {}).get("value")
            self.node.index_doc(cfg["rollup_index"],
                                _doc_id_for(bucket["key"]), doc)
            n += 1
        if self.node.indices.exists(cfg["rollup_index"]):
            self.node.indices.get(cfg["rollup_index"]).refresh()
        self.state[job_id]["rollups_indexed"] += n
        return {"rollups_indexed": n}

    def caps(self, index_pattern: str) -> dict:
        out: Dict[str, Any] = {}
        for jid, cfg in self.jobs.items():
            if cfg["index_pattern"] == index_pattern or index_pattern == "_all":
                out.setdefault(cfg["index_pattern"], {"rollup_jobs": []})
                out[cfg["index_pattern"]]["rollup_jobs"].append(
                    {"job_id": jid, "rollup_index": cfg["rollup_index"],
                     "index_pattern": cfg["index_pattern"],
                     "fields": self._field_caps(cfg)})
        return out

    def _field_caps(self, cfg: dict) -> Dict[str, list]:
        fields: Dict[str, list] = {}
        dh = cfg["groups"]["date_histogram"]
        fields[dh["field"]] = [{"agg": "date_histogram",
                                **{k: v for k, v in dh.items() if k != "field"}}]
        for tf in cfg["groups"].get("terms", {}).get("fields", []):
            fields.setdefault(tf, []).append({"agg": "terms"})
        for m in cfg.get("metrics", []):
            for metric in m.get("metrics", []):
                fields.setdefault(m["field"], []).append({"agg": metric})
        return fields


def _dot(src: dict, path: str):
    cur: Any = src
    for p in path.split("."):
        if not isinstance(cur, dict) or p not in cur:
            return None
        cur = cur[p]
    return cur
