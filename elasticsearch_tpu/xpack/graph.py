"""Graph exploration API.

Reference: `x-pack/plugin/graph` (1.3k LoC) — `TransportGraphExploreAction`
runs an iterative crawl: seed query → significant terms per requested
vertex field → follow-up queries on found terms to discover connected
vertices, returned as a vertices[] + connections[] graph keyed by array
index. Built here on the public search surface (terms aggregations), one
hop per `connections` nesting level like the reference.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import ValidationError


class GraphService:
    def __init__(self, node):
        self.node = node

    def explore(self, index: str, body: dict) -> dict:
        started = time.time()
        query = body.get("query", {"match_all": {}})
        vertex_specs = body.get("vertices", [])
        if not vertex_specs:
            raise ValidationError("graph explore requires [vertices]")
        use_sig = bool(body.get("use_significance", True))

        vertices: List[dict] = []          # {field, term, weight, depth}
        vertex_index: Dict[Tuple[str, str], int] = {}
        connections: List[dict] = []

        def add_vertex(field: str, term: str, weight: float,
                       depth: int) -> int:
            key = (field, term)
            if key in vertex_index:
                return vertex_index[key]
            vertex_index[key] = len(vertices)
            vertices.append({"field": field, "term": term,
                             "weight": weight, "depth": depth})
            return vertex_index[key]

        # depth 0: seed terms from the query
        seeds: List[int] = []
        for spec in vertex_specs:
            for term, count, weight in self._top_terms(
                    index, query, spec, use_sig):
                seeds.append(add_vertex(spec["field"], term, weight, 0))

        # one hop per connections level (reference: Hop chaining)
        frontier = list(dict.fromkeys(seeds))
        depth = 1
        conn_body = body.get("connections")
        while conn_body and frontier:
            conn_specs = conn_body.get("vertices", [])
            next_frontier: List[int] = []
            frontier_seen: set = set()
            for src_idx in frontier:
                src = vertices[src_idx]
                hop_query = {"bool": {"filter": [
                    {"term": {src["field"]: src["term"]}}]}}
                for spec in conn_specs:
                    for term, count, weight in self._top_terms(
                            index, hop_query, spec, use_sig):
                        if (spec["field"], term) == (src["field"],
                                                     src["term"]):
                            continue
                        tgt_idx = add_vertex(spec["field"], term, weight,
                                             depth)
                        connections.append({"source": src_idx,
                                            "target": tgt_idx,
                                            "weight": weight,
                                            "doc_count": count})
                        if vertices[tgt_idx]["depth"] == depth \
                                and tgt_idx not in frontier_seen:
                            frontier_seen.add(tgt_idx)
                            next_frontier.append(tgt_idx)
            frontier = next_frontier
            conn_body = conn_body.get("connections")
            depth += 1

        return {"took": int((time.time() - started) * 1000),
                "timed_out": False,
                "failures": [],
                "vertices": vertices,
                "connections": connections}

    def _top_terms(self, index: str, query: dict, spec: dict,
                   use_sig: bool) -> List[Tuple[str, int, float]]:
        field = spec["field"]
        size = int(spec.get("size", 5))
        min_doc_count = int(spec.get("min_doc_count", 1))
        agg_kind = "significant_terms" if use_sig else "terms"
        resp = self.node.search(index, {
            "query": query, "size": 0,
            "aggs": {"v": {agg_kind: {"field": field,
                                      "size": size,
                                      "min_doc_count": min_doc_count}}}})
        out = []
        for b in resp["aggregations"]["v"]["buckets"]:
            count = int(b["doc_count"])
            weight = float(b.get("score", count))
            out.append((str(b["key"]), count, weight))
        return out
