"""Graph exploration API.

Reference: `x-pack/plugin/graph` (1.3k LoC),
`TransportGraphExploreAction.java`: an iterative crawl where EACH HOP is
one search — the frontier becomes a boosted bool query (term clauses
weighted by vertex weight), a `sampler` agg caps the docs considered per
hop (`controls.sample_size`, default 100 — the "best matching" sample),
and per source-field terms buckets (include-filtered to the frontier)
nest significant-terms aggs per target vertex spec. Vertex weights are
the significance scores normalized per wave; `use_significance: false`
falls back to popular terms. Per-vertex `include`/`exclude` filter the
crawl, and `controls.timeout` bounds wall time with `timed_out` reported,
matching the reference's deadline checks between waves.
"""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

from elasticsearch_tpu.common.errors import ValidationError

DEFAULT_SAMPLE_SIZE = 100   # GraphExploreRequest.DEFAULT_SAMPLE_SIZE
DEFAULT_VERTEX_SIZE = 5


class GraphService:
    def __init__(self, node):
        self.node = node

    # ---------------------------------------------------------------- api
    def explore(self, index: str, body: dict) -> dict:
        started = time.time()
        body = body or {}
        controls = body.get("controls") or {}
        query = body.get("query", {"match_all": {}})
        vertex_specs = body.get("vertices", [])
        if not vertex_specs:
            raise ValidationError("graph explore requires [vertices]")
        use_sig = bool(controls.get(
            "use_significance", body.get("use_significance", True)))
        sample_size = int(controls.get("sample_size", DEFAULT_SAMPLE_SIZE))
        timeout_ms = controls.get("timeout")
        if timeout_ms is None:
            timeout_ms = body.get("timeout")
        deadline = (started + float(timeout_ms) / 1000.0) \
            if timeout_ms is not None else None
        timed_out = False

        vertices: List[dict] = []          # {field, term, weight, depth}
        vertex_index: Dict[Tuple[str, str], int] = {}
        connections: List[dict] = []

        def add_vertex(field: str, term: str, weight: float,
                       depth: int) -> int:
            key = (field, term)
            if key in vertex_index:
                idx = vertex_index[key]
                # revisits keep the strongest evidence (reference folds
                # repeat sightings into the existing vertex)
                vertices[idx]["weight"] = max(vertices[idx]["weight"],
                                              weight)
                return idx
            vertex_index[key] = len(vertices)
            vertices.append({"field": field, "term": term,
                             "weight": weight, "depth": depth})
            return vertex_index[key]

        # ---- depth 0: seed wave — one search, sampler + per-spec aggs
        seed_aggs = {f"v{i}": self._vertex_agg(spec, use_sig)
                     for i, spec in enumerate(vertex_specs)}
        resp = self.node.search(index, {
            "query": query, "size": 0,
            "aggs": {"sample": {"sampler": {"shard_size": sample_size},
                                "aggs": seed_aggs}}})
        # normalize ONCE per wave (across every spec's buckets), so a
        # marginal term in a sparse field cannot masquerade as weight 1.0
        wave = []
        for i, spec in enumerate(vertex_specs):
            buckets = resp["aggregations"]["sample"][f"v{i}"]["buckets"]
            wave.extend((spec["field"], t, c, s)
                        for t, c, s in self._raw(buckets, use_sig))
        frontier: List[int] = []
        for field, term, _count, weight in self._wave_normalize(wave):
            frontier.append(add_vertex(field, term, weight, 0))
        frontier = list(dict.fromkeys(frontier))

        # ---- hops: ONE search per connections level (Hop chaining)
        conn_body = body.get("connections")
        depth = 1
        while conn_body and frontier:
            if deadline is not None and time.time() > deadline:
                timed_out = True
                break
            conn_specs = conn_body.get("vertices", [])
            if not conn_specs:
                break
            frontier, new_conns = self._one_hop(
                index, vertices, frontier, conn_specs, use_sig,
                sample_size, depth, add_vertex, conn_body.get("query"))
            connections.extend(new_conns)
            conn_body = conn_body.get("connections")
            depth += 1

        return {"took": int((time.time() - started) * 1000),
                "timed_out": timed_out,
                "failures": [],
                "vertices": vertices,
                "connections": connections}

    # ------------------------------------------------------------ one hop
    def _one_hop(self, index, vertices, frontier, conn_specs, use_sig,
                 sample_size, depth, add_vertex, hop_query):
        """Expand the whole frontier with ONE search: boosted bool query
        over the frontier terms; terms agg per source field (include:
        frontier terms) nesting the target vertex aggs — bucket paths
        give source→target connections directly."""
        by_field: Dict[str, List[int]] = {}
        for idx in frontier:
            by_field.setdefault(vertices[idx]["field"], []).append(idx)

        should = [{"term": {vertices[i]["field"]: {
                       "value": vertices[i]["term"],
                       "boost": max(float(vertices[i]["weight"]), 1e-9)}}}
                  for i in frontier]
        query = {"bool": {"should": should, "minimum_should_match": 1}}
        if hop_query:
            # guiding query for this hop (the reference ANDs the hop's
            # optional query with the frontier expansion)
            query = {"bool": {"must": [query, hop_query]}}

        src_aggs = {}
        for f_i, (field, idxs) in enumerate(by_field.items()):
            tgt_aggs = {f"t{j}": self._vertex_agg(spec, use_sig)
                        for j, spec in enumerate(conn_specs)}
            src_aggs[f"s{f_i}"] = {
                "terms": {"field": field,
                          "include": [vertices[i]["term"] for i in idxs],
                          "size": len(idxs)},
                "aggs": tgt_aggs}
        resp = self.node.search(index, {
            "query": query, "size": 0,
            "aggs": {"sample": {"sampler": {"shard_size": sample_size},
                                "aggs": src_aggs}}})

        # collect the WHOLE wave's raw scores first, normalize once, then
        # materialize vertices/connections — per-bucket normalization
        # would hand weak evidence the same 1.0 as the wave's best
        raw_edges = []   # (src_idx, field, term, count, score)
        sample = resp["aggregations"]["sample"]
        for f_i, (field, idxs) in enumerate(by_field.items()):
            for src_bucket in sample[f"s{f_i}"]["buckets"]:
                src_term = str(src_bucket["key"])
                src_idx = next((i for i in idxs
                                if vertices[i]["term"] == src_term), None)
                if src_idx is None:
                    continue
                for j, spec in enumerate(conn_specs):
                    for term, count, score in self._raw(
                            src_bucket[f"t{j}"]["buckets"], use_sig):
                        if (spec["field"], term) == (field, src_term):
                            continue
                        raw_edges.append((src_idx, spec["field"], term,
                                          count, score))
        best = max((s for *_rest, s in raw_edges), default=0.0)
        next_frontier: List[int] = []
        connections: List[dict] = []
        for src_idx, field, term, count, score in raw_edges:
            weight = (score / best) if best > 0 else 1.0
            tgt_idx = add_vertex(field, term, weight, depth)
            connections.append({"source": src_idx, "target": tgt_idx,
                                "weight": weight, "doc_count": count})
            if vertices[tgt_idx]["depth"] == depth:
                next_frontier.append(tgt_idx)
        return list(dict.fromkeys(next_frontier)), connections

    # ------------------------------------------------------------ helpers
    @staticmethod
    def _vertex_agg(spec: dict, use_sig: bool) -> dict:
        """One vertex request -> its terms / significant_terms agg with
        the reference's include/exclude + size + min_doc_count controls."""
        field = spec["field"]
        agg: dict = {"field": field,
                     "size": int(spec.get("size", DEFAULT_VERTEX_SIZE)),
                     "min_doc_count": int(spec.get("min_doc_count",
                                                   3 if use_sig else 1))}
        include = spec.get("include")
        if include:
            # include entries may be bare terms or {term, boost}
            agg["include"] = [e["term"] if isinstance(e, dict) else e
                              for e in include]
        if spec.get("exclude"):
            agg["exclude"] = list(spec["exclude"])
        kind = "significant_terms" if use_sig else "terms"
        return {kind: agg}

    @staticmethod
    def _raw(buckets: List[dict],
             use_sig: bool) -> List[Tuple[str, int, float]]:
        """(term, doc_count, raw_score) per bucket — significance score
        when available, popularity (doc_count) otherwise."""
        out = []
        for b in buckets:
            count = int(b["doc_count"])
            score = float(b.get("score", count)) if use_sig \
                else float(count)
            out.append((str(b["key"]), count, score))
        return out

    @staticmethod
    def _wave_normalize(wave):
        """[(field, term, count, score)] -> same with scores divided by
        the wave's best (the reference normalizes per wave so weights
        compose across hops)."""
        best = max((s for *_rest, s in wave), default=0.0)
        if best <= 0:
            return [(f, t, c, 1.0) for f, t, c, _s in wave]
        return [(f, t, c, s / best) for f, t, c, s in wave]
