"""Monitoring: ship cluster/node/index metrics into monitoring indices.

Reference: `x-pack/plugin/monitoring` (8.2k LoC) — `MonitoringService`
schedules `Collector`s (ClusterStatsCollector, NodeStatsCollector,
IndexStatsCollector, …) on `xpack.monitoring.collection.interval`; the
resulting `MonitoringDoc`s are written by the local exporter into
`.monitoring-es-7-{date}` daily indices; external agents POST documents
through `/_monitoring/bulk`.

Here collection is an explicit `collect()` tick (the scheduler analog —
tests/ops call it; a production deployment would timer-drive it), writing
the same doc shapes into the same daily-index naming.
"""

from __future__ import annotations

import datetime as _dt
import resource
from typing import List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentError


def _today_index() -> str:
    return ".monitoring-es-7-" + _dt.datetime.now(
        _dt.timezone.utc).strftime("%Y.%m.%d")


class MonitoringService:
    def __init__(self, node):
        from elasticsearch_tpu.common.settings import setting_bool
        self.node = node
        self.collection_enabled = setting_bool(
            node.settings.get("xpack.monitoring.collection.enabled"), True)
        self.collected = 0

    # ------------------------------------------------------------ collectors
    def _cluster_stats_doc(self) -> dict:
        n = self.node
        total_docs = sum(s.doc_count() for s in n.indices.indices.values())
        return {"type": "cluster_stats",
                "cluster_stats": {
                    "indices": {"count": len(n.indices.indices),
                                "docs": {"count": total_docs}},
                    "nodes": {"count": {"total": 1}}},
                "license": {"status": "active", "type": "basic"},
                "version": 1}

    def _node_stats_doc(self) -> dict:
        n = self.node
        usage = resource.getrusage(resource.RUSAGE_SELF)
        return {"type": "node_stats",
                "node_stats": {
                    "node_id": n.node_id,
                    "indices": {
                        "docs": {"count": sum(
                            s.doc_count()
                            for s in n.indices.indices.values())},
                        "search": {"query_total":
                                   n.counters.get("search", 0)},
                        "indexing": {"index_total":
                                     n.counters.get("index", 0)}},
                    "jvm": {"mem": {"heap_used_in_bytes":
                                    usage.ru_maxrss * 1024}},
                    "process": {"cpu": {"percent": 0}}}}

    def _index_stats_docs(self) -> List[dict]:
        out = []
        for name, svc in self.node.indices.indices.items():
            if name.startswith(".monitoring-"):
                continue
            out.append({"type": "index_stats",
                        "index_stats": {
                            "index": name,
                            "docs": {"count": svc.doc_count()},
                            "primaries": {"docs": {"count":
                                                   svc.doc_count()}}}})
        return out

    # ----------------------------------------------------------------- tick
    def collect(self) -> dict:
        """One collection interval (reference: MonitoringService.execute)."""
        if not self.collection_enabled:
            return {"collected": 0, "enabled": False}
        docs = [self._cluster_stats_doc(), self._node_stats_doc()]
        docs.extend(self._index_stats_docs())
        index = _today_index()
        ts = _dt.datetime.now(_dt.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%S.000Z")
        for doc in docs:
            doc.update({"cluster_uuid": self.node.node_id,
                        "timestamp": ts,
                        "interval_ms": 10000,
                        "source_node": {"uuid": self.node.node_id,
                                        "name": self.node.node_name}})
            self.node.index_doc(index, None, doc)
        if self.node.indices.exists(index):
            self.node.indices.get(index).refresh()
        self.collected += len(docs)
        return {"collected": len(docs), "enabled": True, "index": index}

    # ------------------------------------------------------- /_monitoring/bulk
    def bulk(self, system_id: Optional[str], lines: List[dict]) -> dict:
        """External agents ship docs (reference: RestMonitoringBulkAction —
        alternating metadata/doc lines like _bulk)."""
        if not system_id:
            raise IllegalArgumentError(
                "no [system_id] for monitoring bulk request")
        index = _today_index()
        ts = _dt.datetime.now(_dt.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%S.000Z")
        ignored = 0
        count = 0
        # strict meta/doc pairing: a bad metadata line drops its doc too,
        # never shifting the pairing frame (reference:
        # RestMonitoringBulkAction skips the pair)
        for j in range(0, len(lines) - len(lines) % 2, 2):
            meta, payload = lines[j], lines[j + 1]
            if not isinstance(meta, dict) \
                    or not isinstance(meta.get("index"), dict) \
                    or not isinstance(payload, dict):
                ignored += 1
                continue
            doc = dict(payload)
            doc.setdefault("timestamp", ts)
            doc["cluster_uuid"] = self.node.node_id
            doc["type"] = meta["index"].get("_type", system_id)
            self.node.index_doc(index, None, doc)
            count += 1
        if len(lines) % 2:
            ignored += 1  # trailing unpaired line
        if count and self.node.indices.exists(index):
            self.node.indices.get(index).refresh()
        return {"took": 0, "ignored": ignored > 0, "errors": False,
                "indexed": count}
