"""EQL: event query language — event queries + sequences.

Reference: `x-pack/plugin/eql` (10.5k LoC; shares the `ql/` frontend with
SQL). Grammar subset:

    <category> where <condition>
    sequence [by <field>] [with maxspan=<time>]
      [ <category> where <cond> ] [by <field>]
      [ <category> where <cond> ] [by <field>]
      ...

Conditions: ==, !=, <, <=, >, >=, and/or/not, `in (...)`, `like "pat*"`,
wildcard(field, "pat*"), field == "literal". Event queries fold into bool
DSL filters (category term + condition), executed timestamp-ordered;
sequence matching is the host-side state machine the reference runs in
`eql/execution/sequence/` (TumblingWindow / SequenceMatcher), keyed by the
join field.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import ParsingError
from elasticsearch_tpu.common.settings import parse_time_value

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<number>\d+\.\d+|\d+)
    | (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
    | (?P<ident>[A-Za-z_][A-Za-z0-9_.]*)
    | (?P<op>==|!=|<=|>=|<|>|\[|\]|\(|\)|,|=)
    )""", re.VERBOSE)

_KEYWORDS = {"where", "and", "or", "not", "in", "like", "sequence", "by",
             "with", "maxspan", "true", "false", "null", "any", "until"}


class _Tok:
    def __init__(self, kind, value):
        self.kind = kind
        self.value = value


def _lex(text: str) -> List[_Tok]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None or m.end() == pos:
            if text[pos:].strip():
                raise ParsingError(f"EQL lexing error at: {text[pos:pos+20]!r}")
            break
        pos = m.end()
        if m.group("number") is not None:
            t = m.group("number")
            out.append(_Tok("number", float(t) if "." in t else int(t)))
        elif m.group("string") is not None:
            raw = m.group("string")[1:-1]
            out.append(_Tok("string", raw.replace('\\"', '"').replace("\\'", "'")))
        elif m.group("ident") is not None:
            w = m.group("ident")
            out.append(_Tok("kw", w.lower()) if w.lower() in _KEYWORDS
                       else _Tok("ident", w))
        else:
            out.append(_Tok("op", m.group("op")))
    out.append(_Tok("eof", None))
    return out


class EventQuery:
    def __init__(self, category: Optional[str], condition: Optional[Any],
                 join_field: Optional[str] = None):
        self.category = category        # None == `any`
        self.condition = condition
        self.join_field = join_field    # per-step `by`


class EqlPlan:
    def __init__(self):
        self.mode = "event"             # event | sequence
        self.events: List[EventQuery] = []
        self.by: Optional[str] = None   # global join key
        self.maxspan_s: Optional[float] = None


class _Parser:
    def __init__(self, toks):
        self.toks = toks
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws):
        if self.peek().kind == "kw" and self.peek().value in kws:
            return self.next().value
        return None

    def accept_op(self, op):
        if self.peek().kind == "op" and self.peek().value == op:
            self.next()
            return True
        return False

    def parse(self) -> EqlPlan:
        plan = EqlPlan()
        if self.accept_kw("sequence"):
            plan.mode = "sequence"
            if self.accept_kw("by"):
                plan.by = self._ident()
            if self.accept_kw("with"):
                if not self.accept_kw("maxspan"):
                    raise ParsingError("expected maxspan after WITH")
                if not self.accept_op("="):
                    raise ParsingError("expected = after maxspan")
                t = self.next()
                # maxspan value may lex as number+ident (10 s) or ident (10s)
                if t.kind == "number" and self.peek().kind == "ident":
                    unit = self.next().value
                    plan.maxspan_s = parse_time_value(f"{t.value}{unit}", "maxspan")
                elif t.kind == "ident":
                    plan.maxspan_s = parse_time_value(t.value, "maxspan")
                else:
                    plan.maxspan_s = float(t.value)
            while self.accept_op("["):
                ev = self._event_query(terminator="]")
                if not self.accept_op("]"):
                    raise ParsingError("expected ] to close sequence step")
                if self.accept_kw("by"):
                    ev.join_field = self._ident()
                plan.events.append(ev)
            if len(plan.events) < 2:
                raise ParsingError("sequence requires at least two steps")
        else:
            plan.events.append(self._event_query(terminator=None))
        if self.peek().kind != "eof":
            raise ParsingError(f"unexpected trailing input [{self.peek().value}]")
        return plan

    def _ident(self) -> str:
        t = self.next()
        if t.kind != "ident":
            raise ParsingError(f"expected identifier, got [{t.value}]")
        return t.value

    def _event_query(self, terminator) -> EventQuery:
        t = self.next()
        if t.kind == "kw" and t.value == "any":
            category = None
        elif t.kind in ("ident", "string"):
            category = t.value
        else:
            raise ParsingError(f"expected event category, got [{t.value}]")
        if not self.accept_kw("where"):
            raise ParsingError("expected WHERE after event category")
        if self.accept_kw("true"):
            return EventQuery(category, None)
        return EventQuery(category, self._expr())

    def _expr(self):
        left = self._and()
        while self.accept_kw("or"):
            left = ("or", left, self._and())
        return left

    def _and(self):
        left = self._not()
        while self.accept_kw("and"):
            left = ("and", left, self._not())
        return left

    def _not(self):
        if self.accept_kw("not"):
            return ("not", self._not())
        if self.accept_op("("):
            e = self._expr()
            if not self.accept_op(")"):
                raise ParsingError("expected )")
            return e
        return self._predicate()

    def _predicate(self):
        t = self.next()
        if t.kind == "ident" and t.value == "wildcard" and self.accept_op("("):
            field = self._ident()
            if not self.accept_op(","):
                raise ParsingError("wildcard(field, pattern)")
            pat = self.next().value
            if not self.accept_op(")"):
                raise ParsingError("expected )")
            return ("like", field, pat)
        if t.kind != "ident":
            raise ParsingError(f"expected field name, got [{t.value}]")
        field = t.value
        if self.accept_kw("like"):
            pat = self.next().value
            return ("like", field, pat)
        if self.accept_kw("in"):
            if not self.accept_op("("):
                raise ParsingError("IN expects (...)")
            vals = [self.next().value]
            while self.accept_op(","):
                vals.append(self.next().value)
            if not self.accept_op(")"):
                raise ParsingError("expected )")
            return ("in", field, vals)
        op_tok = self.next()
        if op_tok.kind != "op" or op_tok.value not in (
                "==", "!=", "<", "<=", ">", ">="):
            raise ParsingError(f"expected comparison, got [{op_tok.value}]")
        v = self.next()
        if v.kind == "kw" and v.value in ("true", "false"):
            value: Any = v.value == "true"
        elif v.kind == "kw" and v.value == "null":
            value = None
        elif v.kind in ("number", "string", "ident"):
            value = v.value
        else:
            raise ParsingError(f"expected literal, got [{v.value}]")
        return ("cmp", op_tok.value, field, value)


def parse_eql(text: str) -> EqlPlan:
    return _Parser(_lex(text)).parse()


# -- condition → query DSL ---------------------------------------------------

def _ident_resolver(field: str) -> str:
    return field


def condition_to_dsl(expr, exact=_ident_resolver) -> dict:
    kind = expr[0]
    if kind == "and":
        return {"bool": {"must": [condition_to_dsl(expr[1], exact),
                                  condition_to_dsl(expr[2], exact)]}}
    if kind == "or":
        return {"bool": {"should": [condition_to_dsl(expr[1], exact),
                                    condition_to_dsl(expr[2], exact)],
                         "minimum_should_match": 1}}
    if kind == "not":
        return {"bool": {"must_not": [condition_to_dsl(expr[1], exact)]}}
    if kind == "like":
        return {"wildcard": {exact(expr[1]): {"value": expr[2]}}}
    if kind == "in":
        return {"terms": {exact(expr[1]): expr[2]}}
    if kind == "cmp":
        op, field, value = expr[1], expr[2], expr[3]
        if op == "==":
            if value is None:
                return {"bool": {"must_not": [{"exists": {"field": field}}]}}
            if isinstance(value, str):
                field = exact(field)
            return {"term": {field: {"value": value}}}
        if op == "!=":
            if value is None:
                return {"exists": {"field": field}}
            if isinstance(value, str):
                field = exact(field)
            return {"bool": {"must_not": [{"term": {field: {"value": value}}}]}}
        range_op = {"<": "lt", "<=": "lte", ">": "gt", ">=": "gte"}[op]
        return {"range": {field: {range_op: value}}}
    raise ParsingError(f"unsupported EQL construct [{kind}]")


def event_to_dsl(ev: EventQuery, category_field: str,
                 exact=_ident_resolver) -> dict:
    filters = []
    if ev.category is not None:
        filters.append({"term": {exact(category_field): {"value": ev.category}}})
    if ev.condition is not None:
        filters.append(condition_to_dsl(ev.condition, exact))
    if not filters:
        return {"match_all": {}}
    return {"bool": {"filter": filters}}


# -- execution ---------------------------------------------------------------

class EqlEngine:
    def __init__(self, node):
        self.node = node

    def _exact(self, index: str):
        """Field → exact-match field (`.keyword` subfield for text), same
        resolution SQL uses — the shared `ql/` frontend in the reference."""
        defs: Dict[str, dict] = {}
        try:
            services = self.node.indices.resolve(index)
        except Exception:
            services = []
        for svc in services:
            def walk(props, prefix=""):
                for fname, fdef in props.items():
                    full = prefix + fname
                    if "properties" in fdef:
                        walk(fdef["properties"], full + ".")
                    else:
                        defs[full] = fdef
            walk(svc.mapper_service.to_dict().get("properties", {}))

        def resolve(field: str) -> str:
            d = defs.get(field)
            if d is not None and d.get("type") == "text" and \
                    "keyword" in d.get("fields", {}):
                return field + ".keyword"
            return field
        return resolve

    def search(self, index: str, body: dict) -> dict:
        plan = parse_eql(body.get("query", ""))
        category_field = body.get("event_category_field", "event.category")
        ts_field = body.get("timestamp_field", "@timestamp")
        size = int(body.get("size", 10))
        fetch_size = int(body.get("fetch_size", 1000))
        exact = self._exact(index)
        if plan.mode == "event":
            dsl = event_to_dsl(plan.events[0], category_field, exact)
            if body.get("filter"):
                dsl = {"bool": {"must": [dsl], "filter": [body["filter"]]}}
            result = self.node.search(index, {
                "query": dsl, "size": size,
                "sort": [{ts_field: {"order": "asc"}}]})
            events = [self._event(h) for h in result["hits"]["hits"]]
            return {"is_partial": False, "is_running": False,
                    "took": result.get("took", 0), "timed_out": False,
                    "hits": {"total": result["hits"]["total"],
                             "events": events}}
        # sequence: fetch each step's matching events time-ordered, then run
        # the state machine over the merged stream
        step_events: List[List[dict]] = []
        for ev in plan.events:
            dsl = event_to_dsl(ev, category_field, exact)
            result = self.node.search(index, {
                "query": dsl, "size": fetch_size,
                "sort": [{ts_field: {"order": "asc"}}]})
            step_events.append(result["hits"]["hits"])
        sequences = self._match_sequences(plan, step_events, ts_field, size)
        return {"is_partial": False, "is_running": False, "took": 0,
                "timed_out": False,
                "hits": {"total": {"value": len(sequences), "relation": "eq"},
                         "sequences": sequences}}

    def _event(self, hit: dict) -> dict:
        return {"_index": hit["_index"], "_id": hit["_id"],
                "_source": hit.get("_source", {})}

    def _match_sequences(self, plan: EqlPlan, step_events: List[List[dict]],
                         ts_field: str, size: int) -> List[dict]:
        def ts(h):
            v = _get_dotted(h.get("_source", {}), ts_field)
            if isinstance(v, str):
                from elasticsearch_tpu.index.mapping import parse_date_millis
                return parse_date_millis(v)
            return v if v is not None else 0

        def join_key(h, step_idx):
            field = plan.events[step_idx].join_field or plan.by
            if field is None:
                return "__all__"
            return str(_get_dotted(h.get("_source", {}), field))

        # merged time-ordered stream of (ts, step, hit)
        stream: List[Tuple[Any, int, dict]] = []
        for step, hits in enumerate(step_events):
            for h in hits:
                stream.append((ts(h), step, h))
        stream.sort(key=lambda x: x[0])

        n_steps = len(plan.events)
        # per join key: list of partial sequences, each = list of hits so far
        partial: Dict[str, List[List[Tuple[Any, dict]]]] = {}
        done: List[dict] = []
        maxspan_ms = plan.maxspan_s * 1000 if plan.maxspan_s else None
        for t, step, h in stream:
            key = join_key(h, step)
            partial.setdefault(key, [])
            if step == 0:
                partial[key].append([(t, h)])
                continue
            # extend the oldest partial waiting at step-1 (reference semantics:
            # each stage consumes the earliest in-progress sequence)
            for seq in partial[key]:
                if len(seq) != step:
                    continue
                if maxspan_ms is not None and t - seq[0][0] > maxspan_ms:
                    continue
                if t < seq[-1][0]:
                    continue
                seq.append((t, h))
                if len(seq) == n_steps:
                    done.append({
                        "join_keys": [] if key == "__all__" else [key],
                        "events": [self._event(hit) for _, hit in seq]})
                    partial[key].remove(seq)
                    if len(done) >= size:
                        return done
                break
        return done


def _get_dotted(src: dict, path: str):
    cur: Any = src
    for p in path.split("."):
        if not isinstance(cur, dict) or p not in cur:
            return None
        cur = cur[p]
    return cur
