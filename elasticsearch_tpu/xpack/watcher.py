"""Watcher: scheduled alerting — triggers → input → condition → actions.

Reference: `x-pack/plugin/watcher` (25k LoC) — a watch is
trigger/input/condition/actions (`Watch.java`); `ExecutionService` runs due
watches, records history, honors acks + throttle periods. Tick-driven here
(`run_once(now_ms)`) like ILM — the reference's `TickerScheduleTriggerEngine`
fires the same way off a periodic ticker thread.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError,
    ResourceNotFoundError,
    ValidationError,
)
from elasticsearch_tpu.common.settings import parse_time_value


def _get_path(obj: Any, dotted: str) -> Any:
    cur = obj
    for part in dotted.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        elif isinstance(cur, list) and part.isdigit() and int(part) < len(cur):
            cur = cur[int(part)]
        else:
            return None
    return cur


def _render_templates(obj: Any, ctx: dict) -> Any:
    """Render {{ctx.*}} mustache placeholders anywhere in an action/input
    definition (reference: TextTemplateEngine applied across watch parts)."""
    from elasticsearch_tpu.script import mustache
    if isinstance(obj, str):
        if "{{" in obj:
            return mustache.render(obj, ctx)
        return obj
    if isinstance(obj, dict):
        return {k: _render_templates(v, ctx) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_render_templates(v, ctx) for v in obj]
    return obj


class WatcherService:
    def __init__(self, node):
        self.node = node
        self.watches: Dict[str, dict] = {}
        self.state: Dict[str, dict] = {}      # id -> runtime state
        self.history: List[dict] = []
        self.running = True

    # -- CRUD -----------------------------------------------------------------
    @staticmethod
    def validate_watch(body: dict) -> None:
        for part in ("trigger", "actions"):
            if part not in body:
                raise ValidationError(f"watch must define [{part}]")

    def put_watch(self, watch_id: str, body: dict, active: bool = True) -> dict:
        self.validate_watch(body)
        created = watch_id not in self.watches
        self.watches[watch_id] = body
        self.state[watch_id] = {
            "active": active, "last_checked": None, "last_met": None,
            "acked": {}, "last_executed": {},
            "version": self.state.get(watch_id, {}).get("version", 0) + 1,
        }
        return {"_id": watch_id, "created": created,
                "_version": self.state[watch_id]["version"]}

    def get_watch(self, watch_id: str) -> dict:
        if watch_id not in self.watches:
            raise ResourceNotFoundError(f"watch [{watch_id}] not found")
        st = self.state[watch_id]
        return {"found": True, "_id": watch_id, "watch": self.watches[watch_id],
                "status": {"state": {"active": st["active"]},
                           "actions": {a: {"ack": {"state":
                                           "acked" if a in st["acked"] else "awaits_successful_execution"}}
                                       for a in self.watches[watch_id].get("actions", {})},
                           "version": st["version"]}}

    def delete_watch(self, watch_id: str) -> None:
        if watch_id not in self.watches:
            raise ResourceNotFoundError(f"watch [{watch_id}] not found")
        del self.watches[watch_id]
        self.state.pop(watch_id, None)

    def set_active(self, watch_id: str, active: bool) -> None:
        if watch_id not in self.watches:
            raise ResourceNotFoundError(f"watch [{watch_id}] not found")
        self.state[watch_id]["active"] = active

    def ack(self, watch_id: str, action_ids: Optional[List[str]] = None) -> None:
        if watch_id not in self.watches:
            raise ResourceNotFoundError(f"watch [{watch_id}] not found")
        actions = self.watches[watch_id].get("actions", {})
        for a in (action_ids or list(actions)):
            self.state[watch_id]["acked"][a] = time.time()

    # -- execution ------------------------------------------------------------
    def _interval_s(self, watch: dict) -> Optional[float]:
        sched = watch.get("trigger", {}).get("schedule", {})
        if "interval" in sched:
            return parse_time_value(sched["interval"], "interval")
        # cron/hourly/daily schedules fire whenever ticked (tests drive ticks)
        return None

    def run_once(self, now_ms: Optional[int] = None) -> List[dict]:
        """One scheduler tick: execute every due active watch."""
        if not self.running:
            return []
        now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
        results = []
        for wid in list(self.watches):
            st = self.state[wid]
            if not st["active"]:
                continue
            interval = self._interval_s(self.watches[wid])
            if interval is not None and st["last_checked"] is not None and \
                    now_ms - st["last_checked"] < interval * 1000:
                continue
            results.append(self.execute(wid, now_ms=now_ms))
        return results

    def execute(self, watch_id: str, now_ms: Optional[int] = None,
                trigger_data: Optional[dict] = None,
                record_execution: bool = True,
                alternative_input: Optional[dict] = None) -> dict:
        if watch_id not in self.watches:
            raise ResourceNotFoundError(f"watch [{watch_id}] not found")
        watch = self.watches[watch_id]
        st = self.state[watch_id]
        now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
        st["last_checked"] = now_ms
        payload = (alternative_input if alternative_input is not None
                   else self._run_input(watch.get("input", {"none": {}})))
        ctx = {"ctx": {"watch_id": watch_id, "payload": payload,
                       "execution_time": now_ms,
                       "trigger": trigger_data or {}}}
        met = self._check_condition(watch.get("condition", {"always": {}}), ctx)
        record = {"watch_id": watch_id, "state": "executed" if met else
                  "execution_not_needed", "condition_met": met,
                  "timestamp": now_ms, "actions": []}
        if met:
            st["last_met"] = now_ms
            throttle_s = parse_time_value(
                watch.get("throttle_period", "0s"), "throttle_period")
            for name, action in watch.get("actions", {}).items():
                if name in st["acked"]:
                    record["actions"].append({"id": name, "status": "acked"})
                    continue
                last = st["last_executed"].get(name)
                if throttle_s and last is not None and \
                        now_ms - last < throttle_s * 1000:
                    record["actions"].append({"id": name, "status": "throttled"})
                    continue
                status = self._run_action(name, action, ctx)
                st["last_executed"][name] = now_ms
                record["actions"].append(status)
        else:
            # condition went false → acks reset (reference ack semantics)
            st["acked"].clear()
        if record_execution:
            self.history.append(record)
            if len(self.history) > 10_000:
                del self.history[:5_000]
        return record

    def _run_input(self, input_def: dict) -> dict:
        if "search" in input_def:
            request = input_def["search"].get("request", {})
            indices = request.get("indices", ["*"])
            if isinstance(indices, str):
                indices = [indices]
            body = request.get("body", {})
            result = self.node.search(",".join(indices), body)
            return result
        if "simple" in input_def:
            return dict(input_def["simple"])
        if "http" in input_def:
            # no egress in this environment; record the intent
            return {"_http_input_skipped": True}
        return {}

    def _check_condition(self, cond: dict, ctx: dict) -> bool:
        if "always" in cond:
            return True
        if "never" in cond:
            return False
        if "compare" in cond:
            for path, check in cond["compare"].items():
                value = _get_path(ctx, path)
                for op, expected in check.items():
                    if not _compare(op, value, expected):
                        return False
            return True
        if "array_compare" in cond:
            for path, spec in cond["array_compare"].items():
                arr = _get_path(ctx, path) or []
                sub = spec.get("path", "")
                for op, rule in ((k, v) for k, v in spec.items() if k != "path"):
                    quantifier = rule.get("quantifier", "some")
                    expected = rule.get("value")
                    hits = [
                        _compare(op, _get_path(item, sub) if sub else item,
                                 expected) for item in arr]
                    ok = all(hits) if quantifier == "all" else any(hits)
                    if not ok:
                        return False
            return True
        if "script" in cond:
            return self._script_condition(cond["script"], ctx)
        raise IllegalArgumentError(f"unknown condition type {list(cond)}")

    def _script_condition(self, spec, ctx: dict) -> bool:
        import ast
        resolved = self.node.scripts.resolve(spec)
        source = resolved["source"]
        params = resolved["params"]
        tree = ast.parse(source, mode="eval")
        env = {"ctx": ctx["ctx"], "params": params}

        def ev(node):
            if isinstance(node, ast.Expression):
                return ev(node.body)
            if isinstance(node, ast.Constant):
                return node.value
            if isinstance(node, ast.Name):
                if node.id in env:
                    return env[node.id]
                raise IllegalArgumentError(f"unknown variable [{node.id}]")
            if isinstance(node, ast.Attribute):
                base = ev(node.value)
                if isinstance(base, dict) and node.attr in base:
                    return base[node.attr]
                return None
            if isinstance(node, ast.Subscript):
                base = ev(node.value)
                key = ev(node.slice)
                try:
                    return base[key]
                except Exception:
                    return None
            if isinstance(node, ast.Compare):
                left = ev(node.left)
                ok = True
                for op, comp in zip(node.ops, node.comparators):
                    right = ev(comp)
                    ops = {ast.Eq: lambda a, b: a == b,
                           ast.NotEq: lambda a, b: a != b,
                           ast.Lt: lambda a, b: a < b,
                           ast.LtE: lambda a, b: a <= b,
                           ast.Gt: lambda a, b: a > b,
                           ast.GtE: lambda a, b: a >= b}
                    try:
                        ok = ok and ops[type(op)](left, right)
                    except TypeError:
                        return False
                    left = right
                return ok
            if isinstance(node, ast.BoolOp):
                vals = [ev(v) for v in node.values]
                return all(vals) if isinstance(node.op, ast.And) else any(vals)
            if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
                return not ev(node.operand)
            if isinstance(node, ast.BinOp):
                import operator as _op
                ops = {ast.Add: _op.add, ast.Sub: _op.sub, ast.Mult: _op.mul,
                       ast.Div: _op.truediv, ast.Mod: _op.mod}
                return ops[type(node.op)](ev(node.left), ev(node.right))
            raise IllegalArgumentError(
                f"script condition construct [{type(node).__name__}] not allowed")

        return bool(ev(tree))

    def _run_action(self, name: str, action: dict, ctx: dict) -> dict:
        rendered = _render_templates(action, ctx)
        if "logging" in rendered:
            text = rendered["logging"].get("text", "")
            return {"id": name, "type": "logging", "status": "success",
                    "logging": {"logged_text": text}}
        if "index" in rendered:
            spec = rendered["index"]
            doc = ctx["ctx"]["payload"]
            if "_doc" in spec:
                doc = spec["_doc"]
            result = self.node.index_doc(spec["index"], spec.get("doc_id"), doc)
            return {"id": name, "type": "index", "status": "success",
                    "index": {"response": {"index": spec["index"],
                                           "result": result.get("result",
                                                                "created")}}}
        if "webhook" in rendered:
            # zero-egress environment: record, don't send
            return {"id": name, "type": "webhook", "status": "simulated",
                    "webhook": {"request": rendered["webhook"]}}
        if "email" in rendered:
            return {"id": name, "type": "email", "status": "simulated"}
        return {"id": name, "type": "unknown", "status": "failure",
                "reason": f"unsupported action {list(action)}"}

    def stats(self) -> dict:
        return {"watcher_state": "started" if self.running else "stopped",
                "watch_count": len(self.watches),
                "execution_history_count": len(self.history)}


def _compare(op: str, value, expected) -> bool:
    try:
        if op == "eq":
            return value == expected
        if op == "not_eq":
            return value != expected
        if value is None:
            return False
        if op == "gt":
            return value > expected
        if op == "gte":
            return value >= expected
        if op == "lt":
            return value < expected
        if op == "lte":
            return value <= expected
    except TypeError:
        return False
    raise IllegalArgumentError(f"unknown compare operator [{op}]")
