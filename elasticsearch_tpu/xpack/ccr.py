"""Cross-cluster search (CCS) + cross-cluster replication (CCR).

Reference:
- CCS: `transport/RemoteClusterService.java` — remote clusters registered
  under `cluster.remote.{alias}` settings; `TransportSearchAction` splits
  `remote:index` expressions, fans out, and merges shard results.
- CCR: `x-pack/plugin/ccr` (9.4k LoC) — follower shards long-poll the
  leader's operation history (`ShardChangesAction.java:59`) above a
  checkpoint, guarded by retention leases; auto-follow patterns create
  followers for new leader indices (`AutoFollowCoordinator`).

Here a "remote cluster" is another Node reachable in-process (the analog of
the reference's in-JVM `InternalTestCluster` wiring — production would dial
the HTTP/RPC layer; the merge/checkpoint logic is identical either way).
Change-tailing reads docs above the follower's seq_no checkpoint from the
leader's readers, plus an id-level anti-join for deletes.
"""

from __future__ import annotations

import fnmatch
import time
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError,
    ResourceNotFoundError,
)


class RemoteClusterService:
    """alias → remote node registry (reference: RemoteClusterService)."""

    def __init__(self, node):
        self.node = node
        self.remotes: Dict[str, Any] = {}
        self.seeds: Dict[str, List[str]] = {}

    def register(self, alias: str, remote_node) -> None:
        self.remotes[alias] = remote_node
        self.seeds.setdefault(alias, [f"in-process:{id(remote_node):x}"])

    def unregister(self, alias: str) -> None:
        self.remotes.pop(alias, None)
        self.seeds.pop(alias, None)

    def get(self, alias: str):
        if alias not in self.remotes:
            raise ResourceNotFoundError(f"no such remote cluster: [{alias}]")
        return self.remotes[alias]

    def info(self) -> dict:
        return {alias: {"connected": alias in self.remotes,
                        "mode": "sniff",
                        "seeds": self.seeds.get(alias, []),
                        "num_nodes_connected": 1 if alias in self.remotes else 0}
                for alias in set(self.remotes) | set(self.seeds)}

    # -- CCS ------------------------------------------------------------------
    @staticmethod
    def split_indices(index_expr: Optional[str]) -> Tuple[Optional[str],
                                                          Dict[str, str]]:
        """'l1,r:idx,r:idx2' → ('l1', {'r': 'idx,idx2'}). A lone '*:*'-style
        remote part groups by alias like GroupShardsIterator building."""
        if not index_expr:
            return index_expr, {}
        local_parts: List[str] = []
        remote_parts: Dict[str, List[str]] = {}
        for part in index_expr.split(","):
            if ":" in part:
                alias, _, idx = part.partition(":")
                remote_parts.setdefault(alias, []).append(idx)
            else:
                local_parts.append(part)
        return (",".join(local_parts) if local_parts else None,
                {a: ",".join(ps) for a, ps in remote_parts.items()})

    def search_remotes(self, remote_exprs: Dict[str, str],
                       body: dict) -> List[dict]:
        """Run the query on each remote; return per-cluster responses with
        hits re-labelled `alias:index` like the reference's CCS merge."""
        responses = []
        for alias, expr in remote_exprs.items():
            remote = self.get(alias)
            resp = remote.search(expr, body)
            for h in resp.get("hits", {}).get("hits", []):
                h["_index"] = f"{alias}:{h['_index']}"
            responses.append(resp)
        return responses


def merge_ccs_responses(local: Optional[dict], remotes: List[dict],
                        body: dict) -> dict:
    """Merge coordinator-side: concatenate hit lists, re-sort by score (or
    sort values), recompute totals (reference: SearchResponseMerger)."""
    responses = ([local] if local else []) + remotes
    if not responses:
        return {"hits": {"total": {"value": 0, "relation": "eq"},
                         "hits": [], "max_score": None}}
    if len(responses) == 1:
        return responses[0]
    size = int((body or {}).get("size", 10))
    all_hits = []
    total = 0
    relation = "eq"
    took = 0
    for r in responses:
        h = r.get("hits", {})
        all_hits.extend(h.get("hits", []))
        total += h.get("total", {}).get("value", 0)
        if h.get("total", {}).get("relation") == "gte":
            relation = "gte"
        took = max(took, r.get("took", 0))
    if (body or {}).get("sort"):
        # trust per-response sort ordering; merge by sort values
        def key(h):
            sv = h.get("sort", [])
            return tuple(sv)
        try:
            all_hits.sort(key=key)
        except TypeError:
            pass
    else:
        all_hits.sort(key=lambda h: -(h.get("_score") or 0.0))
    all_hits = all_hits[:size]
    max_score = max((h.get("_score") or 0.0 for h in all_hits), default=None)
    merged = {
        "took": took, "timed_out": False,
        "_shards": {"total": sum(r.get("_shards", {}).get("total", 0)
                                 for r in responses),
                    "successful": sum(r.get("_shards", {}).get("successful", 0)
                                      for r in responses),
                    "skipped": 0, "failed": 0},
        "_clusters": {"total": len(responses), "successful": len(responses),
                      "skipped": 0},
        "hits": {"total": {"value": total, "relation": relation},
                 "max_score": max_score, "hits": all_hits},
    }
    # aggregations merge across clusters needs the full reduce tree; only
    # single-source agg responses pass through (reference merges via
    # InternalAggregation.reduce — multi-cluster agg reduce is future work)
    agg_sources = [r for r in responses if r.get("aggregations")]
    if len(agg_sources) == 1:
        merged["aggregations"] = agg_sources[0]["aggregations"]
    return merged


# ---------------------------------------------------------------------------
# CCR
# ---------------------------------------------------------------------------

class CcrService:
    def __init__(self, node):
        self.node = node
        # follower index -> config + replication state
        self.followers: Dict[str, dict] = {}
        self.auto_follow: Dict[str, dict] = {}

    # -- follow lifecycle -----------------------------------------------------
    def follow(self, follower_index: str, body: dict) -> dict:
        remote = body.get("remote_cluster")
        leader = body.get("leader_index")
        if not remote or not leader:
            raise IllegalArgumentError(
                "follow requires [remote_cluster] and [leader_index]")
        leader_node = self.node.remotes.get(remote)
        leader_svc = leader_node.indices.get(leader)
        if not self.node.indices.exists(follower_index):
            self.node.indices.create_index(
                follower_index,
                settings=body.get("settings"),
                mappings=leader_svc.mapper_service.to_dict())
        self.followers[follower_index] = {
            "remote_cluster": remote, "leader_index": leader,
            "status": "active", "checkpoint": -1,
            "operations_written": 0, "last_poll": None,
        }
        self.poll(follower_index)
        return {"follow_index_created": True,
                "follow_index_shards_acked": True, "index_following_started": True}

    def pause(self, follower_index: str) -> None:
        self._follower(follower_index)["status"] = "paused"

    def resume(self, follower_index: str) -> None:
        self._follower(follower_index)["status"] = "active"
        self.poll(follower_index)

    def unfollow(self, follower_index: str) -> None:
        if self._follower(follower_index)["status"] != "paused":
            raise IllegalArgumentError(
                f"cannot convert follower [{follower_index}] to a normal "
                "index: pause following first")
        del self.followers[follower_index]

    def _follower(self, follower_index: str) -> dict:
        if follower_index not in self.followers:
            raise ResourceNotFoundError(
                f"follower index [{follower_index}] does not exist")
        return self.followers[follower_index]

    # -- replication ----------------------------------------------------------
    def poll(self, follower_index: str) -> dict:
        """One change-tailing round (reference: ShardChangesAction request
        above the follower checkpoint + applying ops via the follow task)."""
        cfg = self._follower(follower_index)
        if cfg["status"] != "active":
            return {"operations": 0}
        leader_node = self.node.remotes.get(cfg["remote_cluster"])
        leader_svc = leader_node.indices.get(cfg["leader_index"])
        leader_svc.refresh()
        reader = leader_svc.combined_reader()
        ops = 0
        leader_live_ids = set()
        max_seq = cfg["checkpoint"]
        for view in reader.views:
            seg = view.segment
            for local in range(seg.num_docs):
                if not view.live[local]:
                    continue
                leader_live_ids.add(seg.ids[local])
                seq = int(seg.seq_nos[local])
                if seq <= cfg["checkpoint"]:
                    continue
                self.node.index_doc(follower_index, seg.ids[local],
                                    seg.sources[local])
                ops += 1
                max_seq = max(max_seq, seq)
        # deletes: anti-join follower ids against leader live set
        follower_svc = self.node.indices.get(follower_index)
        follower_svc.refresh()
        freader = follower_svc.combined_reader()
        for view in freader.views:
            seg = view.segment
            for local in range(seg.num_docs):
                if not view.live[local]:
                    continue
                if seg.ids[local] not in leader_live_ids:
                    self.node.delete_doc(follower_index, seg.ids[local])
                    ops += 1
        follower_svc.refresh()
        cfg["checkpoint"] = max_seq
        cfg["operations_written"] += ops
        cfg["last_poll"] = time.time()
        return {"operations": ops}

    def run_once(self) -> dict:
        """Scheduler tick: poll all active followers + evaluate auto-follow."""
        results = {}
        for name in list(self.followers):
            if self.followers[name]["status"] == "active":
                results[name] = self.poll(name)["operations"]
        self._auto_follow_tick()
        return results

    # -- auto-follow ----------------------------------------------------------
    def put_auto_follow(self, name: str, body: dict) -> None:
        if not body.get("remote_cluster") or not body.get("leader_index_patterns"):
            raise IllegalArgumentError(
                "auto-follow requires [remote_cluster] and [leader_index_patterns]")
        self.auto_follow[name] = body

    def get_auto_follow(self, name: Optional[str] = None) -> dict:
        if name is None:
            return {"patterns": [{"name": n, "pattern": p}
                                 for n, p in self.auto_follow.items()]}
        if name not in self.auto_follow:
            raise ResourceNotFoundError(f"auto-follow pattern [{name}] missing")
        return {"patterns": [{"name": name, "pattern": self.auto_follow[name]}]}

    def delete_auto_follow(self, name: str) -> None:
        if name not in self.auto_follow:
            raise ResourceNotFoundError(f"auto-follow pattern [{name}] missing")
        del self.auto_follow[name]

    def _auto_follow_tick(self) -> None:
        for pat_name, pat in self.auto_follow.items():
            remote = pat["remote_cluster"]
            try:
                leader_node = self.node.remotes.get(remote)
            except ResourceNotFoundError:
                continue
            suffix = pat.get("follow_index_pattern", "{{leader_index}}")
            for leader_name in list(leader_node.indices.indices):
                if not any(fnmatch.fnmatchcase(leader_name, p)
                           for p in pat["leader_index_patterns"]):
                    continue
                follower_name = suffix.replace("{{leader_index}}", leader_name)
                if follower_name in self.followers or \
                        self.node.indices.exists(follower_name):
                    continue
                self.follow(follower_name, {"remote_cluster": remote,
                                            "leader_index": leader_name})

    # -- stats ----------------------------------------------------------------
    def stats(self) -> dict:
        return {
            "auto_follow_stats": {
                "number_of_successful_follow_indices": len(self.followers)},
            "follow_stats": {"indices": [
                {"index": name,
                 "shards": [{"remote_cluster": cfg["remote_cluster"],
                             "leader_index": cfg["leader_index"],
                             "follower_index": name,
                             "follower_global_checkpoint": cfg["checkpoint"],
                             "operations_written": cfg["operations_written"]}]}
                for name, cfg in self.followers.items()]},
        }

    def follow_info(self, index_expr: str) -> dict:
        out = []
        for name, cfg in self.followers.items():
            if index_expr in ("_all", "*", name):
                out.append({"follower_index": name,
                            "remote_cluster": cfg["remote_cluster"],
                            "leader_index": cfg["leader_index"],
                            "status": cfg["status"]})
        return {"follower_indices": out}
