"""Cross-cluster search (CCS) + cross-cluster replication (CCR).

Reference:
- CCS: `transport/RemoteClusterService.java` — remote clusters registered
  under `cluster.remote.{alias}` settings; `TransportSearchAction` splits
  `remote:index` expressions, fans out, and merges shard results.
- CCR: `x-pack/plugin/ccr` (9.4k LoC) — follower shards long-poll the
  leader's operation history (`ShardChangesAction.java:59`) above a
  checkpoint, guarded by retention leases; auto-follow patterns create
  followers for new leader indices (`AutoFollowCoordinator`).

Remote clusters are reached through the adapter interface in
`xpack/remote_cluster.py`: `WireRemote` holds sniff-mode pooled
connections over the real binary transport (production; configured via
`cluster.remote.<alias>.seeds`), `InProcessRemote` wraps another Node in
this process (test clusters). CCR change-tailing and CCS merging are
identical over either.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError,
    ResourceNotFoundError,
)


class RemoteClusterService:
    """alias → remote cluster registry (reference: RemoteClusterService)."""

    def __init__(self, node):
        self.node = node
        self.remotes: Dict[str, Any] = {}

    def register(self, alias: str, remote_node) -> None:
        """In-process registration (test clusters)."""
        from elasticsearch_tpu.xpack.remote_cluster import InProcessRemote
        self.remotes[alias] = InProcessRemote(alias, remote_node)

    def configure(self, alias: str, seeds: List[str],
                  skip_unavailable: bool = False) -> None:
        """Wire registration from `cluster.remote.<alias>.*` settings:
        sniff-mode pooled connections over the binary transport."""
        from elasticsearch_tpu.xpack.remote_cluster import WireRemote
        old = self.remotes.pop(alias, None)
        if old is not None:
            old.close()
        self.remotes[alias] = WireRemote(
            alias, seeds, skip_unavailable=skip_unavailable)

    def apply_settings(self, flat: Dict[str, Any]) -> None:
        """Apply `cluster.remote.*` keys (boot settings or a
        _cluster/settings update). `seeds: null` removes the alias.
        Per-alias isolation: one malformed remote entry must not keep the
        others from registering."""
        import logging

        from elasticsearch_tpu.xpack.remote_cluster import (
            parse_remote_settings,
        )
        for alias, cfg in parse_remote_settings(flat).items():
            try:
                if "seeds" in cfg and cfg["seeds"] is None:
                    self.unregister(alias)
                    continue
                existing = self.remotes.get(alias)
                if "seeds" in cfg:
                    self.configure(alias, cfg["seeds"],
                                   skip_unavailable=cfg.get(
                                       "skip_unavailable",
                                       getattr(existing, "skip_unavailable",
                                               False)))
                elif existing is not None and "skip_unavailable" in cfg:
                    existing.skip_unavailable = cfg["skip_unavailable"]
            except Exception:  # noqa: BLE001
                logging.getLogger("elasticsearch_tpu.remote_cluster").warning(
                    "failed to configure remote cluster [%s]", alias,
                    exc_info=True)

    def unregister(self, alias: str) -> None:
        old = self.remotes.pop(alias, None)
        if old is not None:
            old.close()

    def get(self, alias: str):
        if alias not in self.remotes:
            raise ResourceNotFoundError(f"no such remote cluster: [{alias}]")
        return self.remotes[alias]

    def info(self) -> dict:
        return {alias: remote.info_entry()
                for alias, remote in self.remotes.items()}

    # -- CCS ------------------------------------------------------------------
    @staticmethod
    def split_indices(index_expr: Optional[str]) -> Tuple[Optional[str],
                                                          Dict[str, str]]:
        """'l1,r:idx,r:idx2' → ('l1', {'r': 'idx,idx2'}). A lone '*:*'-style
        remote part groups by alias like GroupShardsIterator building."""
        if not index_expr:
            return index_expr, {}
        local_parts: List[str] = []
        remote_parts: Dict[str, List[str]] = {}
        for part in index_expr.split(","):
            if ":" in part:
                alias, _, idx = part.partition(":")
                remote_parts.setdefault(alias, []).append(idx)
            else:
                local_parts.append(part)
        return (",".join(local_parts) if local_parts else None,
                {a: ",".join(ps) for a, ps in remote_parts.items()})

    def search_remotes(self, remote_exprs: Dict[str, str],
                       body: dict) -> Tuple[List[dict], dict]:
        """Run the query on each remote (ccs_minimize_roundtrips shape:
        one request per cluster); returns (responses, clusters_meta) with
        hits re-labelled `alias:index` like the reference's CCS merge.

        `skip_unavailable: true` clusters that fail are SKIPPED (counted
        in `_clusters.skipped`); others fail the whole search
        (RemoteClusterService.java `skip_unavailable` contract)."""
        responses = []
        clusters = {"total": len(remote_exprs), "successful": 0,
                    "skipped": 0}
        for alias, expr in remote_exprs.items():
            remote = self.get(alias)
            try:
                resp = remote.search(expr, body)
            except Exception:  # noqa: BLE001 — connectivity or remote error
                if getattr(remote, "skip_unavailable", False):
                    clusters["skipped"] += 1
                    continue
                raise
            clusters["successful"] += 1
            for h in resp.get("hits", {}).get("hits", []):
                h["_index"] = f"{alias}:{h['_index']}"
            responses.append(resp)
        return responses, clusters


def merge_ccs_responses(local: Optional[dict], remotes: List[dict],
                        body: dict,
                        clusters: Optional[dict] = None) -> dict:
    """Merge coordinator-side: concatenate hit lists, re-sort by score (or
    sort values), recompute totals (reference: SearchResponseMerger).
    `clusters`: remote-cluster accounting from `search_remotes` — the
    local cluster is added here when it contributed."""
    n_local = 1 if local else 0
    cl = {"total": (clusters or {}).get("total", len(remotes)) + n_local,
          "successful": (clusters or {}).get("successful",
                                             len(remotes)) + n_local,
          "skipped": (clusters or {}).get("skipped", 0)}
    responses = ([local] if local else []) + remotes
    if not responses:
        return {"took": 0, "timed_out": False,
                "_shards": {"total": 0, "successful": 0, "skipped": 0,
                            "failed": 0},
                "_clusters": cl,
                "hits": {"total": {"value": 0, "relation": "eq"},
                         "hits": [], "max_score": None}}
    if len(responses) == 1:
        if not remotes and clusters is None:
            return responses[0]  # pure local: not a CCS response at all
        # a lone response passes through VERBATIM (suggest, profile,
        # _scroll_id, real timed_out all survive) with the cluster
        # accounting attached
        out = dict(responses[0])
        out["_clusters"] = cl
        return out
    size = int((body or {}).get("size", 10))
    all_hits = []
    total = 0
    relation = "eq"
    took = 0
    for r in responses:
        h = r.get("hits", {})
        all_hits.extend(h.get("hits", []))
        total += h.get("total", {}).get("value", 0)
        if h.get("total", {}).get("relation") == "gte":
            relation = "gte"
        took = max(took, r.get("took", 0))
    if (body or {}).get("sort"):
        # trust per-response sort ordering; merge by sort values
        def key(h):
            sv = h.get("sort", [])
            return tuple(sv)
        try:
            all_hits.sort(key=key)
        except TypeError:
            pass
    else:
        all_hits.sort(key=lambda h: -(h.get("_score") or 0.0))
    all_hits = all_hits[:size]
    max_score = max((h.get("_score") or 0.0 for h in all_hits), default=None)
    merged = {
        "took": took, "timed_out": False,
        "_shards": {"total": sum(r.get("_shards", {}).get("total", 0)
                                 for r in responses),
                    "successful": sum(r.get("_shards", {}).get("successful", 0)
                                      for r in responses),
                    "skipped": 0, "failed": 0},
        "_clusters": cl,
        "hits": {"total": {"value": total, "relation": relation},
                 "max_score": max_score, "hits": all_hits},
    }
    # aggregations merge across clusters needs the full reduce tree; only
    # single-source agg responses pass through (reference merges via
    # InternalAggregation.reduce — multi-cluster agg reduce is future work)
    agg_sources = [r for r in responses if r.get("aggregations")]
    if len(agg_sources) == 1:
        merged["aggregations"] = agg_sources[0]["aggregations"]
    return merged


# ---------------------------------------------------------------------------
# CCR
# ---------------------------------------------------------------------------

class CcrService:
    def __init__(self, node):
        self.node = node
        # follower index -> config + replication state
        self.followers: Dict[str, dict] = {}
        self.auto_follow: Dict[str, dict] = {}

    # -- follow lifecycle -----------------------------------------------------
    def follow(self, follower_index: str, body: dict) -> dict:
        remote = body.get("remote_cluster")
        leader = body.get("leader_index")
        if not remote or not leader:
            raise IllegalArgumentError(
                "follow requires [remote_cluster] and [leader_index]")
        remote_cluster = self.node.remotes.get(remote)
        leader_mappings = remote_cluster.get_mappings(leader)
        if not self.node.indices.exists(follower_index):
            self.node.indices.create_index(
                follower_index,
                settings=body.get("settings"),
                mappings=leader_mappings)
        self.followers[follower_index] = {
            "remote_cluster": remote, "leader_index": leader,
            "status": "active", "checkpoint": -1,
            "operations_written": 0, "last_poll": None,
        }
        self.poll(follower_index)
        return {"follow_index_created": True,
                "follow_index_shards_acked": True, "index_following_started": True}

    def pause(self, follower_index: str) -> None:
        self._follower(follower_index)["status"] = "paused"

    def resume(self, follower_index: str) -> None:
        self._follower(follower_index)["status"] = "active"
        self.poll(follower_index)

    def unfollow(self, follower_index: str) -> None:
        if self._follower(follower_index)["status"] != "paused":
            raise IllegalArgumentError(
                f"cannot convert follower [{follower_index}] to a normal "
                "index: pause following first")
        del self.followers[follower_index]

    def _follower(self, follower_index: str) -> dict:
        if follower_index not in self.followers:
            raise ResourceNotFoundError(
                f"follower index [{follower_index}] does not exist")
        return self.followers[follower_index]

    # -- replication ----------------------------------------------------------
    def poll(self, follower_index: str) -> dict:
        """One change-tailing round: a ShardChanges request above the
        follower checkpoint over the remote adapter (the wire RPC in
        production, an in-process scan for test clusters), then ops
        applied locally via the follow task (`ShardChangesAction.java:59`
        request/response + ShardFollowNodeTask apply)."""
        cfg = self._follower(follower_index)
        if cfg["status"] != "active":
            return {"operations": 0}
        remote_cluster = self.node.remotes.get(cfg["remote_cluster"])
        changes = remote_cluster.shard_changes(cfg["leader_index"],
                                               cfg["checkpoint"])
        ops = 0
        for op in changes["operations"]:
            self.node.index_doc(follower_index, op["id"], op["source"])
            ops += 1
        # deletes: anti-join follower ids against the leader live set
        leader_live_ids = set(changes["live_ids"])
        follower_svc = self.node.indices.get(follower_index)
        follower_svc.refresh()
        freader = follower_svc.combined_reader()
        for view in freader.views:
            seg = view.segment
            for local in range(seg.num_docs):
                if not view.live[local]:
                    continue
                if seg.ids[local] not in leader_live_ids:
                    self.node.delete_doc(follower_index, seg.ids[local])
                    ops += 1
        follower_svc.refresh()
        cfg["checkpoint"] = max(cfg["checkpoint"],
                                int(changes["max_seq_no"]))
        cfg["operations_written"] += ops
        cfg["last_poll"] = time.time()
        return {"operations": ops}

    def run_once(self) -> dict:
        """Scheduler tick: poll all active followers + evaluate auto-follow.
        Per-follower isolation: one unreachable leader cluster must not
        starve the other followers (each ShardFollowNodeTask retries
        independently in the reference)."""
        results = {}
        for name in list(self.followers):
            cfg = self.followers[name]
            if cfg["status"] != "active":
                continue
            try:
                results[name] = self.poll(name)["operations"]
                cfg.pop("last_failure", None)
            except Exception as e:  # noqa: BLE001 — retry next tick
                cfg["last_failure"] = f"{type(e).__name__}: {e}"
                results[name] = 0
        self._auto_follow_tick()
        return results

    # -- auto-follow ----------------------------------------------------------
    def put_auto_follow(self, name: str, body: dict) -> None:
        if not body.get("remote_cluster") or not body.get("leader_index_patterns"):
            raise IllegalArgumentError(
                "auto-follow requires [remote_cluster] and [leader_index_patterns]")
        self.auto_follow[name] = body

    def get_auto_follow(self, name: Optional[str] = None) -> dict:
        if name is None:
            return {"patterns": [{"name": n, "pattern": p}
                                 for n, p in self.auto_follow.items()]}
        if name not in self.auto_follow:
            raise ResourceNotFoundError(f"auto-follow pattern [{name}] missing")
        return {"patterns": [{"name": name, "pattern": self.auto_follow[name]}]}

    def delete_auto_follow(self, name: str) -> None:
        if name not in self.auto_follow:
            raise ResourceNotFoundError(f"auto-follow pattern [{name}] missing")
        del self.auto_follow[name]

    def _auto_follow_tick(self) -> None:
        for pat_name, pat in self.auto_follow.items():
            remote = pat["remote_cluster"]
            try:
                remote_cluster = self.node.remotes.get(remote)
                leader_names = remote_cluster.list_indices(
                    ",".join(pat["leader_index_patterns"]))
            except Exception:  # noqa: BLE001 — unreachable remote: next tick
                continue
            suffix = pat.get("follow_index_pattern", "{{leader_index}}")
            for leader_name in leader_names:
                follower_name = suffix.replace("{{leader_index}}", leader_name)
                if follower_name in self.followers or \
                        self.node.indices.exists(follower_name):
                    continue
                self.follow(follower_name, {"remote_cluster": remote,
                                            "leader_index": leader_name})

    # -- stats ----------------------------------------------------------------
    def stats(self) -> dict:
        return {
            "auto_follow_stats": {
                "number_of_successful_follow_indices": len(self.followers)},
            "follow_stats": {"indices": [
                {"index": name,
                 "shards": [{"remote_cluster": cfg["remote_cluster"],
                             "leader_index": cfg["leader_index"],
                             "follower_index": name,
                             "follower_global_checkpoint": cfg["checkpoint"],
                             "operations_written": cfg["operations_written"]}]}
                for name, cfg in self.followers.items()]},
        }

    def follow_info(self, index_expr: str) -> dict:
        out = []
        for name, cfg in self.followers.items():
            if index_expr in ("_all", "*", name):
                out.append({"follower_index": name,
                            "remote_cluster": cfg["remote_cluster"],
                            "leader_index": cfg["leader_index"],
                            "status": cfg["status"]})
        return {"follower_indices": out}
