"""Remote-cluster connectivity over the real binary transport.

Reference:
- `transport/RemoteClusterService.java` + `SniffConnectionStrategy.java`:
  per-alias sniff connections — dial a seed address, handshake, learn the
  remote cluster's gateway nodes, hold pooled connections to up to 3.
- `TransportSearchAction` with `ccs_minimize_roundtrips=true` (the
  default): ONE search request per remote cluster, executed remotely,
  merged at the coordinator (`SearchResponseMerger`).
- `x-pack/plugin/ccr ShardChangesAction.java:59`: followers poll leader
  operation history above a checkpoint over the same transport.

Two adapters implement one small interface (`search`, `shard_changes`,
`list_indices`, `get_mappings`, `info_entry`, `ping`):

- `WireRemote` — sniff-mode client over `transport/tcp.py`. Used by real
  deployments (`cluster.remote.<alias>.seeds` settings). Runs its RPCs on
  a background asyncio loop so the synchronous search path can block on
  them; server nodes answer via the handlers in
  `register_remote_handlers` (wired in server.py for both single-node and
  clustered boots).
- `InProcessRemote` — wraps another `Node` object in the same process
  (the test-cluster analog of the reference's in-JVM
  `InternalTestCluster`). Reports mode "in_process" honestly instead of
  fabricating "sniff".
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import SearchEngineError
from elasticsearch_tpu.transport.tcp import ConnectTransportError as _ConnErr

REMOTE_INFO_ACTION = "internal:remote/info"
REMOTE_SEARCH_ACTION = "indices:data/read/remote/search"
REMOTE_SHARD_CHANGES_ACTION = "indices:data/read/remote/shard_changes"
REMOTE_RESOLVE_ACTION = "internal:remote/resolve"
REMOTE_MAPPINGS_ACTION = "internal:remote/mappings"

MAX_GATEWAY_NODES = 3  # SniffConnectionStrategy default connection count


# ---------------------------------------------------------------------------
# server side: the actions a cluster answers for its remote peers
# ---------------------------------------------------------------------------

def match_indices(names, pattern: str) -> List[str]:
    """Comma-separated wildcard patterns → sorted matching index names
    (shared by the wire `resolve` action and InProcessRemote)."""
    import fnmatch
    parts = [p for p in (pattern or "*").split(",") if p]
    return sorted(n for n in names
                  if any(fnmatch.fnmatchcase(n, p) for p in parts))


def collect_shard_changes(node, index: str, from_seq_no: int) -> dict:
    """Operations above `from_seq_no` for one leader index + the live-id
    set the follower anti-joins for deletes (ShardChangesAction response
    analog; the flattened scan replaces translog history reads because
    segments carry seq_nos + sources)."""
    svc = node.indices.get(index)
    svc.refresh()
    reader = svc.combined_reader()
    ops: List[dict] = []
    live_ids: List[str] = []
    max_seq = int(from_seq_no)
    for view in reader.views:
        seg = view.segment
        for local in range(seg.num_docs):
            if not view.live[local]:
                continue
            live_ids.append(seg.ids[local])
            seq = int(seg.seq_nos[local])
            if seq <= from_seq_no:
                continue
            ops.append({"id": seg.ids[local], "seq_no": seq,
                        "source": seg.sources[local]})
            max_seq = max(max_seq, seq)
    return {"operations": ops, "live_ids": live_ids, "max_seq_no": max_seq}


def register_remote_handlers(transport, node) -> None:
    """Register the remote-facing actions on a node's transport.

    `node` is anything exposing `.search(expr, body)`, `.indices`,
    `.cluster_name` — the single-process `Node` or the clustered
    `ClusterAwareNode` both qualify. Heavy work (search, change scans)
    runs on the node's generic pool, never on the transport event loop;
    failures respond as `{"error": ...}` envelopes the client re-raises
    (the NODES_DISPATCH error convention)."""
    nid = transport.node_id
    loop = getattr(transport, "loop", None)

    def _offloaded(work):
        def handler(sender, request, respond):
            def run():
                try:
                    out = work(request or {})
                except Exception as e:  # noqa: BLE001 — surface, never hang
                    out = {"error": {"type": type(e).__name__,
                                     "reason": str(e),
                                     "status": int(getattr(e, "status",
                                                           500))}}
                if loop is not None:
                    loop.call_soon_threadsafe(respond, out)
                else:
                    respond(out)
            pool = getattr(node, "thread_pool", None)
            if pool is not None:
                pool.submit("generic", run)
            else:
                run()
        return handler

    def info(sender, request, respond):
        # report the seed node AND every cluster peer whose transport
        # address this node learned from published cluster state — the
        # sniff strategy pools them as gateways, so a remote alias
        # survives the death of the node it first connected through
        # (SniffConnectionStrategy: ask the seed for the cluster's nodes)
        host, port = transport.bound_address
        nodes = {nid: [host, port]}
        for peer, (phost, pport) in dict(
                getattr(transport, "_addresses", {})).items():
            nodes.setdefault(peer, [phost, pport])
        respond({"cluster_name": getattr(node, "cluster_name", "cluster"),
                 "nodes": nodes})

    def search(request):
        return {"response": node.search(request.get("expr"),
                                        request.get("body") or {})}

    def shard_changes(request):
        return collect_shard_changes(node, request["index"],
                                     int(request.get("from_seq_no", -1)))

    def resolve(request):
        return {"indices": match_indices(node.indices.indices,
                                         request.get("pattern"))}

    def mappings(request):
        svc = node.indices.get(request["index"])
        return {"mappings": svc.mapper_service.to_dict()}

    transport.register(nid, REMOTE_INFO_ACTION, info)
    transport.register(nid, REMOTE_SEARCH_ACTION, _offloaded(search))
    transport.register(nid, REMOTE_SHARD_CHANGES_ACTION,
                       _offloaded(shard_changes))
    transport.register(nid, REMOTE_RESOLVE_ACTION, _offloaded(resolve))
    transport.register(nid, REMOTE_MAPPINGS_ACTION, _offloaded(mappings))


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

_client_loop_lock = threading.Lock()
_client_loop: Optional[asyncio.AbstractEventLoop] = None


def _shared_client_loop() -> asyncio.AbstractEventLoop:
    """One background asyncio loop per process for remote-cluster clients —
    the synchronous search path blocks on RPC futures scheduled here. The
    returned loop is GUARANTEED running (the thread signals from inside
    the loop before this returns), so callers can always
    run_coroutine_threadsafe against it."""
    global _client_loop
    with _client_loop_lock:
        if _client_loop is not None and _client_loop.is_running():
            return _client_loop
        loop = asyncio.new_event_loop()
        started = threading.Event()

        def runner():
            loop.call_soon(started.set)
            loop.run_forever()

        t = threading.Thread(target=runner,
                             name="remote-cluster-client", daemon=True)
        t.start()
        started.wait(10)
        _client_loop = loop
        return loop


class InProcessRemote:
    """Another Node in this process as a remote cluster (test clusters)."""

    mode = "in_process"

    def __init__(self, alias: str, node):
        self.alias = alias
        self.node = node
        self.skip_unavailable = False

    def ping(self) -> bool:
        return True

    def search(self, expr: Optional[str], body: dict) -> dict:
        return self.node.search(expr, body)

    def shard_changes(self, index: str, from_seq_no: int) -> dict:
        return collect_shard_changes(self.node, index, from_seq_no)

    def list_indices(self, pattern: str) -> List[str]:
        return match_indices(self.node.indices.indices, pattern)

    def get_mappings(self, index: str) -> dict:
        return self.node.indices.get(index).mapper_service.to_dict()

    def info_entry(self) -> dict:
        return {"connected": True, "mode": self.mode,
                "seeds": [f"in-process:{id(self.node):x}"],
                "num_nodes_connected": 1,
                "skip_unavailable": self.skip_unavailable}

    def close(self) -> None:
        pass


class WireRemote:
    """Sniff-mode remote cluster over the binary TCP transport.

    Connection strategy (SniffConnectionStrategy): dial each configured
    seed until one handshakes, ask it for the remote cluster's nodes,
    record up to MAX_GATEWAY_NODES gateway addresses, then round-robin
    RPCs over them. A failed RPC marks the connection down; the next call
    re-sniffs once before giving up."""

    mode = "sniff"

    def __init__(self, alias: str, seeds: List[str],
                 skip_unavailable: bool = False,
                 local_node_id: Optional[str] = None,
                 rpc_timeout_s: float = 30.0):
        from elasticsearch_tpu.transport.tcp import TcpTransportService
        self.alias = alias
        self.seeds = list(seeds)
        self.skip_unavailable = bool(skip_unavailable)
        self.rpc_timeout_s = rpc_timeout_s
        self.cluster_name: Optional[str] = None
        self.gateways: List[str] = []
        self.connected = False
        self._rr = 0
        self.loop = _shared_client_loop()
        self.transport = TcpTransportService(
            local_node_id or f"_remote_client_{alias}", loop=self.loop)

    # ------------------------------------------------------------ plumbing
    def _run(self, coro):
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return asyncio.run_coroutine_threadsafe(
                coro, self.loop).result(self.rpc_timeout_s + 5)
        raise SearchEngineError(
            "remote-cluster RPC invoked from its own event loop")

    async def _rpc_async(self, target: str, action: str, request: Any):
        fut = self.loop.create_future()

        def ok(resp):
            if fut.done():
                return
            if isinstance(resp, dict) and resp.get("error") is not None:
                # offloaded-handler error envelope: re-raise remotely-typed
                # with the original HTTP status, NOT as a connection error
                # (the cluster is reachable; the request failed)
                err_info = resp["error"]
                e = SearchEngineError(
                    f"[{self.alias}] {err_info.get('type', 'error')}: "
                    f"{err_info.get('reason', '')}")
                e.status = int(err_info.get("status", 500))
                fut.set_exception(e)
                return
            fut.set_result(resp)

        def fail(err):
            if not fut.done():
                fut.set_exception(err)

        self.transport.send(self.transport.node_id, target, action, request,
                            ok, fail,
                            timeout_ms=int(self.rpc_timeout_s * 1000))
        return await fut

    async def _sniff_async(self) -> None:
        last_err: Optional[Exception] = None
        for seed in self.seeds:
            host, _, port = str(seed).rpartition(":")
            try:
                nid = await self.transport.probe_address(host, int(port))
                info = await self._rpc_async(nid, REMOTE_INFO_ACTION, {})
                self.cluster_name = info.get("cluster_name")
                gateways = []
                for gid, addr in (info.get("nodes") or {}).items():
                    self.transport.add_peer_address(gid, addr[0],
                                                    int(addr[1]))
                    gateways.append(gid)
                if not gateways:
                    raise _ConnErr(f"remote [{self.alias}] returned no nodes")
                self.gateways = gateways[:MAX_GATEWAY_NODES]
                self.connected = True
                return
            except Exception as e:  # noqa: BLE001 — try the next seed
                last_err = e
        self.connected = False
        self.gateways = []
        raise _ConnErr(
            f"unable to connect to remote cluster [{self.alias}] "
            f"(seeds {self.seeds}): {last_err}")

    async def _call_async(self, action: str, request: Any):
        if not self.connected:
            await self._sniff_async()
        err: Optional[Exception] = None
        for _ in range(max(len(self.gateways), 1)):
            gid = self.gateways[self._rr % len(self.gateways)]
            self._rr += 1
            try:
                return await self._rpc_async(gid, action, request)
            except _ConnErr as e:
                err = e
        # every pooled gateway failed: one re-sniff, then give up
        self.connected = False
        await self._sniff_async()
        gid = self.gateways[0]
        try:
            return await self._rpc_async(gid, action, request)
        except _ConnErr as e:
            self.connected = False
            raise e from err

    def _call(self, action: str, request: Any):
        # bound the WHOLE retry ladder (gateway failover + re-sniff) by one
        # deadline: wait_for cancels the coroutine on expiry, so nothing
        # keeps running on the shared loop, and the caller always sees a
        # typed connect error instead of a bare concurrent TimeoutError
        async def bounded():
            try:
                return await asyncio.wait_for(
                    self._call_async(action, request), self.rpc_timeout_s)
            except asyncio.TimeoutError:
                self.connected = False
                raise _ConnErr(
                    f"remote cluster [{self.alias}] did not answer "
                    f"[{action}] within {self.rpc_timeout_s}s") from None
        return self._run(bounded())

    # ------------------------------------------------------------ interface
    def ping(self) -> bool:
        try:
            if not self.connected:
                self._run(self._sniff_async())
            return True
        except Exception:  # noqa: BLE001
            return False

    def search(self, expr: Optional[str], body: dict) -> dict:
        resp = self._call(REMOTE_SEARCH_ACTION, {"expr": expr, "body": body})
        return resp["response"]

    def shard_changes(self, index: str, from_seq_no: int) -> dict:
        return self._call(REMOTE_SHARD_CHANGES_ACTION,
                          {"index": index, "from_seq_no": int(from_seq_no)})

    def list_indices(self, pattern: str) -> List[str]:
        return self._call(REMOTE_RESOLVE_ACTION,
                          {"pattern": pattern})["indices"]

    def get_mappings(self, index: str) -> dict:
        return self._call(REMOTE_MAPPINGS_ACTION, {"index": index})["mappings"]

    def info_entry(self) -> dict:
        return {"connected": self.connected, "mode": self.mode,
                "seeds": list(self.seeds),
                "num_nodes_connected": len(self.gateways),
                "skip_unavailable": self.skip_unavailable,
                **({"cluster_name": self.cluster_name}
                   if self.cluster_name else {})}

    def close(self) -> None:
        async def _close():
            await self.transport.close()
        try:
            asyncio.run_coroutine_threadsafe(_close(), self.loop).result(5)
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass


def parse_remote_settings(flat: Dict[str, Any]) -> Dict[str, dict]:
    """`cluster.remote.<alias>.{seeds,skip_unavailable,mode}` →
    {alias: {seeds: [...], skip_unavailable: bool}}. `seeds: None` (a
    settings reset) removes the alias."""
    out: Dict[str, dict] = {}
    prefix = "cluster.remote."
    for key, value in (flat or {}).items():
        if not key.startswith(prefix):
            continue
        rest = key[len(prefix):]
        alias, _, leaf = rest.partition(".")
        if not alias or not leaf:
            continue
        entry = out.setdefault(alias, {})
        if leaf == "seeds":
            if value is None:
                entry["seeds"] = None
            elif isinstance(value, (list, tuple)):
                entry["seeds"] = [str(v) for v in value]
            else:
                entry["seeds"] = [s.strip() for s in str(value).split(",")
                                  if s.strip()]
        elif leaf == "skip_unavailable":
            entry["skip_unavailable"] = value in (True, "true", "True")
    return out
