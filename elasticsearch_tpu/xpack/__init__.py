"""X-Pack-tier feature plugins (SURVEY.md §2.11): SQL, EQL, ILM, watcher,
transform, rollup, ML, CCR — each composes onto the core layers the way the
reference's x-pack plugins compose onto layer-14 extension points.
"""
