"""Index lifecycle management (ILM) + snapshot lifecycle (SLM) + resize ops.

Reference: `x-pack/plugin/ilm` (7.3k LoC) — a policy is a phase→actions map;
`IndexLifecycleRunner` advances each managed index through the steps that
`PolicyStepsRegistry` resolves; state lives in index metadata; SLM schedules
snapshots. Rollover/shrink/clone/split are core APIs
(`action/admin/indices/rollover/`, `admin/indices/shrink/ResizeRequest`).

Here the runner is tick-driven (`IlmService.run_once(now_ms)`) — the
single-process analog of the reference's periodic `SchedulerEngine` trigger —
so tests drive the clock deterministically.
"""

from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError,
    ResourceNotFoundError,
    ValidationError,
)
from elasticsearch_tpu.common.settings import parse_time_value

# phases in execution order (TimeseriesLifecycleType.VALID_PHASES)
PHASES = ["hot", "warm", "cold", "delete"]

_ROLLOVER_SUFFIX = re.compile(r"^(.*?)-(\d+)$")


# ---------------------------------------------------------------------------
# resize: shrink / clone / split (core API, used by ILM's shrink action)
# ---------------------------------------------------------------------------

def resize_index(node, source: str, target: str, kind: str,
                 body: Optional[dict] = None) -> dict:
    """Copy `source` into a new `target` index (reference:
    TransportResizeAction — here a doc-level copy since segments are
    re-encoded into the device-friendly layout anyway)."""
    body = body or {}
    svc = node.indices.get(source)
    if node.indices.exists(target):
        raise IllegalArgumentError(f"index [{target}] already exists")
    settings = dict(body.get("settings", {}))
    src_shards = int(svc.settings.get("index.number_of_shards", 1))
    if "number_of_shards" in settings:  # un-prefixed form normalizes
        settings.setdefault("index.number_of_shards",
                            settings.pop("number_of_shards"))
    if kind == "shrink":
        settings.setdefault("index.number_of_shards", 1)
        tgt = int(settings["index.number_of_shards"])
        if src_shards % tgt != 0:
            raise IllegalArgumentError(
                f"the number of target shards [{tgt}] must be a factor of "
                f"the number of source shards [{src_shards}]")
    elif kind == "split":
        if "index.number_of_routing_shards" in settings \
                or "number_of_routing_shards" in settings:
            raise IllegalArgumentError(
                "cannot provide index.number_of_routing_shards on resize")
        if "index.number_of_shards" not in settings:
            raise IllegalArgumentError("split requires index.number_of_shards")
        tgt = int(settings["index.number_of_shards"])
        from elasticsearch_tpu.common.errors import IllegalStateError
        if tgt < src_shards or tgt % src_shards != 0:
            raise IllegalStateError(
                f"the number of source shards [{src_shards}] must be a "
                f"factor of the number of target shards [{tgt}]")
        routing = svc.settings.get("index.number_of_routing_shards")
        if routing is not None and int(routing) % tgt != 0:
            # targets must divide the fixed routing-shard count
            # (IndexMetaData#getRoutingFactor)
            raise IllegalStateError(
                f"the number of routing shards [{routing}] must be a "
                f"multiple of the target shards [{tgt}]")
    elif kind == "clone":
        settings.setdefault("index.number_of_shards", src_shards)
        if int(settings["index.number_of_shards"]) != src_shards:
            raise IllegalArgumentError(
                f"cannot clone from [{src_shards}] shards to "
                f"[{settings['index.number_of_shards']}] shards: the number "
                "of shards must stay the same")
    # the target COPIES the source's settings (8.0 resize semantics —
    # copy_settings can no longer be false), minus the per-index
    # internals and write blocks that would break the doc-level copy;
    # request settings override
    _no_copy_prefixes = ("index.number_of_shards",
                         "index.number_of_routing_shards",
                         "index.uuid",
                         "index.version.", "index.creation_date",
                         "index.provided_name", "index.resize.")
    copied_settings = {
        k: v for k, v in svc.settings.as_flat_dict().items()
        if k.startswith("index.")
        and not any(k.startswith(p) for p in _no_copy_prefixes)}
    settings = {**copied_settings, **settings}
    # write blocks copy too (the reference hard-links segments, so the
    # source's read-only block travels) — but THIS copy writes documents
    # through the API, so blocks apply AFTER the data lands
    deferred_blocks = {k: v for k, v in settings.items()
                       if k.startswith("index.blocks.")}
    settings = {k: v for k, v in settings.items()
                if not k.startswith("index.blocks.")}
    mappings = svc.mapper_service.to_dict()
    node.indices.create_index(target, settings=settings,
                              mappings=mappings,
                              aliases=body.get("aliases"))
    svc.refresh()  # the resize source copies its CURRENT docs, buffered too
    reader = svc.combined_reader()
    copied = 0
    for view in reader.views:
        seg = view.segment
        for local in range(seg.num_docs):
            if not view.live[local]:
                continue
            node.index_doc(target, seg.ids[local], seg.sources[local])
            copied += 1
    node.indices.get(target).refresh()
    if deferred_blocks:
        node.indices.update_settings(node.indices.get(target),
                                     deferred_blocks)
    return {"acknowledged": True, "shards_acknowledged": True,
            "index": target, "copied_docs": copied}


# ---------------------------------------------------------------------------
# rollover
# ---------------------------------------------------------------------------

def _next_rollover_name(index_name: str) -> str:
    m = _ROLLOVER_SUFFIX.match(index_name)
    if m is None:
        raise IllegalArgumentError(
            f"index name [{index_name}] does not match pattern '^.*-\\d+$'")
    return f"{m.group(1)}-{int(m.group(2)) + 1:06d}"


def rollover(node, alias: str, body: Optional[dict] = None,
             now_ms: Optional[int] = None, dry_run: bool = False) -> dict:
    """POST /{alias}/_rollover — evaluate conditions on the current write
    index; when met, create the next index and atomically swap the alias
    (reference: TransportRolloverAction / MetaDataRolloverService)."""
    body = body or {}
    now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
    backing = [svc for svc in node.indices.indices.values()
               if alias in svc.aliases]
    if not backing:
        # the alias may actually be a concrete index (datastream-less use)
        raise ResourceNotFoundError(
            f"rollover target [{alias}] does not exist as an alias")
    writers = [svc for svc in backing
               if svc.aliases[alias].get("is_write_index", True)]
    if len(writers) != 1:
        raise IllegalArgumentError(
            f"rollover target [{alias}] must resolve to exactly one write "
            f"index, got {len(writers)}")
    old = writers[0]
    conditions = body.get("conditions", {})
    results: Dict[str, bool] = {}
    age_ms = now_ms - old.creation_date
    if "max_age" in conditions:
        results[f"[max_age: {conditions['max_age']}]"] = (
            age_ms >= parse_time_value(conditions["max_age"], "max_age") * 1000)
    if "max_docs" in conditions:
        results[f"[max_docs: {conditions['max_docs']}]"] = (
            old.doc_count() >= int(conditions["max_docs"]))
    if "max_size" in conditions:
        # doc-source byte estimate; the reference uses on-disk segment size
        import json as _json
        reader = old.combined_reader()
        nbytes = sum(len(_json.dumps(view.segment.sources[i]))
                     for view in reader.views
                     for i in range(view.segment.num_docs))
        from elasticsearch_tpu.common.settings import parse_byte_size
        results[f"[max_size: {conditions['max_size']}]"] = (
            nbytes >= parse_byte_size(conditions["max_size"], "max_size"))
    met = (not conditions) or any(results.values())
    new_index = body.get("new_index") or _next_rollover_name(old.name)
    if body.get("new_index"):
        from elasticsearch_tpu.indices.service import IndicesService
        IndicesService.validate_index_name(str(new_index))
    if node.indices.exists(new_index):
        # checked even for dry runs (MetaDataCreateIndexService validation)
        from elasticsearch_tpu.common.errors import (
            ResourceAlreadyExistsError)
        raise ResourceAlreadyExistsError(
            f"index [{new_index}] already exists", index=new_index)
    out = {"acknowledged": False, "shards_acknowledged": False,
           "old_index": old.name, "new_index": new_index,
           "rolled_over": False, "dry_run": dry_run, "conditions": results}
    if dry_run or not met:
        return out
    explicit_write = "is_write_index" in old.aliases[alias]
    node.indices.create_index(new_index,
                              settings=body.get("settings"),
                              mappings=body.get("mappings"),
                              aliases={alias: ({"is_write_index": True}
                                               if explicit_write else {})})
    if explicit_write:
        # write-alias rollover keeps the alias on both, flipping the flag
        old.aliases[alias] = {**old.aliases[alias], "is_write_index": False}
    else:
        # plain alias swings entirely to the new index
        # (MetaDataRolloverService removes it from the old one)
        old.aliases.pop(alias, None)
    out.update({"acknowledged": True, "shards_acknowledged": True,
                "rolled_over": True})
    return out


# ---------------------------------------------------------------------------
# ILM
# ---------------------------------------------------------------------------

class IlmService:
    def __init__(self, node):
        self.node = node
        self.policies: Dict[str, dict] = {}
        self.running = True
        # per-index lifecycle execution state (reference keeps this in
        # IndexMetaData custom `index.lifecycle`)
        self.index_state: Dict[str, dict] = {}

    # -- policy CRUD ----------------------------------------------------------
    def put_policy(self, name: str, body: dict) -> None:
        policy = body.get("policy")
        if not isinstance(policy, dict) or "phases" not in policy:
            raise ValidationError("policy must define [phases]")
        for phase in policy["phases"]:
            if phase not in PHASES:
                raise ValidationError(f"unknown phase [{phase}]")
        self.policies[name] = {"policy": policy, "version":
                               self.policies.get(name, {}).get("version", 0) + 1,
                               "modified_date": int(time.time() * 1000)}

    def get_policy(self, name: Optional[str] = None) -> dict:
        if name is None:
            return dict(self.policies)
        if name not in self.policies:
            raise ResourceNotFoundError(f"lifecycle policy [{name}] not found")
        return {name: self.policies[name]}

    def delete_policy(self, name: str) -> None:
        if name not in self.policies:
            raise ResourceNotFoundError(f"lifecycle policy [{name}] not found")
        used_by = [idx for idx, st in self.index_state.items()
                   if st.get("policy") == name]
        if used_by:
            raise IllegalArgumentError(
                f"cannot delete policy [{name}]: in use by {used_by}")
        del self.policies[name]

    # -- runner ---------------------------------------------------------------
    def _managed_indices(self) -> List[Any]:
        out = []
        for svc in list(self.node.indices.indices.values()):
            policy = svc.settings.get("index.lifecycle.name")
            if policy:
                out.append((svc, policy))
        return out

    def run_once(self, now_ms: Optional[int] = None) -> List[dict]:
        """One scheduler tick: advance every managed index. Returns the
        actions taken (for tests/observability)."""
        if not self.running:
            return []
        now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
        took: List[dict] = []
        for svc, policy_name in self._managed_indices():
            pol = self.policies.get(policy_name)
            if pol is None:
                continue
            state = self.index_state.setdefault(
                svc.name, {"policy": policy_name, "phase": None,
                           "action": "complete", "step": "complete",
                           "phase_time": svc.creation_date})
            actions = self._advance(svc, pol["policy"], state, now_ms)
            took.extend(actions)
        return took

    def _phase_age_ms(self, phase_def: dict) -> float:
        return parse_time_value(phase_def.get("min_age", "0ms"), "min_age") * 1000

    def _advance(self, svc, policy: dict, state: dict,
                 now_ms: int) -> List[dict]:
        phases = policy.get("phases", {})
        age_ms = now_ms - svc.creation_date
        # find the latest phase whose min_age has elapsed
        target_phase = None
        for phase in PHASES:
            if phase not in phases:
                continue
            if age_ms >= self._phase_age_ms(phases[phase]):
                target_phase = phase
        if target_phase is None or target_phase == state.get("phase"):
            # still run in-phase repeatable actions (hot rollover)
            if state.get("phase") == "hot":
                return self._run_phase_actions(svc, "hot",
                                               phases.get("hot", {}), state,
                                               now_ms, repeat=True)
            return []
        state["phase"] = target_phase
        state["phase_time"] = now_ms
        return self._run_phase_actions(svc, target_phase,
                                       phases.get(target_phase, {}), state,
                                       now_ms)

    def _run_phase_actions(self, svc, phase: str, phase_def: dict,
                           state: dict, now_ms: int,
                           repeat: bool = False) -> List[dict]:
        took: List[dict] = []
        actions = phase_def.get("actions", {})
        name = svc.name
        for action, spec in actions.items():
            if action == "rollover":
                alias = svc.settings.get("index.lifecycle.rollover_alias")
                if not alias or alias not in svc.aliases:
                    continue
                if not svc.aliases[alias].get("is_write_index", True):
                    continue   # already rolled
                result = rollover(self.node, alias,
                                  {"conditions": _rollover_conditions(spec)},
                                  now_ms=now_ms)
                if result["rolled_over"]:
                    # the new index inherits the policy via settings the
                    # caller set in the template; record the event
                    new_svc = self.node.indices.get(result["new_index"])
                    new_svc.settings_update({
                        "index.lifecycle.name": state["policy"],
                        "index.lifecycle.rollover_alias": alias})
                    took.append({"index": name, "action": "rollover",
                                 "new_index": result["new_index"]})
            elif repeat:
                continue       # only rollover repeats within a phase
            elif action == "forcemerge":
                svc.force_merge()
                took.append({"index": name, "action": "forcemerge"})
            elif action == "shrink":
                target = f"shrink-{name}"
                if not self.node.indices.exists(target):
                    resize_index(self.node, name, target, "shrink",
                                 {"settings": {"index.number_of_shards":
                                               spec.get("number_of_shards", 1)}})
                    took.append({"index": name, "action": "shrink",
                                 "target": target})
            elif action == "readonly":
                self.node.indices.update_settings(
                    svc, {"index.blocks.write": True})
                took.append({"index": name, "action": "readonly"})
            elif action == "freeze":
                self.node.indices.update_settings(
                    svc, {"index.frozen": True})
                took.append({"index": name, "action": "freeze"})
            elif action == "delete":
                self.node.indices.delete_index(name)
                self.index_state.pop(name, None)
                took.append({"index": name, "action": "delete"})
                return took   # index is gone; stop processing actions
            elif action in ("allocate", "set_priority", "migrate",
                            "searchable_snapshot", "wait_for_snapshot",
                            "unfollow"):
                took.append({"index": name, "action": action, "noop": True})
        state["action"] = "complete"
        state["step"] = "complete"
        return took

    # -- explain --------------------------------------------------------------
    def explain(self, index_expr: str) -> dict:
        out = {}
        for svc in self.node.indices.resolve(index_expr):
            policy = svc.settings.get("index.lifecycle.name")
            if not policy:
                out[svc.name] = {"index": svc.name, "managed": False}
                continue
            st = self.index_state.get(svc.name, {})
            out[svc.name] = {
                "index": svc.name, "managed": True, "policy": policy,
                "phase": st.get("phase"), "action": st.get("action"),
                "step": st.get("step"),
                "age": f"{max(0, int(time.time()*1000) - svc.creation_date)//1000}s",
            }
        return {"indices": out}


def _rollover_conditions(spec: dict) -> dict:
    out = {}
    for k in ("max_age", "max_docs", "max_size", "max_primary_shard_size"):
        if k in spec:
            out["max_size" if k == "max_primary_shard_size" else k] = spec[k]
    return out


# ---------------------------------------------------------------------------
# SLM
# ---------------------------------------------------------------------------

class SlmService:
    """Snapshot lifecycle: named policies that snapshot on schedule.

    Reference: `x-pack/.../slm/SnapshotLifecycleService` — cron-scheduled;
    here interval-scheduled via `run_once(now)` ticks plus manual
    `_execute`.
    """

    def __init__(self, node):
        self.node = node
        self.policies: Dict[str, dict] = {}
        self.history: List[dict] = []

    def put_policy(self, policy_id: str, body: dict) -> None:
        for req in ("repository", "name"):
            if req not in body:
                raise ValidationError(f"snapshot lifecycle policy requires [{req}]")
        self.policies[policy_id] = {
            **body,
            "version": self.policies.get(policy_id, {}).get("version", 0) + 1,
            "modified_date_millis": int(time.time() * 1000),
            "last_success": None, "next_execution_millis": None,
        }

    def get_policy(self, policy_id: Optional[str] = None) -> dict:
        if policy_id is None:
            return dict(self.policies)
        if policy_id not in self.policies:
            raise ResourceNotFoundError(f"snapshot lifecycle policy "
                                        f"[{policy_id}] not found")
        return {policy_id: self.policies[policy_id]}

    def delete_policy(self, policy_id: str) -> None:
        if policy_id not in self.policies:
            raise ResourceNotFoundError(f"snapshot lifecycle policy "
                                        f"[{policy_id}] not found")
        del self.policies[policy_id]

    def execute(self, policy_id: str) -> dict:
        pol = self.policies.get(policy_id)
        if pol is None:
            raise ResourceNotFoundError(f"snapshot lifecycle policy "
                                        f"[{policy_id}] not found")
        snap_name = pol["name"].replace("<", "").replace(">", "").replace(
            "{now/d}", time.strftime("%Y.%m.%d")) + "-" + str(int(time.time()))
        config = pol.get("config", {})
        result = self.node.snapshots.create_snapshot(
            pol["repository"], snap_name,
            {"indices": config.get("indices", "*")})
        pol["last_success"] = {"snapshot_name": snap_name,
                               "time": int(time.time() * 1000)}
        self.history.append({"policy": policy_id, "snapshot": snap_name,
                             "status": "success"})
        return {"snapshot_name": snap_name}
