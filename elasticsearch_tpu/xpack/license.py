"""License service: feature gating by license tier.

Reference: `x-pack/plugin/core/.../license/LicenseService.java` +
`XPackLicenseState` — the cluster carries one license (basic by default);
features check the license state before executing and fail with a
security_exception when the tier is insufficient.

Tier ladder: basic < standard < gold < platinum < enterprise; `trial`
grants platinum-level features for 30 days.
"""

from __future__ import annotations

import time
import uuid
from typing import Optional

from elasticsearch_tpu.common.errors import SearchEngineError

_TIERS = ("basic", "standard", "gold", "platinum", "enterprise", "trial")

# platinum-tier features (XPackLicenseState checks)
_FEATURE_TIER = {
    "ml": "platinum",
    "ccr": "platinum",
    "dls_fls": "platinum",
    "graph": "platinum",
    "watcher": "gold",
    "security_custom_realms": "platinum",
}


class LicenseExpiredError(SearchEngineError):
    status = 403

    @property
    def error_type(self) -> str:
        return "security_exception"


def _rank(tier: str) -> int:
    tier = "platinum" if tier == "trial" else tier
    try:
        return _TIERS.index(tier)
    except ValueError:
        return 0


class LicenseService:
    def __init__(self, self_generated: str = "trial"):
        # xpack.license.self_generated.type: dev distributions boot with a
        # 30-day trial; "basic" boots feature-gated
        days = 30 if self_generated == "trial" else None
        self._license = self._make(self_generated, days=days)
        self._trial_used = self_generated == "trial"

    @staticmethod
    def _make(ltype: str, days: Optional[int]) -> dict:
        now_ms = int(time.time() * 1000)
        lic = {"status": "active", "uid": uuid.uuid4().hex, "type": ltype,
               "issue_date_in_millis": now_ms,
               "issued_to": "tpu-search cluster", "issuer": "elasticsearch",
               "start_date_in_millis": now_ms, "max_nodes": 1000}
        if days is not None:
            lic["expiry_date_in_millis"] = now_ms + days * 86_400_000
        return lic

    # ------------------------------------------------------------ state
    @property
    def license(self) -> dict:
        lic = dict(self._license)
        exp = lic.get("expiry_date_in_millis")
        if exp is not None and time.time() * 1000 > exp:
            lic["status"] = "expired"
        return lic

    @property
    def tier(self) -> str:
        lic = self.license
        return lic["type"] if lic["status"] == "active" else "basic"

    def allows(self, feature: str) -> bool:
        need = _FEATURE_TIER.get(feature)
        if need is None:
            return True
        return _rank(self.tier) >= _rank(need)

    def gate(self, feature: str) -> None:
        """Raise when the current license doesn't cover `feature`
        (XPackLicenseState.checkFeature -> security_exception 403)."""
        if not self.allows(feature):
            need = _FEATURE_TIER.get(feature, "platinum")
            raise LicenseExpiredError(
                f"current license is non-compliant for [{feature}]; "
                f"a [{need}] license is required")

    # ------------------------------------------------------------ admin
    def put_license(self, body: dict) -> dict:
        licenses = (body or {}).get("licenses") or []
        lic = licenses[0] if licenses else (body or {}).get("license")
        if not isinstance(lic, dict) or not lic.get("type"):
            raise SearchEngineError("malformed license body")
        self._license = {**self._make(str(lic["type"]), days=None), **lic}
        return {"acknowledged": True, "license_status": "valid"}

    def start_trial(self, acknowledge: bool = False) -> dict:
        if not acknowledge:
            return {"acknowledged": False, "trial_was_started": False,
                    "error_message": "Operation failed: Needs acknowledgement."}
        if self._trial_used:
            return {"acknowledged": True, "trial_was_started": False,
                    "error_message": "Operation failed: Trial was already "
                                     "activated."}
        self._trial_used = True
        self._license = self._make("trial", days=30)
        return {"acknowledged": True, "trial_was_started": True,
                "type": "trial"}

    def start_basic(self, acknowledge: bool = False) -> dict:
        if not acknowledge:
            return {"acknowledged": False, "basic_was_started": False,
                    "error_message": "Operation failed: Needs acknowledgement."}
        self._license = self._make("basic", days=None)
        return {"acknowledged": True, "basic_was_started": True}

    def delete_license(self) -> dict:
        self._license = self._make("basic", days=None)
        return {"acknowledged": True}
