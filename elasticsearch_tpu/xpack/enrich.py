"""Enrich: lookup policies + the `enrich` ingest processor.

Reference: `x-pack/plugin/enrich` (4.1k LoC) — `EnrichPolicy` (match /
geo_match types), `EnrichPolicyRunner` (executes a policy by reindexing the
source into a hidden `.enrich-*` lookup index), `EnrichProcessorFactory` /
`MatchProcessor` (ingest-time joins against the lookup index).

Here the policy execution materializes the lookup both as a hidden
`.enrich-{policy}` index (inspectable, like the reference) and as an
in-memory exact-match table the processor reads; geo_match policies match
by envelope containment against geo_shape values.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    ResourceAlreadyExistsError,
    ResourceNotFoundError,
    ValidationError,
)


class EnrichService:
    def __init__(self, node):
        self.node = node
        self.policies: Dict[str, dict] = {}
        # policy -> match_value(str) -> enrich doc
        self.lookups: Dict[str, Dict[str, dict]] = {}
        # policy -> [(envelope, enrich doc)] for geo_match
        self.geo_lookups: Dict[str, List[tuple]] = {}
        self.stats = {"executed": 0}

    def put_policy(self, name: str, body: dict) -> None:
        if name in self.policies:
            raise ResourceAlreadyExistsError(
                f"policy [{name}] already exists")
        ptype = "match" if "match" in body else (
            "geo_match" if "geo_match" in body else None)
        if ptype is None:
            raise ValidationError(
                "policy must define [match] or [geo_match]")
        spec = body[ptype]
        for req in ("indices", "match_field", "enrich_fields"):
            if not spec.get(req):
                raise ValidationError(f"policy requires [{req}]")
        self.policies[name] = {"name": name, "type": ptype, **spec}

    def get_policy(self, name: Optional[str] = None) -> dict:
        if name and name not in ("*", "_all"):
            if name not in self.policies:
                raise ResourceNotFoundError(f"policy [{name}] not found")
            items = [self.policies[name]]
        else:
            items = [self.policies[k] for k in sorted(self.policies)]
        return {"policies": [{"config": {p["type"]: {
            "name": p["name"], "indices": p["indices"],
            "match_field": p["match_field"],
            "enrich_fields": p["enrich_fields"]}}} for p in items]}

    def delete_policy(self, name: str) -> None:
        if name not in self.policies:
            raise ResourceNotFoundError(f"policy [{name}] not found")
        del self.policies[name]
        self.lookups.pop(name, None)
        self.geo_lookups.pop(name, None)

    def execute_policy(self, name: str) -> dict:
        """Materialize the lookup (reference: EnrichPolicyRunner.run)."""
        policy = self.policies.get(name)
        if policy is None:
            raise ResourceNotFoundError(f"policy [{name}] not found")
        indices = policy["indices"]
        index_expr = ",".join(indices) if isinstance(indices, list) else indices
        match_field = policy["match_field"]
        keep = set(policy["enrich_fields"]) | {match_field}
        table: Dict[str, dict] = {}
        geo_table: List[tuple] = []
        count = 0
        sources: List[dict] = []
        # page the full source per index (reference: EnrichPolicyRunner
        # reindexes everything); _doc paging is only stable within one index
        for svc in self.node.indices.resolve(index_expr):
            search_after = None
            while True:
                b = {"query": {"match_all": {}}, "size": 1000,
                     "sort": [{"_doc": {"order": "asc"}}]}
                if search_after is not None:
                    b["search_after"] = search_after
                resp = self.node.search(svc.name, b)
                hits = resp["hits"]["hits"]
                if not hits:
                    break
                sources.extend(h["_source"] for h in hits)
                search_after = hits[-1]["sort"]
        for src in sources:
            enrich_doc = {k: v for k, v in src.items() if k in keep}
            mv = src.get(match_field)
            if mv is None:
                continue
            if policy["type"] == "geo_match":
                from elasticsearch_tpu.index.mapping import (
                    GeoShapeFieldMapper)
                try:
                    env = GeoShapeFieldMapper(match_field).coerce(mv)["envelope"]
                except Exception:
                    continue
                geo_table.append((env, enrich_doc))
            else:
                for v in (mv if isinstance(mv, list) else [mv]):
                    table[str(v)] = enrich_doc
            count += 1
        self.lookups[name] = table
        self.geo_lookups[name] = geo_table
        # hidden lookup index, recreated per execution like the reference
        lookup_index = f".enrich-{name}"
        if self.node.indices.exists(lookup_index):
            self.node.indices.delete_index(lookup_index)
        for key, doc in table.items():
            self.node.index_doc(lookup_index, None,
                                {"_match": key, **doc})
        if self.node.indices.exists(lookup_index):
            self.node.indices.get(lookup_index).refresh()
        self.stats["executed"] += 1
        return {"status": {"phase": "COMPLETE"},
                "task": None, "documents": count}

    def lookup(self, name: str, value) -> List[dict]:
        policy = self.policies.get(name)
        if policy is None:
            raise ResourceNotFoundError(f"policy [{name}] not found")
        if policy["type"] == "geo_match":
            try:
                lat, lon = _as_point(value)
            except Exception:
                return []
            out = []
            for (min_lon, min_lat, max_lon, max_lat), doc in \
                    self.geo_lookups.get(name, []):
                if min_lon <= lon <= max_lon and min_lat <= lat <= max_lat:
                    out.append(doc)
            return out
        doc = self.lookups.get(name, {}).get(str(value))
        return [doc] if doc is not None else []


def _as_point(value):
    if isinstance(value, dict):
        return float(value["lat"]), float(value["lon"])
    if isinstance(value, (list, tuple)) and len(value) == 2:
        return float(value[1]), float(value[0])
    parts = str(value).split(",")
    return float(parts[0]), float(parts[1])


# ---------------------------------------------------------------------------
# ingest processor
# ---------------------------------------------------------------------------

class EnrichProcessorImpl:
    """Registered once; resolves the owning node's EnrichService at run time
    through the per-node IngestService (passed to processors as the pipeline
    registry), so multiple Nodes in one process each enrich against their
    own policies."""

    @staticmethod
    def install() -> None:
        from elasticsearch_tpu.ingest.service import (
            IngestProcessorError, PROCESSORS, Processor, _get_path,
            _set_path,
        )
        if "enrich" in PROCESSORS:
            return

        import copy

        class EnrichProcessor(Processor):
            kind = "enrich"

            def run(self, ctx):
                svc = getattr(getattr(self, "_registry", None),
                              "enrich_service", None)
                if svc is None:
                    raise IngestProcessorError(
                        "no enrich service attached to this node")
                value = _get_path(ctx, self.field)
                if value is None:
                    if self.ignore_missing:
                        return
                    raise IngestProcessorError(
                        f"field [{self.field}] not present")
                matches = svc.lookup(self.spec["policy_name"], value)
                if not matches:
                    return
                max_matches = int(self.spec.get("max_matches", 1))
                target = self.spec["target_field"]
                # deep-copy: the lookup table entries are shared across docs
                if max_matches == 1:
                    _set_path(ctx, target, copy.deepcopy(matches[0]))
                else:
                    _set_path(ctx, target,
                              copy.deepcopy(matches[:max_matches]))

        PROCESSORS[EnrichProcessor.kind] = EnrichProcessor


def attach_enrich(node) -> EnrichService:
    """Create the node's EnrichService and expose it to ingest pipelines."""
    svc = EnrichService(node)
    node.ingest.enrich_service = svc
    EnrichProcessorImpl.install()
    return svc
