"""SQL wire client + CLI.

Reference: `x-pack/plugin/sql/jdbc/` and `x-pack/plugin/sql/sql-cli/`.
The reference's JDBC driver is NOT a custom socket protocol — it speaks
HTTP `POST /_sql` with a BINARY content type (CBOR) and pages results
through opaque cursors (`JdbcHttpClient` → `RestSqlQueryAction`); sql-cli
is a terminal REPL over the same wire. This module is that pair:

* `SqlWireClient` — binary CBOR request/response bodies (the xcontent
  layer this framework already negotiates), cursor paging, cursor close
  on early exit. A packet capture of this client shows no JSON on the
  wire — the JDBC-lite property.
* `main()` — `python -m elasticsearch_tpu.sql_cli --url http://... "
  SELECT ..."`: one-shot or stdin REPL, text-table output like sql-cli.
"""

from __future__ import annotations

import sys
import urllib.request
from typing import Any, Dict, Iterator, List, Optional

from elasticsearch_tpu.common import xcontent

CBOR = "application/cbor"


class SqlWireClient:
    """JDBC-lite: `/_sql` over binary CBOR with cursor paging."""

    def __init__(self, base_url: str, timeout: float = 30.0,
                 ssl_context=None, headers: Optional[Dict[str, str]] = None):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.ssl_context = ssl_context
        self.headers = dict(headers or {})

    def _post(self, path: str, body: dict) -> dict:
        raw = xcontent.dumps(body, xcontent.XContentType.CBOR)
        req = urllib.request.Request(
            self.base_url + path, data=raw, method="POST",
            headers={"Content-Type": CBOR, "Accept": CBOR, **self.headers})
        kw = {"timeout": self.timeout}
        if self.ssl_context is not None:
            kw["context"] = self.ssl_context
        with urllib.request.urlopen(req, **kw) as resp:
            ct = resp.headers.get("Content-Type", "")
            data = resp.read()
        return xcontent.loads(data, xcontent.XContentType.from_media_type(ct))

    def query(self, sql: str, fetch_size: int = 1000,
              params: Optional[List[Any]] = None) -> "SqlResultSet":
        body: Dict[str, Any] = {"query": sql, "fetch_size": fetch_size}
        if params:
            body["params"] = params
        return SqlResultSet(self, self._post("/_sql", body))

    def close_cursor(self, cursor: str) -> bool:
        out = self._post("/_sql/close", {"cursor": cursor})
        return bool(out.get("succeeded"))


class SqlResultSet:
    """Streaming rows across cursor pages (the JDBC ResultSet analog)."""

    def __init__(self, client: SqlWireClient, first_page: dict):
        self.client = client
        self.columns = first_page.get("columns", [])
        self._rows: List[list] = list(first_page.get("rows", []))
        self._cursor = first_page.get("cursor")
        self.closed = False

    def __iter__(self) -> Iterator[list]:
        """Forward-only, like a JDBC ResultSet: rows are consumed from the
        buffer as they are yielded, so a second (or resumed) iteration
        continues where the previous one stopped instead of replaying the
        buffered page."""
        while True:
            while self._rows:
                yield self._rows.pop(0)
            if not self._cursor:
                return
            page = self.client._post("/_sql", {"cursor": self._cursor})
            self._rows = list(page.get("rows", []))
            self._cursor = page.get("cursor")

    def close(self) -> None:
        """Release the server-side cursor without draining (JDBC
        ResultSet.close on early exit)."""
        if self._cursor and not self.closed:
            self.client.close_cursor(self._cursor)
            self._cursor = None
        self.closed = True


def _text_table(columns: List[dict], rows: List[list]) -> str:
    names = [c.get("name", "?") for c in columns]
    widths = [len(n) for n in names]
    rendered = [[("" if v is None else str(v)) for v in r] for r in rows]
    for r in rendered:
        for i, v in enumerate(r):
            widths[i] = max(widths[i], len(v))
    def fmt(vals):
        return " | ".join(v.ljust(w) for v, w in zip(vals, widths))
    lines = [fmt(names), "-+-".join("-" * w for w in widths)]
    lines += [fmt(r) for r in rendered]
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="elasticsearch-tpu-sql",
        description="SQL CLI over the binary /_sql wire (sql-cli analog)")
    parser.add_argument("--url", default="http://127.0.0.1:9200")
    parser.add_argument("--fetch-size", type=int, default=1000)
    parser.add_argument("sql", nargs="?", help="one-shot statement; "
                        "omit for a stdin REPL")
    args = parser.parse_args(argv)
    client = SqlWireClient(args.url)

    def run(stmt: str) -> None:
        rs = client.query(stmt, fetch_size=args.fetch_size)
        print(_text_table(rs.columns, list(rs)))

    if args.sql:
        run(args.sql)
        return 0
    for line in sys.stdin:
        stmt = line.strip().rstrip(";")
        if not stmt:
            continue
        if stmt.lower() in ("exit", "quit"):
            break
        try:
            run(stmt)
        except Exception as e:  # noqa: BLE001 — REPL keeps going
            print(f"error: {e}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
