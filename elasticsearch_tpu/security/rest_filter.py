"""Security REST integration: the filter + `_security/*` endpoints.

Reference: `SecurityRestFilter.java:30` wraps every REST handler (authn),
`SecurityActionFilter.java:42` authorizes; the `_security` API handlers live
in `x-pack/plugin/security/.../rest/action/`. DLS/FLS composes by rewriting
the search body before the handler parses it — the single-process analog of
`SecurityIndexSearcherWrapper` wrapping the shard searcher.
"""

from __future__ import annotations

import json

from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.rest.controller import RestController, RestRequest
from elasticsearch_tpu.security.service import (
    Authentication,
    SecurityService,
)

#: paths reachable without credentials (reference: `RestRequestFilter`
#: allowlist — only the root banner and _security/_authenticate error path)
_ANONYMOUS_PATHS = set()


def make_security_filter(svc: SecurityService):
    def security_filter(req: RestRequest):
        if not svc.enabled:
            return None
        auth = svc.authenticate(req.headers)   # raises 401 → controller renders
        req.context["authentication"] = auth
        index_param = req.params.get("index")
        svc.authorize(auth, req.method, req.path, index_param)
        _maybe_rewrite_for_dls_fls(svc, auth, req, index_param)
        return None
    return security_filter


def _maybe_rewrite_for_dls_fls(svc: SecurityService, auth: Authentication,
                               req: RestRequest, index_param) -> None:
    if auth.is_superuser or index_param is None:
        return
    is_search = req.path.endswith(("_search", "_count", "_async_search")) or \
        "_search/template" in req.path
    if not is_search:
        return
    indices = index_param.split(",")
    # restrictions are per-index; for multi-index requests apply the union
    # of each index's rewrite only when all indices share the restrictions
    body = {}
    if req.raw_body:
        try:
            body = json.loads(req.raw_body)
        except ValueError:
            return
    rewritten = body
    for index in indices:
        rewritten = svc.rewrite_search_body(auth, index, rewritten)
    if rewritten is not body:
        req.raw_body = json.dumps(rewritten).encode()


def register_security(rc: RestController, node) -> None:
    svc: SecurityService = node.security

    def authenticate(req):
        auth: Authentication = req.context.get("authentication")
        if auth is None:
            # security disabled: report the anonymous built-in like the
            # reference does with a disabled realm chain
            return 200, {"username": "_anonymous", "roles": ["superuser"],
                         "authentication_type": "anonymous", "enabled": True}
        return 200, {"username": auth.username, "roles": auth.role_names,
                     "authentication_type": auth.auth_type, "enabled": True}

    rc.register("GET", "/_security/_authenticate", authenticate)

    # ------------------------------------------------------------- users
    def put_user(req):
        created = svc.store.put_user(req.params["name"], req.json() or {})
        return 200, {"created": created}

    def get_user(req):
        name = req.params.get("name")
        if name:
            return 200, {name: svc.store.get_user(name)}
        return 200, {n: svc.store.get_user(n) for n in svc.store.users}

    def delete_user(req):
        svc.store.delete_user(req.params["name"])
        return 200, {"found": True}

    def change_password(req):
        body = req.json() or {}
        pw = body.get("password")
        if not pw:
            raise IllegalArgumentError("password is required")
        name = req.params.get("name")
        if name is None:
            auth = req.context.get("authentication")
            if auth is None:
                raise IllegalArgumentError("no user in context")
            name = auth.username
        svc.store.change_password(name, pw)
        return 200, {}

    def enable_user(req):
        svc.store.set_enabled(req.params["name"], True)
        return 200, {}

    def disable_user(req):
        svc.store.set_enabled(req.params["name"], False)
        return 200, {}

    rc.register("PUT", "/_security/user/{name}", put_user)
    rc.register("POST", "/_security/user/{name}", put_user)
    rc.register("GET", "/_security/user/{name}", get_user)
    rc.register("GET", "/_security/user", get_user)
    rc.register("DELETE", "/_security/user/{name}", delete_user)
    rc.register("PUT", "/_security/user/{name}/_password", change_password)
    rc.register("POST", "/_security/user/{name}/_password", change_password)
    rc.register("PUT", "/_security/user/_password", change_password)
    rc.register("POST", "/_security/user/_password", change_password)
    rc.register("PUT", "/_security/user/{name}/_enable", enable_user)
    rc.register("POST", "/_security/user/{name}/_enable", enable_user)
    rc.register("PUT", "/_security/user/{name}/_disable", disable_user)
    rc.register("POST", "/_security/user/{name}/_disable", disable_user)

    # ------------------------------------------------------------- roles
    def put_role(req):
        created = svc.store.put_role(req.params["name"], req.json() or {})
        return 200, {"role": {"created": created}}

    def get_role(req):
        name = req.params.get("name")
        if name:
            return 200, {name: svc.store.get_role(name)}
        from elasticsearch_tpu.security.store import RESERVED_ROLES
        out = dict(RESERVED_ROLES)
        out.update(svc.store.roles)
        return 200, out

    def delete_role(req):
        svc.store.delete_role(req.params["name"])
        return 200, {"found": True}

    rc.register("PUT", "/_security/role/{name}", put_role)
    rc.register("POST", "/_security/role/{name}", put_role)
    rc.register("GET", "/_security/role/{name}", get_role)
    rc.register("GET", "/_security/role", get_role)
    rc.register("DELETE", "/_security/role/{name}", delete_role)

    # ---------------------------------------------------------- API keys
    def create_api_key(req):
        auth = req.context.get("authentication")
        if auth is None:
            # security disabled — synthesize the anonymous superuser
            auth = Authentication("_anonymous",
                                  [{"cluster": ["all"],
                                    "indices": [{"names": ["*"],
                                                 "privileges": ["all"]}]}],
                                  ["superuser"])
        return 200, svc.create_api_key(auth, req.json() or {})

    def get_api_key(req):
        return 200, svc.get_api_keys(key_id=req.param("id"),
                                     owner=req.param("username"))

    def invalidate_api_key(req):
        body = req.json() or {}
        ids = body.get("ids") or ([body["id"]] if "id" in body else None)
        return 200, svc.invalidate_api_keys(ids=ids, name=body.get("name"),
                                            owner=body.get("username"))

    rc.register("PUT", "/_security/api_key", create_api_key)
    rc.register("POST", "/_security/api_key", create_api_key)
    rc.register("GET", "/_security/api_key", get_api_key)
    rc.register("DELETE", "/_security/api_key", invalidate_api_key)

    # ------------------------------------------------- OAuth2 token service
    def create_token(req):
        return 200, svc.tokens.grant(
            req.json() or {}, svc,
            authentication=req.context.get("authentication"))

    def invalidate_token(req):
        body = req.json() or {}
        out = svc.tokens.invalidate(token=body.get("token"),
                                    refresh_token=body.get("refresh_token"),
                                    username=body.get("username"),
                                    realm=body.get("realm_name"))
        return 200, out

    rc.register("POST", "/_security/oauth2/token", create_token)
    rc.register("DELETE", "/_security/oauth2/token", invalidate_token)
