"""Privilege model + route → required-privilege classification.

Reference: `x-pack/plugin/core/.../security/authz/privilege/ClusterPrivilege.java`
and `IndexPrivilege.java` define named privilege sets; `RBACEngine` checks a
request's action name against them. Here REST routes are classified directly
(the single-process analog of action-name matching).
"""

from __future__ import annotations

import fnmatch
from typing import FrozenSet, List, Optional, Tuple

# -- cluster privileges (subset of ClusterPrivilege.java's registry) ---------
CLUSTER_ALL = "all"
CLUSTER_MONITOR = "monitor"
CLUSTER_MANAGE = "manage"
CLUSTER_MANAGE_SECURITY = "manage_security"
CLUSTER_MANAGE_ILM = "manage_ilm"
CLUSTER_MANAGE_PIPELINE = "manage_pipeline"
CLUSTER_MANAGE_WATCHER = "manage_watcher"
CLUSTER_MANAGE_ML = "manage_ml"
CLUSTER_MANAGE_TRANSFORM = "manage_transform"
CLUSTER_MANAGE_CCR = "manage_ccr"
CLUSTER_MANAGE_ROLLUP = "manage_rollup"

#: which named cluster privileges imply which others
_CLUSTER_IMPLIES = {
    CLUSTER_ALL: {CLUSTER_MONITOR, CLUSTER_MANAGE, CLUSTER_MANAGE_SECURITY,
                  CLUSTER_MANAGE_ILM, CLUSTER_MANAGE_PIPELINE,
                  CLUSTER_MANAGE_WATCHER, CLUSTER_MANAGE_ML,
                  CLUSTER_MANAGE_TRANSFORM, CLUSTER_MANAGE_CCR,
                  CLUSTER_MANAGE_ROLLUP},
    CLUSTER_MANAGE: {CLUSTER_MONITOR, CLUSTER_MANAGE_ILM,
                     CLUSTER_MANAGE_PIPELINE, CLUSTER_MANAGE_ROLLUP},
}

# -- index privileges (IndexPrivilege.java) ----------------------------------
IDX_ALL = "all"
IDX_READ = "read"
IDX_WRITE = "write"
IDX_INDEX = "index"
IDX_CREATE = "create"
IDX_DELETE = "delete"
IDX_CREATE_INDEX = "create_index"
IDX_DELETE_INDEX = "delete_index"
IDX_MANAGE = "manage"
IDX_VIEW_METADATA = "view_index_metadata"
IDX_MONITOR = "monitor"

_INDEX_IMPLIES = {
    IDX_ALL: {IDX_READ, IDX_WRITE, IDX_INDEX, IDX_CREATE, IDX_DELETE,
              IDX_CREATE_INDEX, IDX_DELETE_INDEX, IDX_MANAGE,
              IDX_VIEW_METADATA, IDX_MONITOR},
    IDX_WRITE: {IDX_INDEX, IDX_CREATE, IDX_DELETE},
    IDX_MANAGE: {IDX_CREATE_INDEX, IDX_DELETE_INDEX, IDX_VIEW_METADATA,
                 IDX_MONITOR},
}


def expand_cluster(privs) -> FrozenSet[str]:
    out = set(privs)
    for p in list(out):
        out |= _CLUSTER_IMPLIES.get(p, set())
    return frozenset(out)


def expand_index(privs) -> FrozenSet[str]:
    out = set(privs)
    for p in list(out):
        out |= _INDEX_IMPLIES.get(p, set())
    return frozenset(out)


def index_pattern_matches(patterns: List[str], index: str) -> bool:
    return any(fnmatch.fnmatchcase(index, p) for p in patterns)


class RouteRequirement:
    """What a request needs: either a cluster privilege, or an index
    privilege on each target index."""

    def __init__(self, cluster: Optional[str] = None,
                 index_priv: Optional[str] = None,
                 indices: Optional[List[str]] = None):
        self.cluster = cluster
        self.index_priv = index_priv
        self.indices = indices or []


# path-prefix → cluster privilege. Checked before index classification.
_CLUSTER_ROUTES: List[Tuple[str, str]] = [
    ("_security", CLUSTER_MANAGE_SECURITY),
    ("_ilm", CLUSTER_MANAGE_ILM),
    ("_slm", CLUSTER_MANAGE_ILM),
    ("_ingest", CLUSTER_MANAGE_PIPELINE),
    ("_watcher", CLUSTER_MANAGE_WATCHER),
    ("_ml", CLUSTER_MANAGE_ML),
    ("_transform", CLUSTER_MANAGE_TRANSFORM),
    ("_ccr", CLUSTER_MANAGE_CCR),
    ("_rollup", CLUSTER_MANAGE_ROLLUP),
    ("_snapshot", CLUSTER_MANAGE),
    ("_scripts", CLUSTER_MANAGE),
    ("_template", CLUSTER_MANAGE),
    ("_index_template", CLUSTER_MANAGE),
    ("_cluster", CLUSTER_MONITOR),
    ("_nodes", CLUSTER_MONITOR),
    ("_cat", CLUSTER_MONITOR),
    ("_tasks", CLUSTER_MONITOR),
    ("_remote", CLUSTER_MONITOR),
]

#: index-API suffixes that only read
_READ_SUFFIXES = {"_search", "_count", "_msearch", "_mget", "_explain",
                  "_field_caps", "_validate", "_rank_eval", "_termvectors",
                  "_source", "_analyze", "_search/template", "_msearch/template",
                  "_async_search", "_graph", "_eql", "_pit", "_knn_search"}
#: suffixes that write documents
_WRITE_SUFFIXES = {"_doc", "_create", "_update", "_bulk", "_update_by_query",
                   "_delete_by_query"}
#: suffixes that manage the index
_MANAGE_SUFFIXES = {"_mapping", "_settings", "_alias", "_aliases", "_refresh",
                    "_flush", "_forcemerge", "_open", "_close", "_rollover",
                    "_shrink", "_split", "_clone", "_freeze", "_unfreeze"}
#: suffixes that only view metadata / stats
_MONITOR_SUFFIXES = {"_stats", "_segments", "_recovery", "_shard_stores"}

# cluster-level read endpoints that fan out over indices (no {index} in path)
_GLOBAL_READ_PREFIXES = {"_search", "_count", "_msearch", "_mget",
                         "_field_caps", "_rank_eval", "_render", "_async_search",
                         "_eql", "_sql", "_validate", "_analyze", "_aliases",
                         "_alias", "_mapping", "_settings", "_resolve",
                         "_reindex", "_scripts"}


def classify(method: str, path: str,
             index_param: Optional[str]) -> RouteRequirement:
    """Map a request to its required privilege.

    Reference analog: each TransportAction's name (`indices:data/read/search`,
    `cluster:admin/...`) determines the privilege; here the REST route shape
    does, which the 124-handler surface makes 1:1.
    """
    segs = [s for s in path.split("/") if s]
    if not segs:
        return RouteRequirement(cluster=CLUSTER_MONITOR)
    if index_param is None and segs[0].startswith("_"):
        # any authenticated principal may introspect itself / change its own
        # password (reference: RestAuthenticateAction and
        # RestChangePasswordAction run as the current user)
        if path.rstrip("/").endswith("_security/_authenticate") or \
                path.rstrip("/").endswith("_security/user/_password"):
            return RouteRequirement(index_priv=None, indices=[])
        for prefix, priv in _CLUSTER_ROUTES:
            if segs[0] == prefix:
                return RouteRequirement(cluster=priv)
        if segs[0] == "_reindex":
            # reindex touches source+dest; conservatively require write on all
            return RouteRequirement(index_priv=IDX_WRITE, indices=["*"])
        # global search-ish endpoints read across all indices
        if segs[0] in _GLOBAL_READ_PREFIXES or segs[0] in _READ_SUFFIXES:
            if method in ("PUT", "POST", "DELETE") and segs[0] in (
                    "_aliases", "_settings", "_scripts"):
                return RouteRequirement(cluster=CLUSTER_MANAGE)
            return RouteRequirement(index_priv=IDX_READ, indices=["*"])
        if segs[0] == "_bulk":
            return RouteRequirement(index_priv=IDX_WRITE, indices=["*"])
        return RouteRequirement(cluster=CLUSTER_MONITOR)

    indices = (index_param or "*").split(",")
    api = next((s for s in segs if s.startswith("_")), None)
    if api is None:
        # bare /{index} — index admin (create/delete/get)
        if method == "PUT":
            return RouteRequirement(index_priv=IDX_CREATE_INDEX, indices=indices)
        if method == "DELETE":
            return RouteRequirement(index_priv=IDX_DELETE_INDEX, indices=indices)
        return RouteRequirement(index_priv=IDX_VIEW_METADATA, indices=indices)
    if api in _WRITE_SUFFIXES:
        if api == "_doc" and method in ("GET", "HEAD"):
            return RouteRequirement(index_priv=IDX_READ, indices=indices)
        return RouteRequirement(index_priv=IDX_WRITE, indices=indices)
    if api in _READ_SUFFIXES:
        return RouteRequirement(index_priv=IDX_READ, indices=indices)
    if api in _MANAGE_SUFFIXES:
        if method in ("GET", "HEAD"):
            return RouteRequirement(index_priv=IDX_VIEW_METADATA, indices=indices)
        return RouteRequirement(index_priv=IDX_MANAGE, indices=indices)
    if api in _MONITOR_SUFFIXES:
        return RouteRequirement(index_priv=IDX_MONITOR, indices=indices)
    return RouteRequirement(index_priv=IDX_READ, indices=indices)
