"""OAuth2-style token service.

Reference: `x-pack/plugin/security/src/main/java/org/elasticsearch/xpack/
security/authc/TokenService.java:1` — access tokens (default 20 min TTL)
granted from realm credentials via `POST /_security/oauth2/token`, used as
`Authorization: Bearer <token>`, paired with single-use refresh tokens
(24 h) that rotate both on refresh; invalidation by token, refresh token,
user, or realm.

Storage rides the security store as hashed records only — presenting a
stored hash must never authenticate (the FileRealm pass-the-hash lesson),
so the wire token is `<id>.<secret>` (urlsafe) and the store keeps
sha256(secret). The reference encrypts tokens with a node key and stores
them in the `.security-tokens` index; hashing gives the same property the
test suite needs (leaked store ≠ leaked credentials) without a key
distribution story.
"""

from __future__ import annotations

import hashlib
import secrets
import time
from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentError

ACCESS_TTL_S = 20 * 60       # reference default: 20 minutes
REFRESH_TTL_S = 24 * 3600    # refresh window: 24 hours


def _hash(secret: str) -> str:
    return hashlib.sha256(secret.encode()).hexdigest()


class TokenService:
    def __init__(self, store):
        self.store = store

    # ------------------------------------------------------------- grants
    def grant(self, body: dict, security, authentication=None) -> dict:
        """`POST /_security/oauth2/token` — grant_type password (realm
        credentials), refresh_token, or client_credentials (the already-
        authenticated caller passed as `authentication`; no refresh token,
        matching the reference)."""
        grant_type = (body or {}).get("grant_type")
        if grant_type == "password":
            username = body.get("username")
            password = body.get("password")
            if not username or password is None:
                raise IllegalArgumentError(
                    "username and password are required for grant_type "
                    "[password]")
            import base64
            basic = base64.b64encode(
                f"{username}:{password}".encode()).decode()
            auth = security.authenticate({"authorization": f"Basic {basic}"})
            return self._issue(auth.username, auth.role_names,
                               realm=auth.realm or "native",
                               with_refresh=True)
        if grant_type == "refresh_token":
            token = body.get("refresh_token")
            if not token:
                raise IllegalArgumentError("refresh_token is required")
            return self.refresh(token)
        if grant_type == "client_credentials":
            if authentication is None:
                raise IllegalArgumentError(
                    "client_credentials requires an authenticated caller")
            return self._issue(authentication.username,
                               authentication.role_names,
                               realm=authentication.realm or "native",
                               with_refresh=False)
        raise IllegalArgumentError(
            f"unsupported grant_type [{grant_type}]")

    def _issue(self, username: str, role_names: List[str], realm: str,
               with_refresh: bool) -> dict:
        self._sweep()
        tid = secrets.token_urlsafe(9)
        access_secret = secrets.token_urlsafe(24)
        refresh_secret = secrets.token_urlsafe(24) if with_refresh else None
        now = time.time()
        self.store.tokens[tid] = {
            "access_hash": _hash(access_secret),
            "refresh_hash": _hash(refresh_secret) if refresh_secret else None,
            "username": username,
            "roles": list(role_names),
            "realm": realm,
            "created": now,
            "access_expires": now + ACCESS_TTL_S,
            # without a refresh token the record is dead once the access
            # token expires — sweep it then, not 24h later
            "refresh_expires": now + (REFRESH_TTL_S if with_refresh
                                      else ACCESS_TTL_S),
            "invalidated": False,
            "refreshed": False,
        }
        self.store.persist()
        out = {"access_token": f"{tid}.{access_secret}",
               "type": "Bearer", "expires_in": ACCESS_TTL_S}
        if refresh_secret:
            out["refresh_token"] = f"{tid}.{refresh_secret}"
        return out

    # ---------------------------------------------------------------- use
    def authenticate_bearer(self, token: str) -> Optional[dict]:
        """Record for a live access token, else None (expired, invalidated,
        unknown, or malformed all fall through to a 401 at the caller)."""
        _tid, rec = self._lookup(token, "access_hash")
        if rec is None or rec["invalidated"]:
            return None
        if time.time() > rec["access_expires"]:
            return None
        return rec

    def _lookup(self, token: str, hash_field: str):
        """(token_id, record) for a hash-matching token, else (None, None).
        Comparison is constant-time."""
        tid, _, secret = (token or "").partition(".")
        if not tid or not secret:
            return None, None
        rec = self.store.tokens.get(tid)
        if rec is None or not rec.get(hash_field):
            return None, None
        import hmac as _hmac
        if not _hmac.compare_digest(rec[hash_field], _hash(secret)):
            return None, None
        return tid, rec

    # ------------------------------------------------------------- refresh
    def refresh(self, refresh_token: str) -> dict:
        """Single-use rotation: the old pair invalidates, a fresh pair
        issues (TokenService.refreshToken)."""
        _tid, rec = self._lookup(refresh_token, "refresh_hash")
        if rec is None:
            raise IllegalArgumentError("invalid refresh token")
        if rec["refreshed"]:
            # reuse of a rotated refresh token: the reference treats this
            # as an attack signal and invalidates the user's chain
            self.invalidate(username=rec["username"])
            raise IllegalArgumentError("refresh token already used")
        if rec["invalidated"]:
            raise IllegalArgumentError("invalid refresh token")
        if time.time() > rec["refresh_expires"]:
            raise IllegalArgumentError("refresh token is expired")
        rec["refreshed"] = True
        rec["invalidated"] = True
        # _issue persists, covering the old record's mutation too
        return self._issue(rec["username"], rec["roles"], rec["realm"],
                           with_refresh=True)

    # ---------------------------------------------------------- invalidate
    def invalidate(self, token: Optional[str] = None,
                   refresh_token: Optional[str] = None,
                   username: Optional[str] = None,
                   realm: Optional[str] = None) -> dict:
        """`DELETE /_security/oauth2/token` by access token, refresh
        token, username, or realm. At least one criterion is required
        (the reference 400s an empty invalidation request)."""
        if token is None and refresh_token is None \
                and username is None and realm is None:
            raise IllegalArgumentError(
                "one of [token, refresh_token, username, realm_name] is "
                "required")
        hit_ids: List[str] = []
        if token is not None:
            tid, rec = self._lookup(token, "access_hash")
            if rec is not None:
                hit_ids.append(tid)
        if refresh_token is not None:
            tid, rec = self._lookup(refresh_token, "refresh_hash")
            if rec is not None:
                hit_ids.append(tid)
        if username is not None or realm is not None:
            for tid, rec in self.store.tokens.items():
                if username is not None and rec["username"] != username:
                    continue
                if realm is not None and rec["realm"] != realm:
                    continue
                hit_ids.append(tid)
        invalidated, previously = [], []
        for tid in dict.fromkeys(hit_ids):  # dedupe, keep order
            rec = self.store.tokens[tid]
            if rec["invalidated"]:
                previously.append(tid)
            else:
                rec["invalidated"] = True
                invalidated.append(tid)
        self.store.persist()
        return {"invalidated_tokens": len(invalidated),
                "previously_invalidated_tokens": len(previously),
                "error_count": 0}

    def _sweep(self) -> None:
        """Opportunistic purge of records past their refresh window (both
        lifetimes over) — the ExpiredTokenRemover analog, run on every
        issue so store.tokens stays bounded by live-token churn."""
        now = time.time()
        dead = [tid for tid, rec in self.store.tokens.items()
                if now > rec["refresh_expires"]]
        for tid in dead:
            del self.store.tokens[tid]
