"""Native user + role stores with PBKDF2 password hashing.

Reference: `x-pack/plugin/security/.../authc/esnative/NativeUsersStore.java`
(users in the `.security` index), `authz/store/NativeRolesStore.java`,
`ReservedRolesStore.java` (builtin roles), `authc/support/Hasher.java`
(bcrypt/pbkdf2 — pbkdf2 here). Persistence is a JSON file under the node
state dir, the single-process analog of the `.security` system index.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import secrets
from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError,
    ResourceNotFoundError,
)

_PBKDF2_ITERS = 5000  # reference default is 10000; lower keeps tests snappy


def hash_password(password: str, salt: Optional[bytes] = None) -> str:
    salt = salt or secrets.token_bytes(16)
    dk = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, _PBKDF2_ITERS)
    return "{PBKDF2}" + base64.b64encode(salt).decode() + "$" + base64.b64encode(dk).decode()


def verify_password(password: str, hashed: str) -> bool:
    if not hashed.startswith("{PBKDF2}"):
        return False
    try:
        salt_b64, dk_b64 = hashed[len("{PBKDF2}"):].split("$", 1)
        salt = base64.b64decode(salt_b64)
        expect = base64.b64decode(dk_b64)
    except Exception:
        return False
    dk = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, _PBKDF2_ITERS)
    return secrets.compare_digest(dk, expect)


#: builtin roles (ReservedRolesStore.java) — superuser gets everything
RESERVED_ROLES: Dict[str, dict] = {
    "superuser": {
        "cluster": ["all"],
        "indices": [{"names": ["*"], "privileges": ["all"]}],
    },
    "monitoring_user": {
        "cluster": ["monitor"],
        "indices": [{"names": ["*"], "privileges": ["monitor"]}],
    },
    "viewer": {
        "cluster": [],
        "indices": [{"names": ["*"], "privileges": ["read", "view_index_metadata"]}],
    },
    "editor": {
        "cluster": [],
        "indices": [{"names": ["*"], "privileges": ["read", "write",
                                                    "view_index_metadata"]}],
    },
}


class SecurityStore:
    """Users + roles + API-key records, persisted as one JSON document."""

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self.users: Dict[str, dict] = {}
        self.roles: Dict[str, dict] = {}
        self.api_keys: Dict[str, dict] = {}
        self.tokens: Dict[str, dict] = {}  # TokenService records (hashed)
        if path and os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
            self.users = data.get("users", {})
            self.roles = data.get("roles", {})
            self.api_keys = data.get("api_keys", {})
            self.tokens = data.get("tokens", {})

    def persist(self) -> None:
        if not self._path:
            return
        os.makedirs(os.path.dirname(self._path), exist_ok=True)
        with open(self._path, "w") as f:
            json.dump({"users": self.users, "roles": self.roles,
                       "api_keys": self.api_keys, "tokens": self.tokens}, f)

    # -- users ---------------------------------------------------------------
    def put_user(self, username: str, body: dict) -> bool:
        existing = username in self.users
        record = self.users.get(username, {})
        if "password" in body:
            pw = body["password"]
            if not isinstance(pw, str) or len(pw) < 6:
                raise IllegalArgumentError(
                    "passwords must be at least [6] characters long")
            record["password_hash"] = hash_password(pw)
        elif not existing:
            raise IllegalArgumentError("password is required for new users")
        record["roles"] = body.get("roles", record.get("roles", []))
        record["full_name"] = body.get("full_name", record.get("full_name"))
        record["email"] = body.get("email", record.get("email"))
        record["metadata"] = body.get("metadata", record.get("metadata", {}))
        record.setdefault("enabled", True)
        self.users[username] = record
        self.persist()
        return not existing

    def get_user(self, username: str) -> dict:
        if username not in self.users:
            raise ResourceNotFoundError(f"user [{username}] not found")
        u = self.users[username]
        return {"username": username, "roles": u.get("roles", []),
                "full_name": u.get("full_name"), "email": u.get("email"),
                "metadata": u.get("metadata", {}),
                "enabled": u.get("enabled", True)}

    def delete_user(self, username: str) -> None:
        if username not in self.users:
            raise ResourceNotFoundError(f"user [{username}] not found")
        del self.users[username]
        self.persist()

    def set_enabled(self, username: str, enabled: bool) -> None:
        if username not in self.users:
            raise ResourceNotFoundError(f"user [{username}] not found")
        self.users[username]["enabled"] = enabled
        self.persist()

    def change_password(self, username: str, password: str) -> None:
        if username not in self.users:
            raise ResourceNotFoundError(f"user [{username}] not found")
        if len(password) < 6:
            raise IllegalArgumentError(
                "passwords must be at least [6] characters long")
        self.users[username]["password_hash"] = hash_password(password)
        self.persist()

    def authenticate(self, username: str, password: str) -> Optional[dict]:
        u = self.users.get(username)
        if u is None or not u.get("enabled", True):
            return None
        if not verify_password(password, u.get("password_hash", "")):
            return None
        return self.get_user(username)

    # -- roles ---------------------------------------------------------------
    def put_role(self, name: str, body: dict) -> bool:
        if name in RESERVED_ROLES:
            raise IllegalArgumentError(f"role [{name}] is reserved")
        existing = name in self.roles
        self.roles[name] = {
            "cluster": body.get("cluster", []),
            "indices": body.get("indices", []),
            "metadata": body.get("metadata", {}),
        }
        self.persist()
        return not existing

    def get_role(self, name: str) -> dict:
        if name in RESERVED_ROLES:
            return RESERVED_ROLES[name]
        if name not in self.roles:
            raise ResourceNotFoundError(f"role [{name}] not found")
        return self.roles[name]

    def delete_role(self, name: str) -> None:
        if name in RESERVED_ROLES:
            raise IllegalArgumentError(f"role [{name}] is reserved")
        if name not in self.roles:
            raise ResourceNotFoundError(f"role [{name}] not found")
        del self.roles[name]
        self.persist()

    def resolve_roles(self, names: List[str]) -> List[dict]:
        out = []
        for n in names:
            if n in RESERVED_ROLES:
                out.append(RESERVED_ROLES[n])
            elif n in self.roles:
                out.append(self.roles[n])
            # unknown roles are skipped, like the reference (missing role ==
            # no privileges, not an error)
        return out
