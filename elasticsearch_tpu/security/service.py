"""SecurityService: authentication + RBAC authorization + audit.

Reference composition (§2.11 "Hook mechanism"): security wraps layers 4-6
without touching them — `SecurityRestFilter.java:30` authenticates every REST
request, `SecurityActionFilter.java:42` authorizes the action, and the
authenticated user propagates in thread context. Here one REST filter does
both (the REST route is 1:1 with the action in this stack), plus request-body
rewriting for document/field-level security.
"""

from __future__ import annotations

import base64
import hashlib
import json
import secrets
import time
from typing import Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError,
    SearchEngineError,
)
from elasticsearch_tpu.security import privileges as priv
from elasticsearch_tpu.security.store import SecurityStore, hash_password, verify_password


class AuthenticationError(SearchEngineError):
    status = 401

    @property
    def error_type(self) -> str:
        return "security_exception"


class AuthorizationError(SearchEngineError):
    status = 403

    @property
    def error_type(self) -> str:
        return "security_exception"


class Authentication:
    """The authenticated principal + its resolved roles."""

    def __init__(self, username: str, roles: List[dict], role_names: List[str],
                 auth_type: str = "realm", api_key_id: Optional[str] = None,
                 realm: Optional[str] = None):
        self.username = username
        self.roles = roles
        self.role_names = role_names
        self.auth_type = auth_type
        self.api_key_id = api_key_id
        self.realm = realm  # name of the realm that authenticated, if any

    @property
    def is_superuser(self) -> bool:
        return any("all" in r.get("cluster", []) for r in self.roles)


class SecurityService:
    def __init__(self, store: SecurityStore, enabled: bool = True,
                 bootstrap_password: str = "changeme",
                 realms: Optional[list] = None,
                 anonymous_roles: Optional[List[str]] = None):
        self.store = store
        self.enabled = enabled
        self.audit: List[dict] = []
        # ordered realm chain (InternalRealms); default: native only
        from elasticsearch_tpu.security.realms import NativeRealm
        self.realms = realms if realms is not None \
            else [NativeRealm("default_native", store)]
        # xpack.security.authc.anonymous.roles (AnonymousUser)
        self.anonymous_roles = anonymous_roles or []
        # OAuth2 token service (TokenService.java): Bearer auth + refresh
        from elasticsearch_tpu.security.tokens import TokenService
        self.tokens = TokenService(store)
        # reserved superuser, like the `elastic` user bootstrapped from the
        # keystore (`ReservedRealm.java`)
        if "elastic" not in store.users:
            store.users["elastic"] = {
                "password_hash": hash_password(bootstrap_password),
                "roles": ["superuser"], "enabled": True, "reserved": True,
            }
            store.persist()

    # ------------------------------------------------------------ audit
    def _audit(self, event: str, **fields) -> None:
        self.audit.append({"ts": time.time(), "event": event, **fields})
        if len(self.audit) > 10_000:
            del self.audit[:5_000]

    # ---------------------------------------------------------- API keys
    def create_api_key(self, auth: Authentication, body: dict) -> dict:
        name = body.get("name")
        if not name:
            raise IllegalArgumentError("api key name is required")
        key_id = secrets.token_urlsafe(12)
        key_secret = secrets.token_urlsafe(24)
        expiration = body.get("expiration")
        expires_at = None
        if expiration:
            from elasticsearch_tpu.common.settings import parse_time_value
            expires_at = time.time() + parse_time_value(expiration, "expiration")
        # role_descriptors restrict below the owner's roles; empty = inherit
        self.store.api_keys[key_id] = {
            "name": name,
            "hash": hashlib.sha256(key_secret.encode()).hexdigest(),
            "owner": auth.username,
            "owner_roles": auth.role_names,
            "role_descriptors": body.get("role_descriptors", {}),
            "created": time.time(),
            "expires_at": expires_at,
            "invalidated": False,
        }
        self.store.persist()
        self._audit("create_api_key", user=auth.username, key_id=key_id)
        encoded = base64.b64encode(f"{key_id}:{key_secret}".encode()).decode()
        return {"id": key_id, "name": name, "api_key": key_secret,
                "encoded": encoded,
                "expiration": int(expires_at * 1000) if expires_at else None}

    def invalidate_api_keys(self, ids: Optional[List[str]] = None,
                            name: Optional[str] = None,
                            owner: Optional[str] = None) -> dict:
        invalidated = []
        for kid, rec in self.store.api_keys.items():
            if rec["invalidated"]:
                continue
            if ids and kid not in ids:
                continue
            if name and rec["name"] != name:
                continue
            if owner and rec["owner"] != owner:
                continue
            if not (ids or name or owner):
                continue
            rec["invalidated"] = True
            invalidated.append(kid)
        self.store.persist()
        return {"invalidated_api_keys": invalidated,
                "previously_invalidated_api_keys": [], "error_count": 0}

    def get_api_keys(self, key_id: Optional[str] = None,
                     owner: Optional[str] = None) -> dict:
        out = []
        for kid, rec in self.store.api_keys.items():
            if key_id and kid != key_id:
                continue
            if owner and rec["owner"] != owner:
                continue
            out.append({"id": kid, "name": rec["name"],
                        "creation": int(rec["created"] * 1000),
                        "invalidated": rec["invalidated"],
                        "username": rec["owner"], "realm": "native"})
        return {"api_keys": out}

    # ------------------------------------------------------ authentication
    def authenticate(self, headers: Dict[str, str]) -> Authentication:
        header = headers.get("authorization", "")
        if header.startswith("Basic "):
            try:
                userpass = base64.b64decode(header[6:]).decode()
                username, _, password = userpass.partition(":")
            except Exception:
                raise AuthenticationError("failed to decode basic authentication header")
            user = None
            realm_name = None
            for realm in self.realms:
                user = realm.authenticate(username, password)
                if user is not None:
                    realm_name = realm.name
                    break
            if user is None:
                self._audit("authentication_failed", user=username)
                raise AuthenticationError(
                    f"unable to authenticate user [{username}] for REST request")
            roles = self.store.resolve_roles(user["roles"])
            self._audit("authentication_success", user=username,
                        realm=realm_name)
            return Authentication(username, roles, user["roles"],
                                  realm=realm_name)
        if header.startswith("Bearer "):
            rec = self.tokens.authenticate_bearer(header[7:].strip())
            if rec is None:
                self._audit("authentication_failed", token="bearer")
                raise AuthenticationError(
                    "unable to authenticate with provided token")
            roles = self.store.resolve_roles(rec["roles"])
            self._audit("authentication_success", user=rec["username"],
                        realm="token")
            # the token record remembers the ORIGINATING realm, so tokens
            # minted by a Bearer-authenticated caller stay attributed to it
            return Authentication(rec["username"], roles, rec["roles"],
                                  auth_type="token", realm=rec["realm"])
        if header.startswith("Negotiate "):
            try:
                ticket = base64.b64decode(header[10:].strip())
            except Exception:
                raise AuthenticationError(
                    "failed to decode negotiate authentication header")
            for realm in self.realms:
                validate = getattr(realm, "authenticate_ticket", None)
                if validate is None:
                    continue
                user = validate(ticket)
                if user is not None:
                    roles = self.store.resolve_roles(user["roles"])
                    self._audit("authentication_success",
                                user=user["username"], realm=realm.name)
                    return Authentication(user["username"], roles,
                                          user["roles"],
                                          auth_type="kerberos",
                                          realm=realm.name)
            self._audit("authentication_failed", token="negotiate")
            raise AuthenticationError(
                "unable to authenticate user with negotiate header")
        if header.startswith("ApiKey "):
            try:
                decoded = base64.b64decode(header[7:]).decode()
                key_id, _, key_secret = decoded.partition(":")
            except Exception:
                raise AuthenticationError("failed to decode API key header")
            rec = self.store.api_keys.get(key_id)
            if (rec is None or rec["invalidated"]
                    or rec["hash"] != hashlib.sha256(key_secret.encode()).hexdigest()):
                self._audit("authentication_failed", api_key_id=key_id)
                raise AuthenticationError("unable to authenticate with provided api key")
            if rec["expires_at"] and time.time() > rec["expires_at"]:
                raise AuthenticationError("api key is expired")
            if rec["role_descriptors"]:
                roles = [
                    {"cluster": d.get("cluster", []),
                     "indices": d.get("indices", d.get("index", []))}
                    for d in rec["role_descriptors"].values()
                ]
                role_names = list(rec["role_descriptors"].keys())
            else:
                roles = self.store.resolve_roles(rec["owner_roles"])
                role_names = rec["owner_roles"]
            self._audit("authentication_success", api_key_id=key_id)
            return Authentication(rec["owner"], roles, role_names,
                                  auth_type="api_key", api_key_id=key_id)
        if self.anonymous_roles:
            roles = self.store.resolve_roles(self.anonymous_roles)
            self._audit("authentication_success", user="_anonymous_")
            return Authentication("_anonymous_", roles,
                                  list(self.anonymous_roles),
                                  auth_type="anonymous")
        self._audit("anonymous_access_denied")
        raise AuthenticationError(
            "missing authentication credentials for REST request")

    # ------------------------------------------------------- authorization
    def authorize(self, auth: Authentication, method: str, path: str,
                  index_param: Optional[str]) -> priv.RouteRequirement:
        req = priv.classify(method, path, index_param)
        if req.cluster is not None:
            allowed = set()
            for role in auth.roles:
                allowed |= priv.expand_cluster(role.get("cluster", []))
            if req.cluster not in allowed:
                self._audit("access_denied", user=auth.username,
                            privilege=req.cluster, path=path)
                raise AuthorizationError(
                    f"action [cluster:{req.cluster}] is unauthorized for user "
                    f"[{auth.username}]")
        else:
            for index in req.indices:
                if not self._index_allowed(auth, index, req.index_priv):
                    self._audit("access_denied", user=auth.username,
                                privilege=req.index_priv, index=index, path=path)
                    raise AuthorizationError(
                        f"action [indices:{req.index_priv}] is unauthorized for "
                        f"user [{auth.username}] on index [{index}]")
        self._audit("access_granted", user=auth.username, path=path)
        return req

    def _index_allowed(self, auth: Authentication, index: str,
                       index_priv: str) -> bool:
        for role in auth.roles:
            for grant in role.get("indices", []):
                names = grant.get("names", [])
                if not priv.index_pattern_matches(names, index) and index != "*":
                    continue
                if index == "*" and names != ["*"]:
                    # searching all indices needs a wildcard grant
                    continue
                if index_priv in priv.expand_index(grant.get("privileges", [])):
                    return True
        return False

    # -------------------------------------- document/field-level security
    def restrictions_for(self, auth: Authentication,
                         index: str) -> Tuple[Optional[List[dict]], Optional[List[str]]]:
        """Collect DLS queries and FLS grant patterns that apply to `index`.

        Reference: `authz/accesscontrol/IndicesAccessControl` carries per-index
        DLS queries + FLS field permissions from the matched role grants.
        A grant with no restrictions wins (union semantics): if any matching
        grant is unrestricted, no restriction applies.
        """
        dls: List[dict] = []
        fls: List[str] = []
        unrestricted = False
        for role in auth.roles:
            for grant in role.get("indices", []):
                if not priv.index_pattern_matches(grant.get("names", []), index):
                    continue
                q = grant.get("query")
                f = grant.get("field_security", {}).get("grant")
                if q is None and f is None:
                    unrestricted = True
                if q is not None:
                    dls.append(json.loads(q) if isinstance(q, str) else q)
                if f is not None:
                    fls.extend(f)
        if unrestricted:
            return None, None
        return (dls or None), (fls or None)

    def rewrite_search_body(self, auth: Authentication, index: str,
                            body: dict) -> dict:
        """Apply DLS (wrap query in a bool filter) and FLS (_source
        includes) to a search body."""
        dls, fls = self.restrictions_for(auth, index)
        if dls is None and fls is None:
            return body
        body = dict(body or {})
        if dls:
            original = body.get("query", {"match_all": {}})
            body["query"] = {"bool": {"must": [original],
                                      "filter": [{"bool": {"should": dls,
                                                           "minimum_should_match": 1}}]}}
        if fls:
            body["_source"] = {"includes": fls}
        return body
