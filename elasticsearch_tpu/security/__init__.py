"""Security subsystem: authn realms (native users, API keys), RBAC authz,
document/field-level security, audit trail.

Reference: `x-pack/plugin/security` (§2.11) — composes onto the REST layer
via a filter without touching it.
"""

from elasticsearch_tpu.security.service import (
    Authentication,
    AuthenticationError,
    AuthorizationError,
    SecurityService,
)
from elasticsearch_tpu.security.store import SecurityStore

__all__ = ["Authentication", "AuthenticationError", "AuthorizationError",
           "SecurityService", "SecurityStore"]
