"""Authentication realms: ordered chain of credential sources.

Reference: `x-pack/plugin/security/.../authc/InternalRealms.java` registers
realm types (reserved, native, file, ldap, pki, saml, ...) and
`AuthenticationService` walks them in order until one authenticates. Here:

* `FileRealm` — users from the classic file-realm format: a `users` file of
  `username:password_hash` lines (also accepts plaintext for test
  fixtures) and a `users_roles` file of `role:user1,user2` lines
  (reference: `FileUserPasswdStore` / `FileUserRolesStore`).
* `NativeRealm` — the security index (SecurityStore) this stack already
  persists.

The chain resolves per `xpack.security.authc.realms.<type>.<name>.order`
settings; without explicit config the default chain is file (when the
files exist) then native, matching the reference's implicit realms.
"""

from __future__ import annotations

import hmac
import os
from typing import Dict, List, Optional

from elasticsearch_tpu.security.store import verify_password


class Realm:
    type_name = "realm"

    def __init__(self, name: str, order: int = 0):
        self.name = name
        self.order = order

    def authenticate(self, username: str, password: str) -> Optional[dict]:
        """User dict {"roles": [...]} on success, None to try the next
        realm (unknown user OR wrong password both fall through, like the
        reference's realm chain)."""
        raise NotImplementedError

    def lookup(self, username: str) -> Optional[dict]:
        return None


class FileRealm(Realm):
    type_name = "file"

    def __init__(self, name: str, users_path: str, roles_path: str,
                 order: int = 0):
        super().__init__(name, order)
        self.users_path = users_path
        self.roles_path = roles_path
        self._mtimes = (None, None)
        self._users: Dict[str, str] = {}
        self._roles: Dict[str, List[str]] = {}
        self._load()

    def _load(self) -> None:
        users: Dict[str, str] = {}
        roles: Dict[str, List[str]] = {}
        if os.path.exists(self.users_path):
            with open(self.users_path) as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith("#"):
                        continue
                    user, _, secret = line.partition(":")
                    users[user.strip()] = secret.strip()
        if os.path.exists(self.roles_path):
            with open(self.roles_path) as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith("#"):
                        continue
                    role, _, members = line.partition(":")
                    for user in members.split(","):
                        user = user.strip()
                        if user:
                            roles.setdefault(user, []).append(role.strip())
        self._users, self._roles = users, roles
        self._mtimes = tuple(
            os.path.getmtime(p) if os.path.exists(p) else None
            for p in (self.users_path, self.roles_path))

    def _maybe_reload(self) -> None:
        current = tuple(os.path.getmtime(p) if os.path.exists(p) else None
                        for p in (self.users_path, self.roles_path))
        if current != self._mtimes:  # hot reload (FileWatcher analog)
            self._load()

    def authenticate(self, username: str, password: str) -> Optional[dict]:
        self._maybe_reload()
        stored = self._users.get(username)
        if stored is None:
            return None
        # hashed entries ONLY verify as hashes — never as a literal string,
        # or a leaked users file becomes credential-equivalent (pass-the-
        # hash). Plaintext entries (test fixtures / `elasticsearch-users
        # useradd -p`) compare constant-time.
        if stored.startswith("{PBKDF2}"):
            ok = verify_password(password, stored)
        else:
            ok = hmac.compare_digest(password.encode(), stored.encode())
        if not ok:
            return None
        return {"roles": self._roles.get(username, []), "enabled": True}

    def lookup(self, username: str) -> Optional[dict]:
        self._maybe_reload()
        if username in self._users:
            return {"roles": self._roles.get(username, []), "enabled": True}
        return None


class NativeRealm(Realm):
    type_name = "native"

    def __init__(self, name: str, store, order: int = 0):
        super().__init__(name, order)
        self.store = store

    def authenticate(self, username: str, password: str) -> Optional[dict]:
        return self.store.authenticate(username, password)

    def lookup(self, username: str) -> Optional[dict]:
        return self.store.users.get(username)


def build_realm_chain(settings, store, data_path: str) -> List[Realm]:
    """Resolve the ordered realm chain from node settings.

    `xpack.security.authc.realms.file.<name>.order` (+ optional
    `.files.users` / `.files.users_roles` paths) configures file realms;
    the native realm joins unless explicitly disabled. With no explicit
    realm settings, a file realm is added implicitly when
    `<data>/config/users` exists — the reference's default behavior."""
    get = settings.get if hasattr(settings, "get") else \
        (lambda k, d=None: (settings or {}).get(k, d))
    realms: List[Realm] = []
    flat = {}
    as_flat = getattr(settings, "as_flat_dict", None)
    if callable(as_flat):
        flat = as_flat()
    elif isinstance(settings, dict):
        flat = settings
    prefix = "xpack.security.authc.realms."
    configured: Dict[tuple, dict] = {}
    for key, value in flat.items():
        if not key.startswith(prefix):
            continue
        rest = key[len(prefix):].split(".")
        if len(rest) < 3:
            continue
        rtype, rname = rest[0], rest[1]
        configured.setdefault((rtype, rname), {})[".".join(rest[2:])] = value

    default_users = os.path.join(data_path, "config", "users")
    default_roles = os.path.join(data_path, "config", "users_roles")
    for (rtype, rname), conf in configured.items():
        order = int(conf.get("order", 0))
        if str(conf.get("enabled", "true")).lower() == "false":
            continue
        if rtype == "file":
            realms.append(FileRealm(
                rname,
                str(conf.get("files.users", default_users)),
                str(conf.get("files.users_roles", default_roles)),
                order=order))
        elif rtype == "native":
            realms.append(NativeRealm(rname, store, order=order))
        elif rtype == "kerberos":
            realms.append(KerberosRealm(
                rname, order=order,
                keytab_path=conf.get("keytab.path")))
        # ldap/pki/saml/oidc configs are accepted but unsupported in this
        # environment (no egress); they simply never authenticate
    if not any(r.type_name == "file" for r in realms) \
            and os.path.exists(default_users):
        realms.append(FileRealm("default_file", default_users,
                                default_roles, order=-1))
    if not any(r.type_name == "native" for r in realms):
        realms.append(NativeRealm("default_native", store, order=100))
    realms.sort(key=lambda r: r.order)
    # Kerberos principals resolve roles via delegated lookup in the other
    # realms (the reference's authorization_realms delegation)
    for r in realms:
        if isinstance(r, KerberosRealm):
            r.lookup_realms = [o for o in realms if o is not r]
    return realms


class KerberosRealm(Realm):
    """Kerberos realm slot (reference: the `kerberos` entry in
    `InternalRealms.java` + `KerberosRealm.java`): authenticates
    `Authorization: Negotiate <base64 ticket>` headers.

    Real GSS/SPNEGO needs a KDC and a keytab — unavailable here (no
    egress), so ticket validation is pluggable: `ticket_validator(ticket
    bytes) -> principal str or None`. Deployments inject a real validator;
    tests inject a stub. Without one the realm never authenticates, the
    same posture as the unconfigured ldap/saml/oidc slots. Principals map
    to roles through delegated lookup in the other realms (the reference's
    authorization_realms delegation), falling back to role-mapping-less
    empty roles."""

    type_name = "kerberos"

    def __init__(self, name: str, order: int = 0, keytab_path=None,
                 ticket_validator=None, lookup_realms=()):
        super().__init__(name, order)
        self.keytab_path = keytab_path
        self.ticket_validator = ticket_validator
        self.lookup_realms = list(lookup_realms)

    def authenticate(self, username: str, password: str):
        return None  # Kerberos never does username/password

    def authenticate_ticket(self, ticket: bytes):
        """dict {username, roles} for a valid service ticket, else None."""
        if self.ticket_validator is None:
            return None
        principal = self.ticket_validator(ticket)
        if not principal:
            return None
        # user@REALM -> user, like the reference's remove_realm_name
        username = str(principal).partition("@")[0]
        for realm in self.lookup_realms:
            user = realm.lookup(username)
            if user is not None:
                # a DISABLED user must not slip in through a valid ticket
                # (the Kerberos path bypasses password checks, not the
                # account state)
                if not user.get("enabled", True):
                    return None
                return {"username": username,
                        "roles": user.get("roles", [])}
        return {"username": username, "roles": []}
