# tpulint: hot-path
"""Generational corpus + background merge scheduler.

`GenerationalCorpus` is the device-side engine lifecycle the reference
gets from Lucene (PAPER.md, indices/engine layer): refresh SEALS delta
rows into small L0 generations (O(delta), never a corpus re-upload),
deletes flip per-generation tombstone masks, and a budgeted background
merge thread consolidates generations up the tier ladder — copy-on-write
installs, so a search dispatched against the previous generation set
keeps reading valid arrays (the `ShardedFieldState.append` contract,
applied to the whole corpus lifecycle).

The merge scheduler also owns the two expensive stories the refresh
thread must never pay:

* IVF — a merge that produces a new base generation re-enters the
  trained layout via `IVFIndex.clone().add(delta)` (copy-on-write: the
  old router keeps serving mid-merge); when drift trips
  `needs_retrain`, the k-means retrain runs HERE, on the merge thread;
* mesh — L0 generations stay single-device; a merge graduates the new
  base into the sharded serving corpus (`extend_or_build`: delta append
  into per-shard headroom when prefix-compatible, full SPMD build
  otherwise).

Search fans one dispatch per live generation (`segments.knn` for sealed
buckets, the monolithic `knn.exact` grid for the initial base) and fuses
the per-generation boards through the existing `ops/topk.merge_top_k` —
stable concatenation in generation order reproduces the monolithic
tie-break exactly, which is what makes generational serving
byte-identical to the single-corpus path.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import knn as knn_ops
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops import topk as topk_ops
from elasticsearch_tpu.segments.generation import (
    Generation, build_generation)
from elasticsearch_tpu.segments.policy import MergeSpec, TieredMergePolicy

logger = logging.getLogger("elasticsearch_tpu.segments")

_NEG_INF_F32 = float(np.float32(-3.0e38))  # sim.NEG_INF as a host float


class GenerationSet:
    """Immutable snapshot of the live generations (the searchable view).

    The flat logical row space is the concatenation of the generations'
    row maps IN ORDER (tombstoned rows keep their slots — masked, not
    compacted — so positions are stable between merges)."""

    __slots__ = ("generations", "offsets", "row_map", "total_rows",
                 "total_pad", "dead_rows")

    def __init__(self, generations: Sequence[Generation]):
        self.generations = tuple(generations)
        sizes = [g.n_rows for g in self.generations]
        self.offsets = np.concatenate(
            [[0], np.cumsum(sizes)]).astype(np.int64) if sizes \
            else np.zeros(1, dtype=np.int64)
        self.row_map = (np.concatenate([g.row_map
                                        for g in self.generations])
                        if self.generations else np.zeros(0, dtype=np.int64))
        self.total_rows = int(self.offsets[-1])
        self.total_pad = sum(g.n_pad for g in self.generations)
        self.dead_rows = sum(g.dead_rows for g in self.generations)

    @property
    def simple(self) -> bool:
        """One clean generation — serving degenerates to the exact
        monolithic path (same kernels, same warmup grid)."""
        return (len(self.generations) == 1
                and not self.generations[0].has_tombstones)

    @property
    def l0_count(self) -> int:
        return sum(1 for g in self.generations if g.tier == 0)

    def live_row_map(self) -> np.ndarray:
        """Engine rows currently live, in flat order (the refresh
        classifier's baseline)."""
        if self.dead_rows == 0:
            return self.row_map
        return np.concatenate(
            [g.row_map[g.live_mask()] for g in self.generations]) \
            if self.generations else self.row_map

    def gather_rows(self, flat_ids: np.ndarray) -> np.ndarray:
        """Exact f32 host rows for ASCENDING UNIQUE flat row ids,
        resolved per generation through the shared block store
        (`Generation.source`) — the two-phase rescore's candidate
        gather (`quant/rescore.py`). O(window) rows materialize."""
        flat_ids = np.asarray(flat_ids, dtype=np.int64)
        if len(flat_ids) == 0 or not self.generations:
            d = (self.generations[0].source.dims
                 if self.generations else 0)
            return np.zeros((0, d), dtype=np.float32)
        out = np.zeros((len(flat_ids), self.generations[0].source.dims),
                       dtype=np.float32)
        for gen, off in zip(self.generations, self.offsets[:-1]):
            lo = int(off)
            hi = lo + gen.n_rows
            sel = (flat_ids >= lo) & (flat_ids < hi)
            if sel.any():
                out[sel] = gen.source.gather(flat_ids[sel] - lo)
        return out

    # ------------------------------------------------------------ search
    def search_async(self, queries: np.ndarray, n_real: int, k_eff: int,
                     filters: Sequence[Optional[np.ndarray]],
                     metric: str, precision: str,
                     num_candidates: Optional[int] = None,
                     knn_stats: Optional[dict] = None) -> Tuple:
        """Fan one dispatch per generation, fuse via `merge_top_k`.

        queries: [B_pad, D] f32, already padded to the query bucket.
        filters: per-request allowed engine-row arrays (or None), length
        n_real. Returns (scores, flat_ids, phases): un-synced [B_pad,
        k_t] device boards in the FLAT row space — the caller lands them
        at response-assembly time (`finalize_many`)."""
        import jax.numpy as jnp

        b_pad = len(queries)
        k_t = dispatch.bucket_k(k_eff, limit=self.total_pad)
        any_filter = any(fr is not None for fr in filters)
        qj = jnp.asarray(queries)
        board_s: List = []
        board_i: List = []
        legs: List[str] = []
        for gen, off in zip(self.generations, self.offsets[:-1]):
            if gen.n_rows == 0:
                continue
            s, ids, leg = self._search_generation(
                gen, int(off), qj, queries, n_real, b_pad, k_t,
                any_filter, filters, metric, precision, num_candidates,
                knn_stats)
            board_s.append(s)
            board_i.append(ids)
            legs.append(leg)
        if not board_s:
            return (np.full((b_pad, k_t), _NEG_INF_F32, dtype=np.float32),
                    np.full((b_pad, k_t), -1, dtype=np.int32),
                    {"engine": "tpu_generational", "generations": 0})
        # stable concat in generation order == flat-order tie-break ==
        # the monolithic corpus's lower-row-index tie-break
        s, i = topk_ops.merge_top_k(jnp.stack(board_s), jnp.stack(board_i),
                                    k=k_t)
        phases = {"engine": "tpu_generational",
                  "generations": len(self.generations),
                  "l0_generations": self.l0_count,
                  "tombstoned_rows": self.dead_rows,
                  "legs": legs}
        return s, i, phases

    def _search_generation(self, gen: Generation, off: int, qj,
                           queries: np.ndarray, n_real: int, b_pad: int,
                           k_t: int, any_filter: bool, filters,
                           metric: str, precision: str,
                           num_candidates: Optional[int],
                           knn_stats: Optional[dict]):
        """One generation's board [B_pad, k_t] in flat ids: mesh / IVF /
        exhaustive leg selection mirrors the monolithic router."""
        import jax.numpy as jnp

        n_pad = gen.n_pad
        need_mask = gen.has_tombstones or any_filter
        # -------- IVF leg (graduated base; tombstones drop the router)
        if gen.router is not None and not need_mask:
            reason = gen.router.should_fallback(
                min(k_t, gen.n_rows), False, precision)
            if reason is None:
                return self._ivf_board(gen, off, queries, k_t,
                                       num_candidates, knn_stats)
        # -------- mesh leg (graduated base; masks map via the slot map)
        if gen.mesh_state is not None:
            from elasticsearch_tpu.parallel import policy as mesh_policy
            # batch = the already-padded query bucket: with dp > 1 the
            # policy picks full-mesh vs one dp-group submesh per leg
            mesh = mesh_policy.decide("knn", gen.live_rows,
                                      has_mesh_state=True, batch=b_pad)
            if mesh is not None:
                if k_t <= gen.mesh_state.layout.rows_per_shard:
                    return self._mesh_board(gen, off, queries, n_real,
                                            b_pad, k_t, any_filter,
                                            filters, metric, precision,
                                            knn_stats, mesh)
                mesh_policy.reclassify_single("knn_k_deeper_than_shard")
        # -------- exhaustive leg (un-synced device board)
        k_g = dispatch.bucket_k(min(k_t, n_pad), limit=n_pad)
        mask = None
        if need_mask:
            live = gen.live_mask()
            if any_filter:
                m = np.zeros((b_pad, n_pad), dtype=bool)
                for qi in range(n_real):
                    fr = filters[qi]
                    allow = live if fr is None \
                        else live & np.isin(gen.row_map, fr)
                    m[qi, :gen.n_rows] = allow
            else:
                m = np.zeros(n_pad, dtype=bool)
                m[:gen.n_rows] = live
            mask = jnp.asarray(m)
        if gen.kernel == "knn.exact" and mask is None:
            # the initial base rides the monolithic auto-router (binned
            # Pallas fast path on TPU, warmed grid) — byte-identical to
            # the pre-generational serving path by construction
            s, ids = knn_ops.knn_search_auto(qj, gen.corpus, k=k_g,
                                             metric=metric,
                                             precision=precision)
        else:
            s, ids = dispatch.call(gen.kernel, qj, gen.corpus, mask,
                                   k=k_g, metric=metric,
                                   precision=precision, block_size=None)
        ids = ids + np.int32(off)
        if k_g < k_t:
            s = jnp.pad(s, ((0, 0), (0, k_t - k_g)),
                        constant_values=sim.NEG_INF)
            ids = jnp.pad(ids, ((0, 0), (0, k_t - k_g)),
                          constant_values=-1)
        return s, ids, gen.kernel

    def _ivf_board(self, gen: Generation, off: int, queries: np.ndarray,
                   k_t: int, num_candidates: Optional[int],
                   knn_stats: Optional[dict]):
        """Graduated base served through its IVF router (host-synced —
        the router prunes and merges internally)."""
        from elasticsearch_tpu.parallel import policy as mesh_policy

        k_i = dispatch.bucket_k(min(k_t, gen.n_rows), limit=gen.n_rows)
        mesh = mesh_policy.decide("ivf", gen.live_rows,
                                  batch=len(queries))
        scores, rows, _phases = gen.router.search(
            queries, k_i, num_candidates=num_candidates, mesh=mesh)
        scores = np.asarray(scores, dtype=np.float32)
        rows = np.asarray(rows)
        ids = np.where(rows >= 0, rows + off, -1).astype(np.int32)
        if k_i < k_t:
            pad = ((0, 0), (0, k_t - k_i))
            scores = np.pad(scores, pad, constant_values=_NEG_INF_F32)
            ids = np.pad(ids, pad, constant_values=-1)
        if knn_stats is not None:
            knn_stats["ivf_searches"] += 1
            if _phases.get("engine") == "tpu_ivf_mesh":
                knn_stats["mesh_searches"] += 1
        return scores, ids, "ivf"

    def _mesh_board(self, gen: Generation, off: int, queries: np.ndarray,
                    n_real: int, b_pad: int, k_t: int, any_filter: bool,
                    filters, metric: str, precision: str,
                    knn_stats: Optional[dict], mesh):
        """Graduated base served as ONE SPMD program over its sharded
        copy; tombstones and per-query filters map through the slot map.
        `mesh` is the router's pick — the full serving mesh or a
        dp-group submesh (the group view reads the same immutable
        snapshot, so every replica serves one corpus version). Syncs
        internally (like the monolithic mesh route)."""
        import jax
        import jax.numpy as jnp

        from elasticsearch_tpu.parallel import mesh as mesh_lib
        from elasticsearch_tpu.parallel import policy as mesh_policy
        from elasticsearch_tpu.parallel.sharded_knn import (
            distributed_knn_search)

        ms = gen.mesh_state
        if (mesh is not ms.mesh
                and mesh_lib.shard_size(mesh) != ms.layout.n_shards):
            # policy reconfigured under this graduated base: its layout
            # is baked for its own shard count — serve on the state's
            # mesh until the next graduation rebuilds
            mesh = ms.mesh
        per = ms.layout.rows_per_shard
        k_b = dispatch.bucket_k(min(k_t, per), limit=per)
        t0 = time.perf_counter_ns()
        mask = None
        if any_filter or gen.has_tombstones:
            live = gen.live_mask()
            if any_filter:
                m = np.zeros((b_pad, len(ms.slot_map)), dtype=bool)
                for qi in range(n_real):
                    fr = filters[qi]
                    allow = live if fr is None \
                        else live & np.isin(gen.row_map, fr)
                    m[qi] = ms.filter_mask(allow)
                mask = jax.device_put(jnp.asarray(m),
                                      ms.mask_sharding(2, mesh))
            else:
                mask = jax.device_put(jnp.asarray(ms.filter_mask(live)),
                                      ms.mask_sharding(1, mesh))
        q = jax.device_put(jnp.asarray(queries), ms.query_sharding(mesh))
        scores, gids = distributed_knn_search(
            q, ms.corpus_for(mesh), k_b, mesh, metric=metric,
            filter_mask=mask, precision=precision)
        gids.block_until_ready()
        t1 = time.perf_counter_ns()
        scores = np.asarray(scores, dtype=np.float32)
        local = ms.map_ids(np.asarray(gids))   # flat rows of this gen
        ids = np.where(local >= 0, local + off, -1).astype(np.int32)
        if k_b < k_t:
            pad = ((0, 0), (0, k_t - k_b))
            scores = np.pad(scores, pad, constant_values=_NEG_INF_F32)
            ids = np.pad(ids, pad, constant_values=-1)
        gather = mesh_policy.gather_bytes(mesh_lib.shard_size(mesh),
                                          b_pad, k_b)
        mesh_policy.record_leg("knn", t1 - t0,
                               time.perf_counter_ns() - t1, gather)
        if knn_stats is not None:
            knn_stats["mesh_searches"] += 1
        return scores, ids, "mesh"


class GenerationalCorpus:
    """One vector field's generation lifecycle: the O(delta) refresh
    classifier, the copy-on-write generation set, and the background
    merge scheduler. Thread contract: `_lock` guards the installed set +
    stats; merge EXECUTION runs outside the lock and the install
    validates the merged generations are still the live objects (a
    refresh that tombstoned a victim mid-merge aborts the install — the
    next cycle retries against the fresh set)."""

    def __init__(self, metric: str, dtype: str, rescore: bool, dims: int,
                 policy: Optional[TieredMergePolicy] = None,
                 merge_budget_ms: float = 50.0, background: bool = True,
                 warmup_cb=None, knn_params: Optional[dict] = None,
                 view_cb=None):
        self.metric = metric
        self.dtype = dtype
        self.rescore = bool(rescore)
        self.dims = int(dims)
        self.policy = policy or TieredMergePolicy()
        self.merge_budget_ms = float(merge_budget_ms)
        self.background = bool(background)
        self.warmup_cb = warmup_cb          # callable(entries) or None
        # IVF graduation parameters: engine/nlist/nprobe/recall_target/
        # min_rows (threaded from the store so the merge thread rebuilds
        # routers with the index's own settings)
        self.knn_params = dict(knn_params or {})
        # called (outside the lock) after a merge installs, so the store
        # can refresh its FieldCorpus view and drop stale device refs
        self.view_cb = view_cb
        self._lock = threading.Lock()
        self._set = GenerationSet(())
        self._next_gen_id = 0
        self._merge_thread: Optional[threading.Thread] = None
        self._last_merge_nanos = 0
        self.last_rebuild_reason: Optional[str] = None
        self.stats = {
            "seals": 0, "sealed_rows": 0, "merges": 0, "merge_nanos": 0,
            "merged_rows": 0, "aborted_merges": 0, "tombstone_deletes": 0,
            "ivf_background_builds": 0, "mesh_graduations": 0,
            "dtype_retargets": 0, "dtype_reencodes": 0}

    # ------------------------------------------------------------ set-up
    @classmethod
    def from_monolithic(cls, corpus, row_map: np.ndarray, source,
                        metric: str, dtype: str,
                        rescore: bool, dims: int, host=None, router=None,
                        mesh_state=None, **kwargs) -> "GenerationalCorpus":
        """Wrap a legacy full build as generation 0 (kernel `knn.exact`
        — the monolithic grid the store already warms). `source` is the
        columnar RowSource over the build's rows (store-backed on the
        sync path, so the base generation pins nothing); a raw ndarray
        is accepted for direct construction and wrapped (pinning)."""
        from elasticsearch_tpu.columnar import RowSource
        if isinstance(source, np.ndarray):
            source = RowSource.from_array(source)
        gc = cls(metric, dtype, rescore, dims, **kwargs)
        gen = Generation(gc._next_gen_id, corpus,
                         np.asarray(row_map, dtype=np.int64),
                         source, kernel="knn.exact", host=host,
                         router=router, mesh_state=mesh_state)
        gc._next_gen_id += 1
        gc._set = GenerationSet((gen,))
        return gc

    def snapshot(self) -> GenerationSet:
        with self._lock:
            return self._set

    # ----------------------------------------------------------- refresh
    def try_incremental(self, view, row_map: np.ndarray,
                        dtype: str, metric: str,
                        rescore: bool) -> Optional[str]:
        """Absorb one refresh as tombstones + an L0 seal. Returns the
        outcome string ("append" / "delete" / "append+delete" / "noop"),
        or None when only a full rebuild can represent the new reader
        (`last_rebuild_reason` says why). O(delta) END TO END: `view` is
        the columnar store's lazy `FieldRowsView` — only the DELTA rows
        ever materialize (a pure append touches the tail blocks alone,
        which the store extracted delta-only too); the host
        classification is one isin pass over the row maps."""
        retargeted = False
        with self._lock:
            cur = self._set
            if not cur.generations:
                self.last_rebuild_reason = "first_build"
                return None
            if metric != self.metric:
                # a metric change re-prepares every row (cosine
                # normalization happens at encode time) — only a
                # rebuild is sound
                self.last_rebuild_reason = "metric_change"
                return None
            if dtype != self.dtype or bool(rescore) != self.rescore:
                # dtype change done on the MERGE thread: future seals
                # encode at the new target immediately; the resident
                # generations keep serving their old encoding until the
                # background merger re-encodes them
                # (`_select` → "dtype_reencode" merges) — the refresh
                # and serving paths never pay a full rebuild for a
                # mapping update
                self.dtype = dtype
                self.rescore = bool(rescore)
                self.stats["dtype_retargets"] += 1
                retargeted = True
            old_rows = cur.row_map
            old_live = cur.live_row_map()
            new = np.asarray(row_map, dtype=np.int64)
            deleted_any = False
            if len(new) >= len(old_live) \
                    and np.array_equal(new[:len(old_live)], old_live):
                # fast path: pure append (the steady-state refresh) —
                # only the tail rows materialize from the block store
                added = new[len(old_live):]
                added_src = view.source_slice(len(old_live))
            else:
                keep = np.isin(new, old_rows)
                added = new[~keep]
                # rows the engine re-based (a host segment merge) look
                # like mass delete+add in a new row space — sealing the
                # whole corpus as a "delta" would double residency, so
                # that shape rebuilds instead
                if len(added) and len(old_rows) \
                        and added.min() <= old_rows.max():
                    self.last_rebuild_reason = "segment_rewrite"
                    return None
                survivors = new[keep]
                still = np.isin(old_live, new)
                if not np.array_equal(old_live[still], survivors):
                    self.last_rebuild_reason = "segment_rewrite"
                    return None
                added_src = view.source_select(~keep)
                gens = []
                for g in cur.generations:
                    gone = g.live_mask() & np.isin(g.row_map, new,
                                                   invert=True)
                    if gone.any():
                        deleted_any = True
                        self.stats["tombstone_deletes"] += int(gone.sum())
                        gens.append(
                            g.with_tombstones(g.tombstones | gone))
                    else:
                        gens.append(g)
                if deleted_any:
                    self._set = GenerationSet(gens)
            gen_id = self._next_gen_id
            self._next_gen_id += 1
        sealed = None
        if len(added):
            # the seal's heavy lifting (f32 copy, normalize, quantize,
            # device upload) runs OUTSIDE the lock — `snapshot()` is on
            # every search dispatch, and stalling it for the seal would
            # feed the build latency straight into search p99 during
            # ingest. Appending at the END of the CURRENT set is safe
            # against a merge installing in between (merges splice
            # interior runs; the tail position is never theirs). The
            # delta gather is the ONLY host materialization this refresh
            # pays; the sealed generation keeps the store-backed source.
            sealed = build_generation(gen_id, added_src.gather(), added,
                                      self.metric, self.dtype,
                                      self.rescore, source=added_src)
            with self._lock:
                self.stats["seals"] += 1
                self.stats["sealed_rows"] += len(added)
                self._set = GenerationSet(self._set.generations
                                          + (sealed,))
        if sealed is not None and self.warmup_cb is not None:
            self.warmup_cb(sealed.warmup_entries(self.dims, self.metric))
        self.notify()
        if sealed is not None and deleted_any:
            outcome = "append+delete"
        elif sealed is not None:
            outcome = "append"
        elif deleted_any:
            outcome = "delete"
        else:
            outcome = "noop"
        if retargeted:
            # the retarget IS a full rebuild avoided, even on an
            # otherwise-noop refresh (the legacy path would have
            # re-encoded the whole corpus on this thread)
            outcome = ("retarget" if outcome == "noop"
                       else outcome + "+retarget")
        return outcome

    # ------------------------------------------------------------ merges
    def _gen_encoding_stale(self, gen: Generation) -> bool:
        """Does this generation still serve a superseded encoding after
        a dtype retarget? (matrix dtype off the target rung, or an int8
        residual level present/absent against the rescore flag)."""
        from elasticsearch_tpu.quant import codec as quant_codec
        if gen.corpus is None:
            return False
        if quant_codec.encoding_of(gen.corpus.matrix.dtype) != self.dtype:
            return True
        if self.dtype == "int8":
            return bool(gen.corpus.residual is not None) != self.rescore
        return False

    def _select(self, gens: Sequence[Generation]) -> Optional[MergeSpec]:
        spec = self.policy.select(gens)
        if spec is not None:
            return spec
        # a tombstoned base dropped its IVF router (dead rows would leak
        # through the partition layout): compact it eagerly so the
        # engine's pruned path comes back without waiting for the GC
        # fraction — in the background, never on the refresh thread
        if (self.knn_params.get("engine") == "tpu_ivf" and gens
                and gens[0].has_tombstones and gens[0].router is None
                and gens[0].live_rows
                >= int(self.knn_params.get("min_rows", 512))):
            return MergeSpec(0, 1, "tombstone_gc")
        # dtype retarget: re-encode superseded generations one at a
        # time on THIS thread — `_build_merged` gathers live rows
        # through the shared block store and seals at the CURRENT
        # target, so a mapping's int8→int4 never full-rebuilds on the
        # refresh or serving path (`segment_counters` dtype_change
        # stays 0)
        for i, g in enumerate(gens):
            if self._gen_encoding_stale(g):
                return MergeSpec(i, i + 1, "dtype_reencode")
        return None

    def merge_pending(self) -> bool:
        with self._lock:
            return self._select(self._set.generations) is not None

    def notify(self) -> None:
        """Kick the background merge thread if work is pending and no
        thread is registered (thread-per-burst: the loop exits when the
        set is steady, so idle corpora hold no threads). The
        registration check is on `is not None` alone — an `is_alive()`
        test would race the window between registering a thread and
        starting it (unstarted threads report not-alive), double-running
        the loop; `_merge_loop` clears the registration in a `finally`,
        so a crashed thread can never wedge merges off."""
        if not self.background:
            return
        with self._lock:
            if self._merge_thread is not None:
                return
            if self._select(self._set.generations) is None:
                return
            t = threading.Thread(target=self._merge_loop, daemon=True,
                                 name="segments-merge")
            self._merge_thread = t
        t.start()

    def _merge_loop(self) -> None:
        budget_ns = max(self.merge_budget_ms, 1.0) * 1e6
        spent = 0.0
        try:
            while self._merge_once():
                spent += self._last_merge_nanos
                if spent > budget_ns:
                    # budget exhausted this cycle: yield to serving (the
                    # merge thread shares host cores with query fan-out)
                    time.sleep(budget_ns / 1e9)
                    spent = 0.0
        finally:
            with self._lock:
                self._merge_thread = None
        # a seal may have landed between the last select and the
        # registration clear; re-kick if so
        self.notify()

    def run_merges(self) -> int:
        """Synchronously drain every pending merge (tests, bench
        determinism). Returns the number of merges executed."""
        n = 0
        while self._merge_once():
            n += 1
        return n

    def force_merge(self) -> bool:
        """Consolidate to a single clean generation (forceMerge(1))."""
        with self._lock:
            spec = TieredMergePolicy.force(self._set.generations)
            victims = (self._set.generations[spec.start:spec.stop]
                       if spec else None)
        if spec is None:
            return False
        return self._execute(spec, victims)

    def drain(self, timeout_s: float = 30.0) -> None:
        """Wait for the background thread to go idle with no pending
        merges (deterministic test/bench checkpoints)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                t = self._merge_thread
                pending = self._select(self._set.generations) is not None
            if t is not None and t.is_alive():
                t.join(0.05)
                continue
            if not pending:
                return
            self.notify()
            time.sleep(0.005)

    def _merge_once(self) -> bool:
        with self._lock:
            spec = self._select(self._set.generations)
            victims = (self._set.generations[spec.start:spec.stop]
                       if spec else None)
        if spec is None:
            self._last_merge_nanos = 0
            return False
        return self._execute(spec, victims)

    def _execute(self, spec: MergeSpec, victims: Tuple[Generation, ...]
                 ) -> bool:
        t0 = time.perf_counter_ns()
        merged = self._build_merged(spec, victims)
        ok = self._install(victims, merged)
        nanos = time.perf_counter_ns() - t0
        self._last_merge_nanos = nanos
        with self._lock:
            if ok:
                self.stats["merges"] += 1
                self.stats["merged_rows"] += merged.n_rows
                if spec.reason == "dtype_reencode":
                    self.stats["dtype_reencodes"] += 1
            else:
                self.stats["aborted_merges"] += 1
            self.stats["merge_nanos"] += nanos
        if ok and self.view_cb is not None:
            self.view_cb(self)
        return ok

    def _build_merged(self, spec: MergeSpec,
                      victims: Tuple[Generation, ...]) -> Generation:
        """Concatenate the victims' LIVE rows and seal the consolidated
        generation; a merge producing the new base (start == 0) also
        graduates it into the IVF layout and the sharded mesh corpus.

        The victim-gather reads live rows THROUGH the shared segment
        block store (each victim's `RowSource`): the f32 concatenation
        is a merge-local transient handed to the corpus build and the
        graduation steps, then dropped — the merged generation keeps
        only the narrowed block references, so merge-input host RAM is
        O(1) in corpus size beyond what the engine segments already
        hold (the pre-columnar path pinned a full `host_vectors` copy
        per generation for its whole lifetime)."""
        from elasticsearch_tpu.columnar import RowSource
        d = self.dims
        src = RowSource.concat(
            [g.source.select(g.live_mask()) for g in victims])
        rows = [g.row_map[g.live_mask()] for g in victims]
        vecs = src.gather()
        if vecs.size == 0:
            vecs = vecs.reshape(0, d)
        rows = (np.concatenate(rows) if rows
                else np.zeros(0, dtype=np.int64))
        with self._lock:
            gen_id = self._next_gen_id
            self._next_gen_id += 1
        merged = build_generation(gen_id, vecs, rows, self.metric,
                                  self.dtype, self.rescore, source=src)
        if spec.start == 0:
            merged.router = self._graduate_ivf(victims[0], merged, vecs)
            merged.mesh_state = self._graduate_mesh(victims[0], merged,
                                                    vecs)
            merged.host = self._graduate_host(merged, vecs)
        if self.warmup_cb is not None:
            self.warmup_cb(merged.warmup_entries(self.dims, self.metric))
        return merged

    def _graduate_ivf(self, old_base: Generation, merged: Generation,
                      vecs: np.ndarray):
        """Re-enter the trained IVF layout (clone + add the delta), or
        retrain from scratch — ALWAYS on this merge thread. `vecs` is
        the merge's transient store-read materialization (no
        re-gather, no pinned copy)."""
        params = self.knn_params
        if params.get("engine") != "tpu_ivf":
            return None
        min_rows = int(params.get("min_rows", 512))
        if merged.n_rows < min_rows:
            return None
        old = old_base.router
        if (old is not None and not old_base.has_tombstones
                and old.index.dtype == self.dtype
                and old.index.metric == self.metric
                and not old.index.needs_retrain
                and old_base.n_rows <= merged.n_rows):
            # append-shaped merge: the old base's rows are a stable
            # prefix of the merged generation, so the delta places into
            # the CLONED layout (copy-on-write — the serving router's
            # host mirror and device pytree stay untouched mid-merge)
            idx = old.index.clone()
            idx.add(vecs[old_base.n_rows:],
                    np.arange(old_base.n_rows, merged.n_rows,
                              dtype=np.int32))
            if not idx.needs_retrain:
                return old.with_index(idx)
        # drift / tombstone compaction: full k-means retrain, here on
        # the merge thread — the refresh path never pays it
        from elasticsearch_tpu.ann import IVFRouter, build_ivf_index
        with self._lock:
            self.stats["ivf_background_builds"] += 1
        nlist = params.get("nlist")
        ivf = build_ivf_index(
            vecs, metric=self.metric,
            nlist=int(nlist) if nlist is not None else None,
            dtype=self.dtype, seed=0)
        return IVFRouter(ivf, nprobe=params.get("nprobe", "auto"),
                         recall_target=float(
                             params.get("recall_target", 0.95)))

    def _graduate_host(self, merged: Generation, vecs: np.ndarray):
        """Rebuild the host VNNI latency mirror for the new base — same
        eligibility policy as the monolithic sync path, built HERE so a
        consolidated corpus keeps the low-latency host route instead of
        silently regressing to device-only after its first merge."""
        from elasticsearch_tpu import native
        from elasticsearch_tpu.vectors.host_corpus import (
            HostFieldCorpus, packed_nbytes)
        max_bytes = int(self.knn_params.get("host_mirror_max_bytes", 0))
        if (not native.AVAILABLE
                or self.dtype in ("int8", "int4", "binary")
                or merged.n_rows == 0
                or packed_nbytes(merged.n_rows, self.dims) > max_bytes):
            return None
        return HostFieldCorpus(vecs, self.metric)

    def _graduate_mesh(self, old_base: Generation, merged: Generation,
                       vecs: np.ndarray):
        """Graduate the merged base into the sharded serving corpus —
        delta append into per-shard headroom when the old base is a
        clean prefix, full SPMD build otherwise. Eligibility accounts
        the dp-replicated HBM cost of the sharded copy
        (`parallel/policy.eligible`)."""
        from elasticsearch_tpu.parallel import policy as mesh_policy
        from elasticsearch_tpu.vectors.store import device_corpus_nbytes
        if not mesh_policy.eligible(
                merged.n_rows,
                device_bytes=device_corpus_nbytes(
                    merged.n_rows, self.dims, self.dtype)):
            return None
        mesh = mesh_policy.serving_mesh()
        if mesh is None:
            return None
        from elasticsearch_tpu.parallel.sharded_knn import extend_or_build
        old_ms = (old_base.mesh_state
                  if not old_base.has_tombstones else None)
        state, appended = extend_or_build(
            old_ms, vecs, old_base.n_rows, mesh,
            self.metric, self.dtype)
        if not appended:
            with self._lock:
                self.stats["mesh_graduations"] += 1
        return state

    def _install(self, victims: Tuple[Generation, ...],
                 merged: Generation) -> bool:
        """Copy-on-write install: splice `merged` where the victims sit
        in the CURRENT list — identity-validated, so a refresh that
        replaced a victim (tombstones) mid-merge aborts the install
        instead of resurrecting its deleted rows."""
        with self._lock:
            gens = list(self._set.generations)
            try:
                i = gens.index(victims[0])
            except ValueError:
                return False
            if i + len(victims) > len(gens) or any(
                    gens[i + j] is not victims[j]
                    for j in range(len(victims))):
                return False
            gens[i:i + len(victims)] = [merged]
            self._set = GenerationSet(gens)
            return True

    # ------------------------------------------------------------- stats
    def segment_stats(self) -> dict:
        with self._lock:
            s = self._set
            out = dict(self.stats)
        tiers: dict = {}
        for g in s.generations:
            t = tiers.setdefault(str(g.tier), {"generations": 0,
                                               "bytes": 0, "rows": 0,
                                               "tombstoned_rows": 0})
            t["generations"] += 1
            t["bytes"] += g.nbytes
            t["rows"] += g.n_rows
            t["tombstoned_rows"] += g.dead_rows
        out.update({
            "generations": len(s.generations),
            "l0_generations": s.l0_count,
            "tombstoned_rows": s.dead_rows,
            "bytes": sum(g.nbytes for g in s.generations),
            "tiers": tiers})
        return out
