"""Tiered merge selection: the Lucene TieredMergePolicy shape.

The reference consolidates write amplification in the background: small
flushed segments accumulate until a size tier holds `segmentsPerTier` of
them, then one merge folds the tier into the next band — total merge
work stays O(n log n) over the index's life while readers never block.
This module is that selection math over device generations.

Selection is CONTIGUOUS on purpose: the generation list is the flat
logical row order (base first, seals appended chronologically), and the
byte-parity contract with the monolithic corpus relies on tie-breaks
resolving by that order (`lax.top_k` stability + `merge_top_k`'s
stable concatenation). Merging a contiguous run and installing the
merged generation at the run's position preserves the order invariant
by construction. Because merged generations always land LEFT of newer
seals, same-tier generations are adjacent in steady state and the
contiguity restriction costs nothing.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence


class MergeSpec(NamedTuple):
    """One selected merge: generations [start, stop) fold into one."""

    start: int
    stop: int
    reason: str   # "tier_full" | "l0_overflow" | "tombstone_gc" | "force"


class TieredMergePolicy:
    """Pick the next merge from a generation snapshot (or None).

    tier_size:   merge when a contiguous run holds >= this many
                 generations of the same size tier (Lucene
                 segmentsPerTier)
    max_l0:      hard cap on tier-0 (freshly sealed) generations — past
                 it the whole trailing L0 run merges even below
                 tier_size, bounding search fan-out under a fast
                 refresh cadence
    gc_deleted_fraction: a generation more than this fraction dead is
                 compacted alone (expungeDeletes analog), reclaiming
                 HBM and shrinking its scan
    """

    def __init__(self, tier_size: int = 4, max_l0: int = 8,
                 gc_deleted_fraction: float = 0.5):
        self.tier_size = max(2, int(tier_size))
        self.max_l0 = max(1, int(max_l0))
        self.gc_deleted_fraction = float(gc_deleted_fraction)

    def select(self, gens: Sequence) -> Optional[MergeSpec]:
        """Next merge over `gens` (objects with .tier / .n_rows /
        .dead_rows), or None when the set is steady. Priority: full
        tiers (the amortizing path) > L0 overflow (fan-out bound) >
        tombstone GC (space/scan reclaim)."""
        n = len(gens)
        if n == 0:
            return None
        # 1. a contiguous same-tier run at tier_size
        run_start, run_tier = 0, gens[0].tier
        for i in range(1, n + 1):
            tier = gens[i].tier if i < n else None
            if tier != run_tier:
                if i - run_start >= self.tier_size:
                    return MergeSpec(run_start,
                                     run_start + self.tier_size,
                                     "tier_full")
                run_start, run_tier = i, tier
        # 2. L0 overflow: merge the trailing run of tier-0 seals
        l0 = [i for i in range(n) if gens[i].tier == 0]
        if len(l0) > self.max_l0:
            start = l0[0]
            while start > 0 and gens[start - 1].tier == 0:
                start -= 1
            stop = start + 1
            while stop < n and gens[stop].tier == 0:
                stop += 1
            if stop - start >= 2:
                return MergeSpec(start, stop, "l0_overflow")
        # 3. tombstone GC (single-generation compaction)
        for i in range(n):
            g = gens[i]
            if g.n_rows > 0 and g.dead_rows > 0 \
                    and g.dead_rows / g.n_rows > self.gc_deleted_fraction:
                return MergeSpec(i, i + 1, "tombstone_gc")
        return None

    @staticmethod
    def force(gens: Sequence) -> Optional[MergeSpec]:
        """Force-merge everything into one generation (Lucene
        forceMerge(1)); None when already consolidated and clean."""
        if len(gens) > 1 or (len(gens) == 1 and gens[0].dead_rows > 0):
            return MergeSpec(0, len(gens), "force")
        return None
