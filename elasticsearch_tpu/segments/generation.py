# tpulint: hot-path
"""One device generation: an immutable sealed slice of the corpus.

The device analog of a sealed Lucene segment: a `Corpus` pytree padded to
the pow-2 row-bucket ladder (`ops/dispatch.bucket_gen_rows`) plus the host
bookkeeping a generation carries through its life — the engine-row map,
a `columnar.RowSource` resolving the raw host rows through the SHARED
segment block store (the merge scheduler's input — generations pin no
private corpus-sized copy), and the tombstone mask deletes flip instead
of triggering a rebuild.

Generations are copy-on-write: tombstoning returns a NEW object sharing
the device corpus, so a search dispatched against a previously-installed
generation set keeps reading valid arrays (same contract as
`ShardedFieldState.append`).

The per-generation search dispatches `segments.knn` — the exact-kNN
implementation under a grid predicate that additionally pins the row
count to the sealed-generation ladder, so the `segments.*` compile set
stays closed under `ES_TPU_DISPATCH_STRICT=1`.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import knn as knn_ops


def generation_tier(n_rows: int) -> int:
    """Size tier of a generation (the Lucene TieredMergePolicy band):
    tier t holds generations whose row bucket is GEN_ROW_BUCKET_MIN << t.
    Rows past the bucket cap all land in the top band."""
    bucket = dispatch.bucket_gen_rows(max(int(n_rows), 1))
    return max(0, (bucket // dispatch.GEN_ROW_BUCKET_MIN).bit_length() - 1)


def _grid_segments_knn(statics, sigs) -> bool:
    """Closed sealed-generation grid: bucketed query count, k on the
    ladder (or clamped to the generation), rows on the pow-2 generation
    ladder."""
    q_shape = sigs[0][0]          # queries [Q, D]
    n_rows = sigs[1][0][0]        # corpus.matrix [N_bucket, D]
    return (dispatch.is_query_bucket(q_shape[0])
            and dispatch.in_k_grid(int(statics["k"]), limit=n_rows)
            and dispatch.in_gen_row_grid(n_rows))


# same implementation as knn.exact — a generation IS an exact corpus —
# but its own kernel name + grid: the monolithic kernel admits any
# lane-padded row count, while sealed generations must sit on the pow-2
# bucket ladder or the per-refresh seal stream would compile per shape
dispatch.DISPATCH.register(
    "segments.knn", knn_ops._knn_search_impl,
    static_argnames=("k", "metric", "precision", "block_size"),
    grid_check=_grid_segments_knn)


class Generation:
    """Immutable device generation + host bookkeeping."""

    __slots__ = ("gen_id", "corpus", "row_map", "source",
                 "tombstones", "kernel", "host", "router", "mesh_state",
                 "_live_cache")

    def __init__(self, gen_id: int, corpus, row_map: np.ndarray,
                 source, tombstones: Optional[np.ndarray] = None,
                 kernel: str = "segments.knn", host=None, router=None,
                 mesh_state=None):
        self.gen_id = gen_id
        self.corpus = corpus              # knn_ops.Corpus (device pytree)
        self.row_map = row_map            # [n_rows] engine global rows
        # columnar.RowSource: the merge scheduler's host-row input,
        # resolved through the SHARED segment block store on demand — a
        # generation never retains a private corpus-sized f32 copy
        # (the pre-columnar `host_vectors` pin doubled host RAM)
        self.source = source
        self.tombstones = (np.zeros(len(row_map), dtype=bool)
                           if tombstones is None else tombstones)
        # dispatch kernel: "knn.exact" for the legacy lane-padded full
        # build (reuses the store's warmed monolithic grid), "segments.knn"
        # for bucket-padded sealed/merged generations
        self.kernel = kernel
        self.host = host                  # HostFieldCorpus mirror (base only)
        self.router = router              # ann.IVFRouter (graduated base)
        self.mesh_state = mesh_state      # parallel ShardedFieldState
        self._live_cache = None

    # ------------------------------------------------------------ shape
    @property
    def n_rows(self) -> int:
        return len(self.row_map)

    @property
    def n_pad(self) -> int:
        return self.corpus.matrix.shape[0]

    @property
    def tier(self) -> int:
        return generation_tier(self.n_rows)

    @property
    def host_vectors(self) -> np.ndarray:
        """Materialize this generation's raw f32 rows from the shared
        block store (transient — callers must not hold the result; the
        compat shape of the retired pinned array)."""
        return self.source.gather()

    def host_pinned_nbytes(self) -> int:
        """Host bytes this generation PINS privately beyond the shared
        segment blocks — 0 on every store-backed path (the
        merge-does-not-pin invariant)."""
        return self.source.private_nbytes()

    @property
    def dead_rows(self) -> int:
        return int(self.tombstones.sum())

    @property
    def live_rows(self) -> int:
        return self.n_rows - self.dead_rows

    @property
    def has_tombstones(self) -> bool:
        return bool(self.tombstones.any())

    @property
    def nbytes(self) -> int:
        """Resident device bytes (matrix + norms + scales + residual)."""
        total = 0
        for arr in (self.corpus.matrix, self.corpus.sq_norms,
                    self.corpus.scales, self.corpus.residual,
                    self.corpus.residual_scales):
            if arr is not None:
                total += int(np.prod(arr.shape)) * arr.dtype.itemsize
        return total

    # ----------------------------------------------------------- copies
    def with_tombstones(self, tombstones: np.ndarray) -> "Generation":
        """Copy-on-write tombstone install: shares the device corpus and
        the row source, drops the graduated router (its partition layout
        would keep returning dead rows — the merge scheduler rebuilds it
        at compaction); the mesh state stays (searches mask it)."""
        return Generation(self.gen_id, self.corpus, self.row_map,
                          self.source, tombstones=tombstones,
                          kernel=self.kernel, host=None, router=None,
                          mesh_state=self.mesh_state)

    def live_mask(self) -> np.ndarray:
        """[n_rows] bool — True for live (non-tombstoned) rows."""
        if self._live_cache is None:
            self._live_cache = ~self.tombstones
        return self._live_cache

    # ----------------------------------------------------------- warmup
    def warmup_entries(self, dims: int, metric: str):
        """(kernel, specs, statics) entries pre-compiling this
        generation's search grid over the interactive buckets."""
        corpus_spec = dispatch.specs_like(self.corpus)
        entries = []
        for q in dispatch.WARMUP_QUERY_BUCKETS:
            qspec = dispatch.query_spec(q, dims)
            for k in dispatch.WARMUP_K_BUCKETS:
                k_b = dispatch.bucket_k(min(k, self.n_pad),
                                        limit=self.n_pad)
                entries.append((
                    self.kernel, (qspec, corpus_spec, None),
                    {"k": k_b, "metric": metric,
                     "precision": "bf16", "block_size": None}))
        return entries


def build_generation(gen_id: int, vectors: np.ndarray, row_map: np.ndarray,
                     metric: str, dtype: str, rescore: bool = False,
                     source=None) -> Generation:
    """Seal host rows into a device generation padded to the pow-2
    row-bucket ladder — the refresh path's ONLY device work, O(delta).

    `source` is the columnar RowSource covering exactly these rows (the
    store-backed, pin-free merge input). When omitted (direct test
    construction), the materialized `vectors` array is wrapped as a
    private source — which pins it, so production callers always pass
    the store-backed source."""
    vectors = np.asarray(vectors, dtype=np.float32)
    n = len(vectors)
    corpus = knn_ops.build_corpus(
        vectors, metric=metric, dtype=dtype,
        pad_to=dispatch.bucket_gen_rows(n), residual=rescore)
    if source is None:
        from elasticsearch_tpu.columnar import RowSource
        source = RowSource.from_array(vectors)
    return Generation(gen_id, corpus, np.asarray(row_map, dtype=np.int64),
                      source, kernel="segments.knn")
