"""Generational device segments: writes-while-searching without rebuilds.

The reference engine never rebuilds its index to absorb writes: Lucene
writers seal small immutable segments, a background TieredMergePolicy
amortizes consolidation, and readers hold point-in-time views that merges
can never invalidate (PAPER.md, indices/engine layer). This package ports
that lifecycle onto the device-resident vector corpus:

* `generation.Generation` — one immutable device corpus slice padded to
  the pow-2 row-bucket ladder (`ops/dispatch.bucket_gen_rows`), searched
  by the `segments.knn` kernel; deletes are per-generation tombstone
  masks, never rebuild triggers;
* `policy.TieredMergePolicy` — the Lucene-mirroring tier math: merge
  when a tier holds >= tier_size same-sized generations (plus L0
  overflow and tombstone-GC selection);
* `generational.GenerationalCorpus` — the copy-on-write generation set
  `vectors/store.py` serves from, the O(delta) refresh classifier, the
  fan-out search fused through `ops/topk.merge_top_k`, and the budgeted
  background merge scheduler that owns IVF retrains and mesh graduation
  (neither ever runs on the refresh thread).
"""

from elasticsearch_tpu.segments.generation import (  # noqa: F401
    Generation, build_generation, generation_tier)
from elasticsearch_tpu.segments.generational import (  # noqa: F401
    GenerationalCorpus, GenerationSet)
from elasticsearch_tpu.segments.policy import (  # noqa: F401
    MergeSpec, TieredMergePolicy)
