"""Device-mesh management.

The reference scales by hash-sharding the corpus across nodes and
scatter-gathering searches (`cluster/routing/OperationRouting.java`,
`AbstractSearchAsyncAction.java:214`). The TPU-native analog is a 2-D
`jax.sharding.Mesh`:

  axis "dp"    — query-batch data parallelism (independent searches)
  axis "shard" — corpus partitioning (one Elasticsearch shard ≈ one mesh
                 column's slice of the HBM-resident matrix)

Cross-shard merges ride ICI collectives inside the compiled program instead
of coordinator-side RPC reduces (`SearchPhaseController.mergeTopDocs:221`).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
SHARD_AXIS = "shard"

# per-device launch locks (lazily created, one per device id): an SPMD
# program's per-device executions must ENQUEUE in a consistent order
# across devices — two threads interleaving enqueues of collective
# programs over overlapping device sets can deadlock the all-gather
# rendezvous (each device stream runs a different program first). The
# guard serializes only the enqueue; execution stays async, and
# launches on DISJOINT device sets (different dp groups) take disjoint
# locks and overlap fully — which is the dp axis's whole point.
_launch_registry_lock = threading.Lock()
_device_launch_locks: Dict[int, threading.Lock] = {}


class _MultiLock:
    """Acquire a list of locks in order (device-id order — globally
    consistent, so overlapping acquirers can't deadlock each other)."""

    __slots__ = ("_locks",)

    def __init__(self, locks):
        self._locks = locks

    def __enter__(self):
        for lock in self._locks:
            lock.acquire()
        return self

    def __exit__(self, *exc):
        for lock in reversed(self._locks):
            lock.release()
        return False


def launch_guard(mesh: Mesh) -> _MultiLock:
    """The enqueue guard for one SPMD dispatch on `mesh` — hold it
    across the `dispatch.call` that launches the program (NOT across
    the sync): per-device locks in device-id order serialize collective
    launches that share devices and let disjoint dp groups launch
    concurrently."""
    ids = sorted(d.id for d in np.asarray(mesh.devices).flat)
    with _launch_registry_lock:
        locks = [_device_launch_locks.setdefault(i, threading.Lock())
                 for i in ids]
    return _MultiLock(locks)


def make_mesh(num_shards: Optional[int] = None, dp: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (dp, shard) mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if num_shards is None:
        num_shards = len(devices) // dp
    if dp * num_shards > len(devices):
        raise ValueError(f"mesh {dp}x{num_shards} needs {dp * num_shards} devices, have {len(devices)}")
    grid = np.array(devices[: dp * num_shards]).reshape(dp, num_shards)
    return Mesh(grid, (DP_AXIS, SHARD_AXIS))


def dp_size(mesh: Mesh) -> int:
    return int(mesh.shape[DP_AXIS])


def shard_size(mesh: Mesh) -> int:
    return int(mesh.shape[SHARD_AXIS])


def dp_submeshes(mesh: Mesh):
    """One (dp=1, shard=S) mesh per dp row — the disjoint device groups
    independent dispatches overlap on. Each submesh keeps BOTH axis
    names, so every existing kernel spec (P("dp", ...) queries,
    P("shard", ...) corpus rows) runs unchanged on a group.

    Callers should take groups from `parallel.policy.dp_groups` rather
    than calling this directly: the dispatch cache keys executables on
    mesh IDENTITY, so the router and the warmup grid must share one set
    of group objects per serving mesh."""
    grid = np.asarray(mesh.devices)
    return tuple(Mesh(grid[r:r + 1], mesh.axis_names)
                 for r in range(grid.shape[0]))


def corpus_sharding(mesh: Mesh) -> NamedSharding:
    """Rows of the corpus matrix split across the shard axis."""
    return NamedSharding(mesh, P(SHARD_AXIS, None))


def per_shard_sharding(mesh: Mesh) -> NamedSharding:
    """1-D per-row metadata (norms, scales) split across the shard axis."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def query_sharding(mesh: Mesh) -> NamedSharding:
    """Query batches split across dp, replicated across shards."""
    return NamedSharding(mesh, P(DP_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
