"""Device-mesh management.

The reference scales by hash-sharding the corpus across nodes and
scatter-gathering searches (`cluster/routing/OperationRouting.java`,
`AbstractSearchAsyncAction.java:214`). The TPU-native analog is a 2-D
`jax.sharding.Mesh`:

  axis "dp"    — query-batch data parallelism (independent searches)
  axis "shard" — corpus partitioning (one Elasticsearch shard ≈ one mesh
                 column's slice of the HBM-resident matrix)

Cross-shard merges ride ICI collectives inside the compiled program instead
of coordinator-side RPC reduces (`SearchPhaseController.mergeTopDocs:221`).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
SHARD_AXIS = "shard"


def make_mesh(num_shards: Optional[int] = None, dp: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (dp, shard) mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if num_shards is None:
        num_shards = len(devices) // dp
    if dp * num_shards > len(devices):
        raise ValueError(f"mesh {dp}x{num_shards} needs {dp * num_shards} devices, have {len(devices)}")
    grid = np.array(devices[: dp * num_shards]).reshape(dp, num_shards)
    return Mesh(grid, (DP_AXIS, SHARD_AXIS))


def corpus_sharding(mesh: Mesh) -> NamedSharding:
    """Rows of the corpus matrix split across the shard axis."""
    return NamedSharding(mesh, P(SHARD_AXIS, None))


def per_shard_sharding(mesh: Mesh) -> NamedSharding:
    """1-D per-row metadata (norms, scales) split across the shard axis."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def query_sharding(mesh: Mesh) -> NamedSharding:
    """Query batches split across dp, replicated across shards."""
    return NamedSharding(mesh, P(DP_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
