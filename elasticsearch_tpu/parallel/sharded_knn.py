"""Multi-device sharded kNN: scatter-gather as one compiled SPMD program.

The reference's multi-shard search is a coordinator RPC fan-out
(`AbstractSearchAsyncAction.performPhaseOnShard:214`) followed by a
host-side heap merge (`SearchPhaseController.mergeTopDocs:221`). Here the
whole scatter-gather collapses into a single shard_map program:

  1. each mesh column scores its corpus slice (local matmul + top-k),
  2. local doc ids are rebased to global ids via the shard axis index
     (padding rows are masked to -inf / id -1 BEFORE the gather, so a
     ragged shard can never leak aliased ids into the merge),
  3. `lax.all_gather` over the "shard" axis moves the tiny [S, Q, k]
     candidate set across ICI,
  4. every device computes the identical global top-k merge.

No host round-trip, no reduce thread, no `batched_reduce_size` staging — the
merge cost is O(S·Q·k) on ICI, not O(network RPC).

Serving integration (PR 5): the program executes through the shape-bucketed
dispatch cache (`ops/dispatch.py`, kernel ``mesh.knn`` keyed on
(mesh, bucket)), so steady-state sharded traffic never compiles; the
``mesh.append`` kernel writes refresh deltas into each shard's padded
headroom copy-on-write (only the delta crosses PCIe, and the old
buffers are NOT donated — in-flight searches keep a valid snapshot);
and `ShardedFieldState` is the host-side bookkeeping `vectors/store.py`
keeps per mesh-resident field (slot maps, per-shard fill, filter masks).

Sharding over hosts (DCN) uses the same program under multi-process JAX; the
mesh simply spans processes.
"""

from __future__ import annotations

import functools
import threading
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pre-0.6 jax keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import knn as knn_ops
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops.similarity import NEG_INF
from elasticsearch_tpu.parallel import layout
from elasticsearch_tpu.parallel import mesh as mesh_lib


def shard_map(f, *, mesh, in_specs, out_specs):
    """Version-portable shard_map with replication checking off (the
    knob was renamed check_rep → check_vma across jax releases)."""
    try:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
    except TypeError:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


class ShardedCorpus(NamedTuple):
    """Global-view corpus arrays laid out for a (dp, shard) mesh.

    matrix:    [S * rows_per_shard, D] — row-sharded over "shard"
    sq_norms:  [S * rows_per_shard]
    scales:    [S * rows_per_shard]
    num_valid: [S] int32 — valid row count per shard slice
    """

    matrix: jax.Array
    sq_norms: jax.Array
    scales: jax.Array
    num_valid: jax.Array


class ShardLayout(NamedTuple):
    """Host-side layout metadata (NOT part of the device pytree).

    n_shards:       mesh shard-axis size
    docs_per_shard: contiguous original rows assigned to each shard (balanced)
    rows_per_shard: padded device rows per shard (>= docs_per_shard; the
                    slack is append headroom for the write path)
    """

    n_shards: int
    docs_per_shard: int
    rows_per_shard: int

    def to_original_ids(self, global_ids: np.ndarray) -> np.ndarray:
        """Device global row id → original corpus row index (only valid
        for the contiguous build layout — after device appends the
        `ShardedFieldState.slot_map` is authoritative). id -1 (masked
        padding) maps to -1."""
        per, chunk = self.rows_per_shard, self.docs_per_shard
        ids = (global_ids // per) * chunk + (global_ids % per)
        return np.where(global_ids < 0, -1, ids)

    def to_global_ids(self, original_ids: np.ndarray) -> np.ndarray:
        per, chunk = self.rows_per_shard, self.docs_per_shard
        return (original_ids // chunk) * per + (original_ids % chunk)


def build_sharded_corpus(
    vectors: np.ndarray,
    mesh: Mesh,
    metric: str = sim.COSINE,
    dtype: str = "bf16",
    min_headroom: int = 0,
):
    """Partition host vectors into balanced contiguous chunks across shards.

    Mirrors the reference's fixed-shard-count document routing
    (`OperationRouting`: hash mod num_shards) with balanced range
    partitioning: each shard holds `docs_per_shard` contiguous rows padded to
    `rows_per_shard` device rows (the slack doubles as append headroom).
    Returns (ShardedCorpus, ShardLayout).
    """
    n_shards = mesh.shape[mesh_lib.SHARD_AXIS]
    n, d = vectors.shape
    chunk = (n + n_shards - 1) // n_shards
    per = knn_ops.pad_rows(max(chunk + min_headroom, 1))

    # Build entirely in host numpy, then ONE sharded device_put per array —
    # a jnp.concatenate here would materialize the full matrix on a single
    # device before resharding, OOMing exactly at the corpus scale sharding
    # exists for (30.7 GB corpus vs 16 GB/core HBM).
    matrix_host = np.zeros((n_shards * per, d), dtype=np.float32)
    sq_host = np.zeros(n_shards * per, dtype=np.float32)
    num_valid = np.zeros(n_shards, dtype=np.int32)
    for s in range(n_shards):
        lo, hi = min(s * chunk, n), min((s + 1) * chunk, n)
        block = np.asarray(vectors[lo:hi], dtype=np.float32)
        if metric == sim.COSINE and len(block):
            norms = np.linalg.norm(block, axis=-1, keepdims=True)
            block = block / np.maximum(norms, 1e-30)
        matrix_host[s * per: s * per + (hi - lo)] = block
        sq_host[s * per: s * per + (hi - lo)] = (block * block).sum(axis=-1)
        num_valid[s] = hi - lo

    if dtype == "int8":
        from elasticsearch_tpu.ops.quantization import quantize_int8_np
        matrix_host, scales_host = quantize_int8_np(matrix_host)
    elif dtype in ("int4", "binary"):
        # packed ladder rungs shard exactly like f32 rows: the codec
        # packs per row, so the [S·per, W] matrix and its per-row aux
        # scales both ride the `shard_rows` layout rule unchanged
        from elasticsearch_tpu.quant import codec as quant_codec
        enc = quant_codec.get(dtype).encode_np(matrix_host)
        matrix_host, scales_host = enc.data, enc.scales
    else:
        if dtype == "bf16":
            import ml_dtypes
            matrix_host = matrix_host.astype(ml_dtypes.bfloat16)
        scales_host = np.ones(n_shards * per, dtype=np.float32)
    # ONE rule-driven upload for the whole pytree (parallel/layout.py):
    # rows shard over "shard" and replicate across every dp row, so each
    # dp group holds a complete copy and group views come for free
    corpus = layout.shard_put(
        ShardedCorpus(matrix_host, sq_host, scales_host, num_valid), mesh)
    return corpus, ShardLayout(n_shards, chunk, per)


# ---------------------------------------------------------------------------
# Search program (dispatched: kernel "mesh.knn")
# ---------------------------------------------------------------------------

def _knn_step(q, mat, sqn, scl, nvalid, fmask, *, k, metric, precision,
              block_size):
    """Per-shard body: local exact kNN, padding masked OUT before the
    gather (a ragged shard whose num_valid < k would otherwise feed
    aliased padding ids into the merge), then the ICI candidate merge."""
    from elasticsearch_tpu.ops.topk import merge_top_k

    local = knn_ops.Corpus(mat, sqn, scl, nvalid[0])
    rows_per_shard = mat.shape[0]
    s, i = knn_ops.knn_search(q, local, k, metric=metric,
                              filter_mask=fmask, precision=precision,
                              block_size=block_size)
    shard_id = jax.lax.axis_index(mesh_lib.SHARD_AXIS)
    # the local top-k returns NEG_INF for padding/filtered slots but an
    # ARBITRARY row index beside it; pin both so no consumer can alias
    valid = s > NEG_INF
    s = jnp.where(valid, s, -jnp.inf)
    gids = jnp.where(valid, i + shard_id * rows_per_shard,
                     jnp.int32(-1))
    all_s = jax.lax.all_gather(s, mesh_lib.SHARD_AXIS)   # [S, Qdp, k] over ICI
    all_i = jax.lax.all_gather(gids, mesh_lib.SHARD_AXIS)
    return merge_top_k(all_s, all_i, k)


def _distributed_knn_impl(queries, corpus, filter_mask, k, mesh,
                          metric=sim.COSINE, precision="bf16",
                          block_size=None):
    # in_specs from the SAME rule table that laid the corpus out
    # (parallel/layout.py) — specs can't drift from residency, and the
    # dp axis applies here without widening any hand-built spec
    corpus_specs = layout.in_specs_for(corpus)
    out_specs = (layout.query_spec(2), layout.query_spec(2))
    step = functools.partial(_knn_step, k=k, metric=metric,
                             precision=precision, block_size=block_size)
    if filter_mask is None:
        def step_nf(q, mat, sqn, scl, nvalid):
            return step(q, mat, sqn, scl, nvalid, None)
        fn = shard_map(
            step_nf, mesh=mesh,
            in_specs=(layout.query_spec(2),) + tuple(corpus_specs),
            out_specs=out_specs)
        return fn(queries, corpus.matrix, corpus.sq_norms, corpus.scales,
                  corpus.num_valid)
    fn = shard_map(
        step, mesh=mesh,
        in_specs=(layout.query_spec(2),) + tuple(corpus_specs)
        + (layout.mask_spec(filter_mask.ndim),), out_specs=out_specs)
    return fn(queries, corpus.matrix, corpus.sq_norms, corpus.scales,
              corpus.num_valid, filter_mask)


def _grid_mesh_knn(statics, sigs) -> bool:
    """Closed sharded grid: bucketed query count, k on the ladder (or
    clamped to the per-shard row count), lane-padded shard slices."""
    q_shape = sigs[0][0]                    # queries [Q, D]
    n_rows = sigs[1][0][0]                  # matrix [S * per, D]
    mesh = statics["mesh"]
    n_shards = mesh.shape[mesh_lib.SHARD_AXIS]
    per = n_rows // max(n_shards, 1)
    return (dispatch.is_query_bucket(q_shape[0])
            and dispatch.in_k_grid(int(statics["k"]), limit=per)
            and per % knn_ops.LANE == 0)


dispatch.DISPATCH.register(
    "mesh.knn", _distributed_knn_impl,
    static_argnames=("k", "mesh", "metric", "precision", "block_size"),
    grid_check=_grid_mesh_knn)


def distributed_knn_search(
    queries: jax.Array,
    corpus: ShardedCorpus,
    k: int,
    mesh: Mesh,
    metric: str = sim.COSINE,
    filter_mask: Optional[jax.Array] = None,
    precision: str = "bf16",
    block_size: Optional[int] = None,
):
    """Search queries [Q, D] against a mesh-sharded corpus.

    Q must be divisible by the dp axis size. filter_mask is [S * per] (one
    shared searchable-set) or [Q, S * per] (per-query pre-filters).
    Returns (scores [Q, k], global_ids [Q, k]) fully replicated across the
    mesh; empty/padding slots come back as (-inf, -1).

    Executes through the shape-bucketed dispatch cache (kernel
    ``mesh.knn``, AOT executables keyed on (mesh, bucket)); calls from
    inside an enclosing jit (the bench scan harness) inline. The launch
    guard serializes the ENQUEUE per device set (collective programs
    that share devices must enqueue in one order) and returns un-synced
    arrays — dispatches on disjoint dp groups overlap end to end.
    """
    with mesh_lib.launch_guard(mesh):
        return dispatch.call("mesh.knn", queries, corpus, filter_mask,
                             k=k, mesh=mesh, metric=metric,
                             precision=precision, block_size=block_size)


# ---------------------------------------------------------------------------
# Incremental append (dispatched: kernel "mesh.append")
# ---------------------------------------------------------------------------

def _append_impl(matrix, sq_norms, scales, num_valid, new_mat, new_sq,
                 new_scales, new_counts, mesh):
    """Write per-shard delta rows into the padded headroom: refresh
    appends move only the delta across PCIe, never the resident corpus.
    The old buffers are NOT donated (see the registration below) — the
    program produces a fresh corpus pytree so searches in flight against
    the pre-append state keep reading valid arrays."""
    def step(mat, sqn, scl, nv, nmat, nsq, nscl, ncnt):
        m = nmat.shape[0]
        start = nv[0]
        lane = jnp.arange(m, dtype=jnp.int32)
        ok = lane < ncnt[0]
        # out-of-range target rows (beyond this shard's delta count) are
        # DROPPED by the scatter, leaving resident rows untouched
        tgt = jnp.where(ok, start + lane, jnp.int32(mat.shape[0]))
        mat = mat.at[tgt].set(nmat.astype(mat.dtype), mode="drop")
        sqn = sqn.at[tgt].set(nsq, mode="drop")
        scl = scl.at[tgt].set(nscl, mode="drop")
        return mat, sqn, scl, nv + ncnt[0]

    r2, r1 = layout.rows_spec(2), layout.rows_spec(1)
    fn = shard_map(
        step, mesh=mesh,
        in_specs=(r2, r1, r1, r1, r2, r1, r1, r1),
        out_specs=(r2, r1, r1, r1))
    mat, sqn, scl, nv = fn(matrix, sq_norms, scales, num_valid,
                           new_mat, new_sq, new_scales, new_counts)
    return ShardedCorpus(mat, sqn, scl, nv)


def _grid_mesh_append(statics, sigs) -> bool:
    """Delta row count per shard padded to a query-style bucket — refresh
    deltas of any size reuse a small closed set of append programs."""
    n_rows = sigs[4][0][0]                  # new_mat [S * m, D]
    mesh = statics["mesh"]
    n_shards = mesh.shape[mesh_lib.SHARD_AXIS]
    m = n_rows // max(n_shards, 1)
    return dispatch.is_query_bucket(m)


# NO donation: `ShardedFieldState.append` is copy-on-write — searches
# dispatched against the pre-append state mid-refresh still read the old
# buffers, so donating them would hand deleted arrays to a live dispatch
dispatch.DISPATCH.register(
    "mesh.append", _append_impl, static_argnames=("mesh",),
    grid_check=_grid_mesh_append)


# ---------------------------------------------------------------------------
# Host-side field state (the vectors/store.py mesh bookkeeping)
# ---------------------------------------------------------------------------

class ShardedFieldState:
    """One vector field's mesh-resident corpus + host bookkeeping.

    Owns the slot map (device global row -> flat corpus row index), the
    per-shard fill counts the append planner balances against, and the
    filter-mask builder. `append` places refresh deltas into the shards
    with the most headroom and ships ONLY the delta (kernel
    ``mesh.append``); when headroom runs out the caller rebuilds."""

    __slots__ = ("corpus", "layout", "mesh", "metric", "dtype",
                 "slot_map", "shard_counts", "n_rows", "_views",
                 "_views_lock")

    def __init__(self, vectors: np.ndarray, mesh: Mesh, metric: str,
                 dtype: str, min_headroom: Optional[int] = None):
        n = len(vectors)
        n_shards = mesh.shape[mesh_lib.SHARD_AXIS]
        chunk = (n + n_shards - 1) // n_shards
        if min_headroom is None:
            # append headroom: an eighth of the shard (>= one lane tile) —
            # refreshes append in place until the corpus grows 12.5%,
            # then one rebuild re-balances
            min_headroom = max(knn_ops.LANE, chunk // 8)
        self.corpus, self.layout = build_sharded_corpus(
            vectors, mesh, metric=metric, dtype=dtype,
            min_headroom=min_headroom)
        self.mesh = mesh
        self.metric = metric
        self.dtype = dtype
        self.n_rows = n
        self._views = {}
        self._views_lock = threading.Lock()
        per = self.layout.rows_per_shard
        self.slot_map = np.full(n_shards * per, -1, dtype=np.int64)
        self.shard_counts = np.zeros(n_shards, dtype=np.int64)
        for s in range(n_shards):
            lo, hi = min(s * chunk, n), min((s + 1) * chunk, n)
            self.slot_map[s * per: s * per + (hi - lo)] = np.arange(lo, hi)
            self.shard_counts[s] = hi - lo

    @property
    def n_shards(self) -> int:
        return self.layout.n_shards

    def headroom(self) -> int:
        return int((self.layout.rows_per_shard
                    - self.shard_counts).sum())

    def can_append(self, n_new: int) -> bool:
        return n_new <= self.headroom()

    def append(self, new_vectors: np.ndarray) -> "ShardedFieldState":
        """Place `new_vectors` (flat corpus rows n_rows..n_rows+m) into
        per-shard headroom, most-free shards first, and ship ONLY the
        delta with one ``mesh.append`` dispatch.

        Copy-on-write: returns a NEW state and leaves `self` (corpus
        buffers AND slot_map/shard_counts bookkeeping) untouched, so a
        search dispatched against the previously-installed FieldCorpus
        mid-refresh keeps a consistent snapshot. The delta program
        therefore must NOT donate the old buffers — append pays a
        transient second matrix allocation on device, but the host->
        device transfer (the cost that scales with the corpus) stays
        delta-sized."""
        m_total = len(new_vectors)
        if m_total == 0:
            return self
        per = self.layout.rows_per_shard
        S = self.n_shards
        free = per - self.shard_counts
        order = np.argsort(-free, kind="stable")
        counts = np.zeros(S, dtype=np.int64)
        remaining = m_total
        # water-fill: level the most-free shards first so the layout
        # stays balanced under repeated appends
        while remaining > 0:
            target = [s for s in order if free[s] - counts[s] > 0]
            if not target:
                raise ValueError("sharded corpus append exceeds headroom")
            share = max(1, remaining // len(target))
            for s in target:
                take = min(share, int(free[s] - counts[s]), remaining)
                counts[s] += take
                remaining -= take
                if remaining == 0:
                    break

        m_pad = dispatch.bucket_queries(int(counts.max()))
        d = new_vectors.shape[1]
        blocks = np.zeros((S * m_pad, d), dtype=np.float32)
        new_sq = np.zeros(S * m_pad, dtype=np.float32)
        new_scales = np.ones(S * m_pad, dtype=np.float32)
        slot_map = self.slot_map.copy()
        pos = 0
        for s in range(S):
            c = int(counts[s])
            if c == 0:
                continue
            block = np.asarray(new_vectors[pos:pos + c], dtype=np.float32)
            if self.metric == sim.COSINE:
                norms = np.linalg.norm(block, axis=-1, keepdims=True)
                block = block / np.maximum(norms, 1e-30)
            blocks[s * m_pad: s * m_pad + c] = block
            new_sq[s * m_pad: s * m_pad + c] = (block * block).sum(axis=-1)
            start = int(self.shard_counts[s])
            slot_map[s * per + start: s * per + start + c] = \
                np.arange(self.n_rows + pos, self.n_rows + pos + c)
            pos += c
        if self.dtype == "int8":
            from elasticsearch_tpu.ops.quantization import quantize_int8_np
            q8, sc = quantize_int8_np(blocks)
            blocks, new_scales = q8, sc
        elif self.dtype in ("int4", "binary"):
            from elasticsearch_tpu.quant import codec as quant_codec
            enc = quant_codec.get(self.dtype).encode_np(blocks)
            blocks, new_scales = enc.data, enc.scales
        elif self.dtype == "bf16":
            import ml_dtypes
            blocks = blocks.astype(ml_dtypes.bfloat16)
        nm = jax.device_put(blocks, mesh_lib.corpus_sharding(self.mesh))
        nsq = jax.device_put(new_sq, mesh_lib.per_shard_sharding(self.mesh))
        nsc = jax.device_put(new_scales,
                             mesh_lib.per_shard_sharding(self.mesh))
        ncnt = jax.device_put(counts.astype(np.int32),
                              mesh_lib.per_shard_sharding(self.mesh))
        # launch-guarded: the append program shares devices with every
        # in-flight search on this mesh, and interleaved collective
        # enqueues can deadlock the device streams
        with mesh_lib.launch_guard(self.mesh):
            corpus = dispatch.call(
                "mesh.append", self.corpus.matrix, self.corpus.sq_norms,
                self.corpus.scales, self.corpus.num_valid, nm, nsq, nsc,
                ncnt, mesh=self.mesh)
        new = ShardedFieldState.__new__(ShardedFieldState)
        new.corpus = corpus
        new.layout = self.layout
        new.mesh = self.mesh
        new.metric = self.metric
        new.dtype = self.dtype
        new.slot_map = slot_map
        new.shard_counts = self.shard_counts + counts
        new.n_rows = self.n_rows + m_total
        # fresh (empty) dp-group view cache: every replica view of the
        # NEW state derives from ITS corpus pytree, so an install can
        # never leave one dp group serving the pre-append arrays while
        # another serves the post-append ones
        new._views = {}
        new._views_lock = threading.Lock()
        return new

    # ---------------------------------------------------------- serving
    def corpus_for(self, mesh: Mesh) -> ShardedCorpus:
        """The corpus pytree to dispatch on `mesh`: the resident arrays
        for the build mesh, a cached dp-group VIEW for one of its
        submeshes. A view is a rule-driven re-layout (`layout.view_for`)
        of this state's dp-replicated arrays — the group's devices
        already hold every shard, so building one is device-side and
        ~free, and every group reads the SAME immutable snapshot: replica
        consistency is structural, not synchronized."""
        if mesh is self.mesh:
            return self.corpus
        with self._views_lock:
            view = self._views.get(mesh)
            if view is None:
                view = layout.view_for(self.corpus, mesh)
                self._views[mesh] = view
            return view

    def filter_mask(self, allowed_flat: np.ndarray) -> np.ndarray:
        """Map a flat-corpus-row bool mask [n_rows] to the device global
        row space [S * per] via the slot map."""
        m = np.zeros(len(self.slot_map), dtype=bool)
        vs = self.slot_map >= 0
        m[vs] = allowed_flat[self.slot_map[vs]]
        return m

    def map_ids(self, global_ids: np.ndarray) -> np.ndarray:
        """Device global ids -> flat corpus row indices (-1 invalid)."""
        out = np.full(global_ids.shape, -1, dtype=np.int64)
        ok = global_ids >= 0
        out[ok] = self.slot_map[global_ids[ok]]
        return out

    def query_sharding(self, mesh: Optional[Mesh] = None) -> NamedSharding:
        return mesh_lib.query_sharding(mesh if mesh is not None
                                       else self.mesh)

    def mask_sharding(self, ndim: int,
                      mesh: Optional[Mesh] = None) -> NamedSharding:
        mesh = mesh if mesh is not None else self.mesh
        return NamedSharding(mesh, layout.mask_spec(ndim))

    def warmup_entries(self, dims: int):
        """(kernel, arg specs, statics) entries pre-compiling the sharded
        serving grid — mirrors `vectors/store._schedule_warmup` but with
        mesh-sharded input layouts baked into the AOT specs. With dp > 1
        the grid covers BOTH routes the dp-vs-shard router can pick: the
        full-mesh program (query buckets the dp axis divides) and every
        dp-group submesh (all interactive buckets), so strict mode stays
        zero-compile whichever way a dispatch routes."""
        per = self.layout.rows_per_shard
        from elasticsearch_tpu.parallel import policy
        meshes = [self.mesh]
        dp = mesh_lib.dp_size(self.mesh)
        if dp > 1:
            meshes.extend(policy.dp_groups(self.mesh))
        entries = []
        for mesh in meshes:
            corpus_spec = layout.shape_specs(self.corpus, mesh)
            mesh_dp = mesh_lib.dp_size(mesh)
            for q in dispatch.WARMUP_QUERY_BUCKETS:
                if q % mesh_dp:
                    continue   # the router never full-meshes this bucket
                qspec = jax.ShapeDtypeStruct(
                    (q, dims), jnp.float32,
                    sharding=mesh_lib.query_sharding(mesh))
                for k in dispatch.WARMUP_K_BUCKETS:
                    k_b = dispatch.bucket_k(min(k, per), limit=per)
                    entries.append((
                        "mesh.knn", (qspec, corpus_spec, None),
                        {"k": k_b, "mesh": mesh, "metric": self.metric,
                         "precision": "bf16", "block_size": None}))
        return entries


def extend_or_build(old_state: Optional[ShardedFieldState],
                    vectors: np.ndarray, prefix_rows: int, mesh: Mesh,
                    metric: str, dtype: str):
    """One owner for the append-vs-rebuild decision both refresh sync
    and the segments merge scheduler make: when `old_state` holds
    exactly the first `prefix_rows` of `vectors` (caller-verified row
    identity) on the same mesh/metric/dtype and its per-shard headroom
    fits the delta, ship ONLY the delta (``mesh.append``,
    copy-on-write); otherwise build the sharded corpus from scratch.
    Returns (state, appended)."""
    n = len(vectors)
    if (old_state is not None and old_state.mesh is mesh
            and old_state.dtype == dtype and old_state.metric == metric
            and old_state.n_rows == prefix_rows and 0 < prefix_rows <= n
            and old_state.can_append(n - prefix_rows)):
        if n == prefix_rows:
            return old_state, True
        return old_state.append(np.asarray(vectors[prefix_rows:],
                                           dtype=np.float32)), True
    return ShardedFieldState(np.asarray(vectors, dtype=np.float32),
                             mesh, metric, dtype), False
