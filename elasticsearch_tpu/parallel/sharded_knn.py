"""Multi-device sharded kNN: scatter-gather as one compiled SPMD program.

The reference's multi-shard search is a coordinator RPC fan-out
(`AbstractSearchAsyncAction.performPhaseOnShard:214`) followed by a
host-side heap merge (`SearchPhaseController.mergeTopDocs:221`). Here the
whole scatter-gather collapses into a single pjit/shard_map program:

  1. each mesh column scores its corpus slice (local matmul + top-k),
  2. local doc ids are rebased to global ids via the shard axis index,
  3. `lax.all_gather` over the "shard" axis moves the tiny [S, Q, k]
     candidate set across ICI,
  4. every device computes the identical global top-k merge.

No host round-trip, no reduce thread, no `batched_reduce_size` staging — the
merge cost is O(S·Q·k) on ICI, not O(network RPC).

Sharding over hosts (DCN) uses the same program under multi-process JAX; the
mesh simply spans processes.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pre-0.6 jax keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map

from elasticsearch_tpu.ops import knn as knn_ops
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops.topk import merge_top_k
from elasticsearch_tpu.parallel import mesh as mesh_lib


def shard_map(f, *, mesh, in_specs, out_specs):
    """Version-portable shard_map with replication checking off (the
    knob was renamed check_rep → check_vma across jax releases)."""
    try:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
    except TypeError:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


class ShardedCorpus(NamedTuple):
    """Global-view corpus arrays laid out for a (dp, shard) mesh.

    matrix:    [S * rows_per_shard, D] — row-sharded over "shard"
    sq_norms:  [S * rows_per_shard]
    scales:    [S * rows_per_shard]
    num_valid: [S] int32 — valid row count per shard slice
    """

    matrix: jax.Array
    sq_norms: jax.Array
    scales: jax.Array
    num_valid: jax.Array


class ShardLayout(NamedTuple):
    """Host-side layout metadata (NOT part of the device pytree).

    n_shards:       mesh shard-axis size
    docs_per_shard: contiguous original rows assigned to each shard (balanced)
    rows_per_shard: padded device rows per shard (>= docs_per_shard; the
                    slack is append headroom for the write path)
    """

    n_shards: int
    docs_per_shard: int
    rows_per_shard: int

    def to_original_ids(self, global_ids: np.ndarray) -> np.ndarray:
        """Device global row id → original corpus row index."""
        per, chunk = self.rows_per_shard, self.docs_per_shard
        return (global_ids // per) * chunk + (global_ids % per)

    def to_global_ids(self, original_ids: np.ndarray) -> np.ndarray:
        per, chunk = self.rows_per_shard, self.docs_per_shard
        return (original_ids // chunk) * per + (original_ids % chunk)


def build_sharded_corpus(
    vectors: np.ndarray,
    mesh: Mesh,
    metric: str = sim.COSINE,
    dtype: str = "bf16",
    min_headroom: int = 0,
):
    """Partition host vectors into balanced contiguous chunks across shards.

    Mirrors the reference's fixed-shard-count document routing
    (`OperationRouting`: hash mod num_shards) with balanced range
    partitioning: each shard holds `docs_per_shard` contiguous rows padded to
    `rows_per_shard` device rows (the slack doubles as append headroom).
    Returns (ShardedCorpus, ShardLayout).
    """
    n_shards = mesh.shape[mesh_lib.SHARD_AXIS]
    n, d = vectors.shape
    chunk = (n + n_shards - 1) // n_shards
    per = knn_ops.pad_rows(max(chunk + min_headroom, 1))

    # Build entirely in host numpy, then ONE sharded device_put per array —
    # a jnp.concatenate here would materialize the full matrix on a single
    # device before resharding, OOMing exactly at the corpus scale sharding
    # exists for (30.7 GB corpus vs 16 GB/core HBM).
    np_dtype = {"f32": np.float32, "bf16": np.float32, "int8": np.float32}[dtype]
    matrix_host = np.zeros((n_shards * per, d), dtype=np_dtype)
    sq_host = np.zeros(n_shards * per, dtype=np.float32)
    num_valid = np.zeros(n_shards, dtype=np.int32)
    for s in range(n_shards):
        lo, hi = min(s * chunk, n), min((s + 1) * chunk, n)
        block = np.asarray(vectors[lo:hi], dtype=np.float32)
        if metric == sim.COSINE and len(block):
            norms = np.linalg.norm(block, axis=-1, keepdims=True)
            block = block / np.maximum(norms, 1e-30)
        matrix_host[s * per: s * per + (hi - lo)] = block
        sq_host[s * per: s * per + (hi - lo)] = (block * block).sum(axis=-1)
        num_valid[s] = hi - lo

    if dtype == "int8":
        from elasticsearch_tpu.ops.quantization import quantize_int8_np
        q, scales_host = quantize_int8_np(matrix_host)
        matrix = jax.device_put(q, mesh_lib.corpus_sharding(mesh))
    else:
        if dtype == "bf16":
            import ml_dtypes
            matrix_host = matrix_host.astype(ml_dtypes.bfloat16)
        matrix = jax.device_put(matrix_host, mesh_lib.corpus_sharding(mesh))
        scales_host = np.ones(n_shards * per, dtype=np.float32)
    sq_norms = jax.device_put(sq_host, mesh_lib.per_shard_sharding(mesh))
    scales = jax.device_put(scales_host, mesh_lib.per_shard_sharding(mesh))
    nv = jax.device_put(num_valid, mesh_lib.per_shard_sharding(mesh))
    return ShardedCorpus(matrix, sq_norms, scales, nv), ShardLayout(n_shards, chunk, per)


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "precision", "block_size", "mesh"),
)
def distributed_knn_search(
    queries: jax.Array,
    corpus: ShardedCorpus,
    k: int,
    mesh: Mesh,
    metric: str = sim.COSINE,
    filter_mask: Optional[jax.Array] = None,
    precision: str = "bf16",
    block_size: Optional[int] = None,
):
    """Search queries [Q, D] against a mesh-sharded corpus.

    Q must be divisible by the dp axis size. Returns (scores [Q, k],
    global_ids [Q, k]) fully replicated across the mesh.
    """
    in_specs = (
        P(mesh_lib.DP_AXIS, None),          # queries
        P(mesh_lib.SHARD_AXIS, None),       # matrix
        P(mesh_lib.SHARD_AXIS),             # sq_norms
        P(mesh_lib.SHARD_AXIS),             # scales
        P(mesh_lib.SHARD_AXIS),             # num_valid
        (P(mesh_lib.SHARD_AXIS) if filter_mask is not None else None),
    )
    out_specs = (P(mesh_lib.DP_AXIS, None), P(mesh_lib.DP_AXIS, None))

    def step(q, mat, sqn, scl, nvalid, fmask):
        local = knn_ops.Corpus(mat, sqn, scl, nvalid[0])
        rows_per_shard = mat.shape[0]
        s, i = knn_ops.knn_search(q, local, k, metric=metric,
                                  filter_mask=fmask, precision=precision,
                                  block_size=block_size)
        shard_id = jax.lax.axis_index(mesh_lib.SHARD_AXIS)
        gids = i + shard_id * rows_per_shard
        all_s = jax.lax.all_gather(s, mesh_lib.SHARD_AXIS)   # [S, Qdp, k] over ICI
        all_i = jax.lax.all_gather(gids, mesh_lib.SHARD_AXIS)
        return merge_top_k(all_s, all_i, k)

    if filter_mask is None:
        def step_nf(q, mat, sqn, scl, nvalid):
            return step(q, mat, sqn, scl, nvalid, None)
        fn = shard_map(step_nf, mesh=mesh, in_specs=in_specs[:-1],
                       out_specs=out_specs)
        return fn(queries, corpus.matrix, corpus.sq_norms, corpus.scales, corpus.num_valid)

    fn = shard_map(step, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return fn(queries, corpus.matrix, corpus.sq_norms, corpus.scales,
              corpus.num_valid, filter_mask)
