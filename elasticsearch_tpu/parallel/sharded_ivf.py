"""Mesh-sharded IVF: replicated centroids, shard-partitioned posting lists.

The `tpu_ivf` engine's SPMD execution mode (PR 5). The partition layout
(`ann/ivf_index.py`) splits over the mesh shard axis by PARTITION id —
the IVF analog of the reference hash-sharding documents across nodes —
while the tiny centroid matrix replicates everywhere:

  route:  every shard computes the identical probe set from the
          replicated centroids (no collective — routing is data-parallel
          by construction),
  score:  each shard scores only the probed partitions IT owns
          (`pid // nlist_local == shard_id`); unowned probes mask to
          NEG_INF exactly like empty partition slots,
  merge:  `lax.all_gather` ships the [S, Q, k] local candidates over ICI
          and every device computes the identical global top-k.

Row ids in the layout are flat device-corpus rows (the same space the
single-device kernel reports), so sharded results are byte-comparable to
`ops/knn_ivf.score_probes` — the parity the tier-1 mesh suite pins.

Executes through the shape-bucketed dispatch cache (kernel ``mesh.ivf``,
executables keyed on (mesh, bucket)); steady-state sharded IVF traffic
compiles nothing.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops.similarity import NEG_INF
from elasticsearch_tpu.parallel import layout
from elasticsearch_tpu.parallel import mesh as mesh_lib
from elasticsearch_tpu.parallel.sharded_knn import shard_map


class ShardedIVF(NamedTuple):
    """Device pytree of a partition layout laid out for a (dp, shard)
    mesh. Same field semantics as `ops/knn_ivf.IVFPartitions`, except
    `parts`/`part_*` are padded to a multiple of the shard count along
    the partition axis (pad partitions hold row id -1 everywhere) and
    row-sharded over it; `centroids`/`centroid_sq` stay UNPADDED and
    replicated so routing scores are bitwise those of the single-device
    kernel."""

    centroids: jax.Array       # [nlist, D] replicated
    centroid_sq: jax.Array     # [nlist] replicated
    parts: jax.Array           # [nlist_pad, cap, D] sharded over "shard"
    part_scales: jax.Array     # [nlist_pad, cap] sharded
    part_sq: jax.Array         # [nlist_pad, cap] sharded
    part_rows: jax.Array       # [nlist_pad, cap] int32 sharded; -1 pad


def build_sharded_partitions(index, mesh: Mesh) -> ShardedIVF:
    """Upload one `ann/ivf_index.IVFIndex` host mirror as a mesh-sharded
    pytree. Quantization runs the exact `device_partitions` recipe over
    the UNPADDED layout first, so every stored value is bitwise the
    single-device copy's."""
    from elasticsearch_tpu.ops.quantization import quantize_int8_np

    S = mesh.shape[mesh_lib.SHARD_AXIS]
    nlist, cap, dims = index.part_vecs.shape
    nlist_pad = -(-nlist // S) * S

    valid = index.part_rows >= 0
    part_sq = np.einsum("kcd,kcd->kc", index.part_vecs, index.part_vecs)
    if index.dtype == "int8":
        flat = index.part_vecs.reshape(-1, dims)
        q8, scales = quantize_int8_np(flat)
        parts_host = q8.reshape(nlist, cap, dims)
        scales_host = np.where(valid, scales.reshape(nlist, cap),
                               0.0).astype(np.float32)
        np_dtype = np.int8
    elif index.dtype in ("int4", "binary"):
        # packed ladder rungs: the codec registry's recipe, bitwise the
        # single-device `device_partitions` copy
        from elasticsearch_tpu.quant import codec as quant_codec
        codec = quant_codec.get(index.dtype)
        enc = codec.encode_np(index.part_vecs.reshape(-1, dims))
        w = codec.packed_width(dims)
        parts_host = enc.data.reshape(nlist, cap, w)
        scales_host = np.where(valid, enc.scales.reshape(nlist, cap),
                               0.0).astype(np.float32)
        np_dtype = codec.packed_np_dtype
    else:
        import ml_dtypes
        np_dtype = (ml_dtypes.bfloat16 if index.dtype == "bf16"
                    else np.float32)
        parts_host = index.part_vecs.astype(np_dtype)
        scales_host = valid.astype(np.float32)

    def pad(a, fill=0):
        if nlist_pad == nlist:
            return a
        out = np.full((nlist_pad,) + a.shape[1:], fill, dtype=a.dtype)
        out[:nlist] = a
        return out

    # rule-driven upload (parallel/layout.py): centroids replicate
    # everywhere (routing tables), part_* shard by partition id over the
    # shard axis and replicate across dp rows
    return layout.shard_put(ShardedIVF(
        centroids=index.centroids.astype(np.float32),
        centroid_sq=np.einsum("kd,kd->k", index.centroids,
                              index.centroids).astype(np.float32),
        parts=pad(parts_host),
        part_scales=pad(scales_host),
        part_sq=pad(part_sq.astype(np.float32)),
        part_rows=pad(index.part_rows, fill=-1)), mesh)


def _ivf_step(q, cents, cent_sq, parts, pscales, psq, prows, *, k, nprobe,
              metric, precision):
    """Per-shard body: replicated routing, owned-probe pruned scoring,
    ICI candidate merge."""
    from elasticsearch_tpu.ops.topk import merge_top_k

    # route on the replicated centroids — identical probe ids everywhere
    dots = jax.lax.dot_general(
        q, cents, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if metric == sim.L2_NORM:
        route_scores = sim.l2_raw_from_dots(dots, q, cent_sq)
    else:
        route_scores = dots
    _, probe_ids = jax.lax.top_k(route_scores, nprobe)
    probe_ids = probe_ids.astype(jnp.int32)

    nq = q.shape[0]
    nlist_local = parts.shape[0]
    shard_id = jax.lax.axis_index(mesh_lib.SHARD_AXIS)
    lo = shard_id * nlist_local
    mm_dtype = jnp.float32 if precision == "f32" else jnp.bfloat16
    init = (jnp.full((nq, k), NEG_INF, dtype=jnp.float32),
            jnp.full((nq, k), -1, dtype=jnp.int32))

    from elasticsearch_tpu.quant import codec as quant_codec
    qbits = None
    if parts.dtype == jnp.uint32:
        qbits = quant_codec.pack_sign_bits_jnp(q)

    def body(carry, pid):
        best_s, best_i = carry
        local_pid = pid - lo
        owned = (local_pid >= 0) & (local_pid < nlist_local)
        safe = jnp.clip(local_pid, 0, nlist_local - 1)
        block = jnp.take(parts, safe, axis=0)          # [Q, cap, D]
        rows = jnp.take(prows, safe, axis=0)           # [Q, cap]
        if parts.dtype == jnp.uint8:
            # int4 packed nibbles (the codec's one blocked-take recipe)
            dots = quant_codec.int4_blocked_dots_jnp(q, block, mm_dtype)
            dots = dots * jnp.take(pscales, safe, axis=0)
        elif parts.dtype == jnp.uint32:
            dots = quant_codec.hamming_pseudo_dots_blocked_jnp(qbits,
                                                               block)
        else:
            dots = jnp.einsum(
                "qd,qcd->qc", q.astype(mm_dtype), block.astype(mm_dtype),
                preferred_element_type=jnp.float32)
            if parts.dtype == jnp.int8:
                dots = dots * jnp.take(pscales, safe, axis=0)
        if metric == sim.L2_NORM:
            part_sq_b = jnp.take(psq, safe, axis=0)
            q_sq = jnp.sum(q * q, axis=-1, keepdims=True)
            s = 2.0 * dots - q_sq - part_sq_b
        else:
            s = dots
        keep = owned[:, None] & (rows >= 0)
        s = jnp.where(keep, s, NEG_INF)
        rows = jnp.where(keep, rows, -1)
        cat_s = jnp.concatenate([best_s, s], axis=1)
        cat_i = jnp.concatenate([best_i, rows], axis=1)
        vals, pos = jax.lax.top_k(cat_s, k)
        return (vals, jnp.take_along_axis(cat_i, pos, axis=1)), None

    (best_s, best_i), _ = jax.lax.scan(body, init, probe_ids.T)
    all_s = jax.lax.all_gather(best_s, mesh_lib.SHARD_AXIS)  # [S, Q, k]
    all_i = jax.lax.all_gather(best_i, mesh_lib.SHARD_AXIS)
    return merge_top_k(all_s, all_i, k)


def _sharded_ivf_impl(queries, sivf, k, nprobe, mesh,
                      metric=sim.COSINE, precision="bf16"):
    # in_specs from the same rule table that laid the pytree out
    in_specs = (layout.query_spec(2), layout.in_specs_for(sivf))
    out_specs = (layout.query_spec(2), layout.query_spec(2))
    step = functools.partial(_ivf_step, k=k, nprobe=nprobe, metric=metric,
                             precision=precision)

    def run(q, cents, cent_sq, parts, pscales, psq, prows):
        return step(q, cents, cent_sq, parts, pscales, psq, prows)

    fn = shard_map(run, mesh=mesh,
                   in_specs=(in_specs[0],) + tuple(in_specs[1]),
                   out_specs=out_specs)
    return fn(queries, sivf.centroids, sivf.centroid_sq, sivf.parts,
              sivf.part_scales, sivf.part_sq, sivf.part_rows)


def _grid_mesh_ivf(statics, sigs) -> bool:
    """Bucketed query count, pow-2 nprobe (or full nlist), k on the
    ladder or clamped to the probed-row budget — the same closed set the
    single-device `ivf.*` kernels enforce."""
    if not dispatch.is_query_bucket(sigs[0][0][0]):
        return False
    nlist = sigs[1][0][0]                   # centroids [nlist, D]
    cap = sigs[3][0][1]                     # parts [nlist_pad, cap, D]
    npro = int(statics["nprobe"])
    k = int(statics["k"])
    pow2_ok = npro == int(nlist) or (npro >= 1 and npro & (npro - 1) == 0)
    return pow2_ok and dispatch.in_k_grid(k, limit=npro * int(cap))


dispatch.DISPATCH.register(
    "mesh.ivf", _sharded_ivf_impl,
    static_argnames=("k", "nprobe", "mesh", "metric", "precision"),
    grid_check=_grid_mesh_ivf)


def sharded_ivf_search(queries: jax.Array, sivf: ShardedIVF, k: int,
                       nprobe: int, mesh: Mesh, metric: str = sim.COSINE,
                       precision: str = "bf16"):
    """Pruned top-k over the mesh-sharded layout: ONE compiled program
    (route + owned-probe score + all-gather merge).

    queries: [Q, D] metric-prepped, Q divisible by the dp axis.
    Returns (scores [Q, k], rows [Q, k] flat device-corpus row ids);
    empty slots come back (NEG_INF, -1) — the single-device contract.
    Enqueue is launch-guarded per device set (collective-ordering
    safety across concurrent dp-group dispatches).
    """
    with mesh_lib.launch_guard(mesh):
        return dispatch.call("mesh.ivf", queries, sivf, k=k,
                             nprobe=nprobe, mesh=mesh, metric=metric,
                             precision=precision)


def warmup_entries(index, mesh: Mesh, nprobe: int):
    """Pre-compile the sharded IVF serving grid (the store's
    warmup-at-sync hook). SHAPE-ONLY: the AOT specs derive from the
    host layout via the same padding math as `build_sharded_partitions`,
    so scheduling warmup never uploads the sharded pytree — the refresh
    thread must not pay (and re-pay, since `IVFIndex.add` invalidates
    the cached upload) a corpus-sized transfer per refresh. The actual
    pytree build stays lazy on the first mesh-routed query, which then
    finds its executable already compiled."""
    from elasticsearch_tpu.parallel import policy

    S = mesh.shape[mesh_lib.SHARD_AXIS]
    nlist, cap, dims = index.part_vecs.shape
    nlist_pad = -(-nlist // S) * S
    part_dtype = {"int8": jnp.int8, "bf16": jnp.bfloat16,
                  "int4": jnp.uint8, "binary": jnp.uint32}.get(
        index.dtype, jnp.float32)
    part_w = dims
    if index.dtype in ("int4", "binary"):
        from elasticsearch_tpu.quant import codec as quant_codec
        part_w = quant_codec.get(index.dtype).packed_width(dims)
    host_like = ShardedIVF(
        jax.ShapeDtypeStruct((nlist, dims), jnp.float32),
        jax.ShapeDtypeStruct((nlist,), jnp.float32),
        jax.ShapeDtypeStruct((nlist_pad, cap, part_w), part_dtype),
        jax.ShapeDtypeStruct((nlist_pad, cap), jnp.float32),
        jax.ShapeDtypeStruct((nlist_pad, cap), jnp.float32),
        jax.ShapeDtypeStruct((nlist_pad, cap), jnp.int32))
    # with dp > 1 the router can send an IVF dispatch to the full mesh
    # or any dp-group submesh — warm all of them (rule-driven specs key
    # to the executables the live pytree views dispatch with)
    meshes = [mesh]
    if mesh_lib.dp_size(mesh) > 1:
        meshes.extend(policy.dp_groups(mesh))
    entries = []
    for m in meshes:
        spec = layout.shape_specs(host_like, m)
        m_dp = mesh_lib.dp_size(m)
        for q in dispatch.WARMUP_QUERY_BUCKETS:
            if q % m_dp:
                continue   # the router never full-meshes this bucket
            qspec = jax.ShapeDtypeStruct(
                (q, dims), jnp.float32,
                sharding=mesh_lib.query_sharding(m))
            for kk in dispatch.WARMUP_K_BUCKETS:
                k_b = dispatch.bucket_k(min(kk, nprobe * cap),
                                        limit=nprobe * cap)
                entries.append(("mesh.ivf", (qspec, spec),
                                {"k": k_b, "nprobe": nprobe, "mesh": m,
                                 "metric": index.metric,
                                 "precision": "bf16"}))
    return entries
